// torchstore_tpu native data path.
//
// The reference's hot transfer loops live in native dependencies (Monarch's
// Rust RDMA engine, torch's C++ SHM, Gloo — SURVEY §2.3). This library is
// the TPU build's equivalent for the host-side data plane: multi-threaded
// memcpy for SHM/staging copies (the measured bottleneck of the pure-Python
// path), POSIX shared-memory helpers, and GIL-free file-descriptor bulk IO.
// Bound via ctypes (no pybind11 in this image).
//
// Build: make -C native   ->  native/libtsnative.so

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr size_t kMinPerThread = 4u << 20;  // 4 MiB per thread minimum

void copy_range(char* dst, const char* src, size_t n) {
  std::memcpy(dst, src, n);
}

}  // namespace

extern "C" {

// Multi-threaded memcpy. nthreads <= 0 -> auto (hardware_concurrency capped
// so we never oversubscribe for small copies).
void ts_parallel_memcpy(void* dst, const void* src, uint64_t n, int nthreads) {
  if (n == 0) return;
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  size_t want = nthreads > 0 ? static_cast<size_t>(nthreads)
                             : static_cast<size_t>(hw);
  size_t by_size = n / kMinPerThread;
  size_t threads = std::min(want, std::max<size_t>(1, by_size));
  threads = std::min<size_t>(threads, 16);
  if (threads <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads - 1);
  size_t chunk = n / threads;
  char* d = static_cast<char*>(dst);
  const char* s = static_cast<const char*>(src);
  for (size_t i = 0; i + 1 < threads; ++i) {
    pool.emplace_back(copy_range, d + i * chunk, s + i * chunk, chunk);
  }
  copy_range(d + (threads - 1) * chunk, s + (threads - 1) * chunk,
             n - (threads - 1) * chunk);
  for (auto& t : pool) t.join();
}

// Strided 2D copy: rows of row_bytes from src (pitch src_stride) to dst
// (pitch dst_stride), parallelized over rows. Covers the common
// "copy a row-block slice" landing pattern without a Python loop.
void ts_copy_2d(void* dst, uint64_t dst_stride, const void* src,
                uint64_t src_stride, uint64_t row_bytes, uint64_t rows,
                int nthreads) {
  if (rows == 0 || row_bytes == 0) return;
  uint64_t total = rows * row_bytes;
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  size_t want = nthreads > 0 ? static_cast<size_t>(nthreads)
                             : static_cast<size_t>(hw);
  size_t threads =
      std::min(want, std::max<uint64_t>(1, total / kMinPerThread));
  threads = std::min<size_t>(threads, 16);
  char* d = static_cast<char*>(dst);
  const char* s = static_cast<const char*>(src);
  auto worker = [=](uint64_t row_lo, uint64_t row_hi) {
    for (uint64_t r = row_lo; r < row_hi; ++r) {
      std::memcpy(d + r * dst_stride, s + r * src_stride, row_bytes);
    }
  };
  if (threads <= 1) {
    worker(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  uint64_t per = rows / threads;
  for (size_t i = 0; i + 1 < threads; ++i) {
    pool.emplace_back(worker, i * per, (i + 1) * per);
  }
  worker((threads - 1) * per, rows);
  for (auto& t : pool) t.join();
}

// POSIX SHM helpers (the ABI /dev/shm files share with Python's mmap path).
int ts_shm_create(const char* path, uint64_t size) {
  int fd = open(path, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    int err = -errno;
    close(fd);
    unlink(path);
    return err;
  }
  return fd;
}

int ts_shm_unlink(const char* path) {
  return unlink(path) == 0 ? 0 : -errno;
}

// Multi-threaded prefault of a writable mapping: touch one byte per page
// across nthreads so a freshly-created tmpfs segment's pages are allocated
// and zeroed BEFORE the hot copy path ever sees them (the cold-start cost a
// first weight sync otherwise pays one trap at a time). Writing 0 into
// untouched tmpfs pages is what allocates them (reads would map the shared
// zero page and still fault on the later write). nthreads <= 0 -> auto.
// Returns 0, or -errno from the advisory madvise (pages are still touched).
int ts_prefault(void* addr, uint64_t len, int nthreads) {
  if (len == 0) return 0;
  madvise(addr, len, MADV_WILLNEED);  // advisory; the touch below is the work
  constexpr uint64_t kPage = 4096;
  size_t threads;
  if (nthreads > 0) {
    // Explicit request (TORCHSTORE_TPU_PREWARM_THREADS): honor it.
    threads = static_cast<size_t>(nthreads);
  } else {
    // Auto: one thread per 16 MiB — page allocation is kernel-time bound,
    // so even tens-of-MB model shards benefit from a few threads.
    unsigned hw = std::thread::hardware_concurrency();
    if (hw == 0) hw = 4;
    threads = std::min<uint64_t>(
        hw, std::max<uint64_t>(1, len / (4 * kMinPerThread)));
  }
  threads = std::min<size_t>(threads, 16);
  threads = std::min<uint64_t>(threads, (len + kPage - 1) / kPage);
  volatile char* base = static_cast<volatile char*>(addr);
  auto worker = [=](uint64_t lo, uint64_t hi) {
    for (uint64_t off = lo; off < hi; off += kPage) base[off] = 0;
  };
  if (threads <= 1) {
    worker(0, len);
    return 0;
  }
  std::vector<std::thread> pool;
  uint64_t pages = (len + kPage - 1) / kPage;
  uint64_t per = (pages / threads) * kPage;
  for (size_t i = 0; i + 1 < threads; ++i) {
    pool.emplace_back(worker, i * per, (i + 1) * per);
  }
  worker((threads - 1) * per, len);
  for (auto& t : pool) t.join();
  return 0;
}

// Batched scatter memcpy: count independent (dst, src, len) copies in one
// GIL-free call, partitioned byte-balanced across threads. This is the
// one-sided warm get's data plane — hundreds of ~64 KB stamped reads per
// batch, where a per-pair Python np.copyto loop pays interpreter + GIL
// hand-off costs comparable to the memcpy itself. Pointers ride as uint64
// arrays (numpy-friendly ctypes ABI). Overlapping ranges are the caller's
// bug. nthreads <= 0 -> auto.
void ts_copy_batch(const uint64_t* dsts, const uint64_t* srcs,
                   const uint64_t* lens, uint64_t count, int nthreads) {
  if (count == 0) return;
  uint64_t total = 0;
  for (uint64_t i = 0; i < count; ++i) total += lens[i];
  if (total == 0) return;
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  size_t want = nthreads > 0 ? static_cast<size_t>(nthreads)
                             : static_cast<size_t>(hw);
  size_t threads =
      std::min(want, std::max<uint64_t>(1, total / kMinPerThread));
  threads = std::min<size_t>(threads, 16);
  threads = std::min<uint64_t>(threads, count);
  auto run = [=](uint64_t lo, uint64_t hi) {
    for (uint64_t i = lo; i < hi; ++i) {
      std::memcpy(reinterpret_cast<void*>(dsts[i]),
                  reinterpret_cast<const void*>(srcs[i]), lens[i]);
    }
  };
  if (threads <= 1) {
    run(0, count);
    return;
  }
  // Byte-balanced split points: pair i goes to the thread whose byte range
  // contains its cumulative start (pairs stay whole — intra-pair splitting
  // is ts_parallel_memcpy's job, and callers chunk huge pairs first).
  std::vector<uint64_t> bounds(threads + 1, count);
  bounds[0] = 0;
  uint64_t per = total / threads, acc = 0, t = 1;
  for (uint64_t i = 0; i < count && t < threads; ++i) {
    acc += lens[i];
    if (acc >= per * t) bounds[t++] = i + 1;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads - 1);
  for (size_t i = 0; i + 1 < threads; ++i) {
    pool.emplace_back(run, bounds[i], bounds[i + 1]);
  }
  run(bounds[threads - 1], bounds[threads]);
  for (auto& t2 : pool) t2.join();
}

// Blocking full-length fd IO, releasing the GIL on the Python side (called
// via ctypes from executor threads). Returns bytes moved or -errno.
int64_t ts_write_fd(int fd, const void* buf, uint64_t n) {
  const char* p = static_cast<const char*>(buf);
  uint64_t done = 0;
  while (done < n) {
    ssize_t w = ::send(fd, p + done, n - done, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    done += static_cast<uint64_t>(w);
  }
  return static_cast<int64_t>(done);
}

int64_t ts_read_fd(int fd, void* buf, uint64_t n) {
  char* p = static_cast<char*>(buf);
  uint64_t done = 0;
  while (done < n) {
    ssize_t r = ::recv(fd, p + done, n - done, 0);
    if (r == 0) return static_cast<int64_t>(done);  // peer closed
    if (r < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    done += static_cast<uint64_t>(r);
  }
  return static_cast<int64_t>(done);
}

// v2: ts_prefault gained the (addr, len, nthreads) multi-threaded signature
// (the provisioning subsystem's prewarm path); v1 binaries lack it.
// v3: ts_copy_batch (one-sided warm-get scatter memcpy); v2 binaries fall
// back to the per-pair Python landing loop.
uint32_t ts_version() { return 3; }

}  // extern "C"
