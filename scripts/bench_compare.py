#!/usr/bin/env python
"""bench_compare — machine-read the BENCH_r* trajectory and fail on regressions.

The repo accumulates one BENCH_*.json per round (r01..r05 so far) and until
now nothing machine-read them: a regression was only caught if a human
compared JSON blobs by eye. This tool diffs two or more headline records —
the LAST file given is the candidate, the earlier ones the baseline — with
per-metric, direction-aware regression thresholds, and exits non-zero when
the candidate regresses.

Accepted file shapes (both live in this repo):

- the raw ``bench.py`` stdout record (``{"metric", "value", "unit",
  "sections", ...}``);
- the driver wrapper (``{"n", "cmd", "rc", "tail", "parsed"}``) whose
  ``parsed`` carries the flat headline and whose ``tail`` may embed the
  full JSON line (we recover it when present; a crashed round with
  ``parsed: null`` contributes nothing and is reported as such).

Usage:
    python scripts/bench_compare.py BENCH_r04.json BENCH_r05.json
    python scripts/bench_compare.py BENCH_r0*.json --baseline median
    python scripts/bench_compare.py old.json new.json --json --scale 1.5

``--baseline prev`` (default) compares against the newest baseline file
that carries each metric; ``best``/``median`` aggregate across all
baseline files (bench hosts are shared and noisy — median is the fairest
cross-round bar). ``--scale`` multiplies every threshold (loosen on known-
noisy hosts). Exit codes: 0 ok, 1 regression(s), 2 usage/parse error.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
from typing import Optional

# metric -> (direction, allowed regression, unit). Direction "higher"
# means bigger is better (a drop beyond the budget regresses); "lower"
# means smaller is better. Unit "rel" budgets a FRACTION of the baseline;
# "abs" budgets in the metric's own units — required for metrics that sit
# near (or legitimately below) zero, where a fractional comparison
# inverts: ledger_overhead_pct's baseline can be slightly negative under
# host noise, and (cand - base) / base with base < 0 would wave a real
# regression through while flagging an improvement. Thresholds are
# deliberately generous: the bench box is shared and host weather moves
# everything 2x between rounds — this gate catches collapses, not jitter.
THRESHOLDS: dict[str, tuple[str, float, str]] = {
    "value": ("higher", 0.30, "rel"),
    "vs_baseline": ("higher", 0.30, "rel"),
    "many_keys_gbps": ("higher", 0.40, "rel"),
    "per_key_put_us": ("lower", 0.60, "rel"),
    "per_key_get_us": ("lower", 0.60, "rel"),
    "many_keys_get_gbps": ("higher", 0.40, "rel"),
    "get_memcpy_ratio": ("lower", 0.60, "rel"),
    "p50_put_ms": ("lower", 0.75, "rel"),
    "p50_get_ms": ("lower", 0.75, "rel"),
    "p50_get_1kb_ms": ("lower", 0.75, "rel"),
    "cold_vs_steady": ("higher", 0.50, "rel"),
    "cold_prewarmed_vs_steady": ("higher", 0.50, "rel"),
    "overlap_ratio": ("higher", 0.25, "rel"),
    # Absolute budgets: ms around zero (decode can beat the seal, so the
    # value is signed) and percentage points for the telemetry overhead.
    "first_token_after_publish_ms": ("lower", 200.0, "abs"),
    "heal_s": ("lower", 1.0, "rel"),
    "failover_get_s": ("lower", 1.0, "rel"),
    "ledger_overhead_pct": ("lower", 2.0, "abs"),
    # History sampler + trend detectors (ISSUE 17): budget <= 1% on the
    # warm get leg even at the bench's 20x production sweep rate.
    "history_overhead_pct": ("lower", 1.0, "abs"),
    # Broadcast fan-out (ISSUE 11). The egress ratio is deterministic at a
    # given K (1/K when every layer rides the tree), so even a small
    # absolute drift means relay hops leaked reads back to the origin; the
    # deep-hop overlap is timing-derived and budgeted like overlap_ratio.
    "fanout_egress_ratio": ("lower", 0.10, "abs"),
    "fanout_overlap_ratio": ("higher", 0.35, "rel"),
    # Tiered capacity (ISSUE 12). The warm leased-version get after the
    # spill writer ran must stay in the one-sided per-key-us regime
    # (budgeted like per_key_get_us); fault-in is disk I/O + a landing
    # copy, budgeted loosely against host weather; the spilled ratio is
    # structural at a fixed working-set/budget shape, so a drop means the
    # watermark policy stopped demoting.
    "warm_get_after_spill_us": ("lower", 0.60, "rel"),
    "fault_in_p50_ms": ("lower", 1.00, "rel"),
    "spilled_bytes_ratio": ("higher", 0.30, "rel"),
    # Quantized + delta wire tier (ISSUE 13). The speedups are measured at
    # a fixed emulated DCN bandwidth, so they are near-structural (wire
    # bytes dominate by construction) — a drop means the codec got slower
    # or the wire tier leaked full-precision bytes; the delta leg's wire
    # compression is deterministic at fixed churn; the dequant error is
    # analytic (bounded by one keyframe step) and budgeted absolutely.
    "delta_speedup_int8_block": ("higher", 0.25, "rel"),
    "delta_speedup_delta": ("higher", 0.25, "rel"),
    "delta_wire_compression_delta": ("higher", 0.25, "rel"),
    "delta_max_abs_err": ("lower", 0.10, "abs"),
    # Scale-out metadata plane (ISSUE 14). The 1 -> 4 shard throughput
    # factor is near-structural at fixed driver load (acceptance >= 2.5x;
    # measured 2.6-3.0x on this 24-core host, where the sharded leg is
    # client-CPU-bound — the shards themselves have headroom) — a drop
    # means shard routing started
    # serializing somewhere (a new coordinator hop on the warm path, a
    # fan-out regression); the sharded leg's absolute rate is host-
    # weather-budgeted like the other throughput legs.
    "metadata_scale_x": ("higher", 0.30, "rel"),
    "metadata_ops_per_s_sharded": ("higher", 0.40, "rel"),
    # Fleet-scale load harness (ISSUE 15). Sustained ops/s is arrival-
    # paced (open-loop clients), so big swings mean drivers died or the
    # fleet stopped keeping up, not host weather; the p99 gate is already
    # asserted inside the section, so the trajectory budget only needs to
    # catch creep; the under-load telemetry overhead carries its own
    # measured noise floor and is budgeted absolutely like
    # ledger_overhead_pct, a bit wider for the storm.
    "fleet_ops_per_s": ("higher", 0.40, "rel"),
    "fleet_get_p99_ms": ("lower", 1.00, "rel"),
    "fleet_ledger_overhead_pct": ("lower", 4.0, "abs"),
    # Traffic-aware placement (ISSUE 16). The recovery ratio divides two
    # ops/s figures from the SAME run (skewed-with-engine over uniform
    # baseline), so host weather largely cancels — a real drop means the
    # engine stopped recovering the skew; the quiet-tenant p99 ratio is
    # tail-over-tail and budgeted loosely; migrated bytes are workload-
    # shaped, so the budget only catches the engine going dark (bytes
    # collapsing toward zero), not round-to-round variation.
    "rebalance_recovery_ratio": ("higher", 0.30, "rel"),
    "tenant_isolation_p99_ratio": ("lower", 1.00, "rel"),
    "migration_bytes": ("higher", 0.90, "rel"),
    # Elastic fleet autoscaling + cold tier (ISSUE 18, --autoscale runs
    # only). The volume-seconds ratio divides two integrals over the SAME
    # diurnal profile, so host weather cancels — the section already
    # asserts the <= 0.60 elasticity gate, and the trajectory budget
    # (absolute: the ratio lives in [0, 1]) only catches the autoscaler
    # going timid (ratio creeping toward 1.0 = static provisioning); the
    # autoscaled p99 is budgeted like the other tail legs; cold restore
    # is blob I/O + re-landing, budgeted loosely against host weather.
    "autoscale_volume_seconds_ratio": ("lower", 0.15, "abs"),
    "autoscale_get_p99_ms": ("lower", 1.00, "rel"),
    "cold_restore_s": ("lower", 1.00, "rel"),
    # Cross-host one-sided tier (ISSUE 20, --cross-host runs only). The
    # push speedup divides two latencies from the SAME paced run, so host
    # weather largely cancels — a real drop means reads stopped serving
    # from the push-staged arena (back to paying the wire at read time);
    # the metadata egress ratio is structural at fixed K (1/K when every
    # image rides the relay tree), so even a small absolute drift means
    # subscribers leaked feed reads back to the index host.
    "push_speedup": ("higher", 0.40, "rel"),
    "push_first_layer_ms": ("lower", 1.00, "rel"),
    "meta_egress_ratio": ("lower", 0.10, "abs"),
}


def extract_metrics(doc: dict) -> dict[str, float]:
    """Flatten one record (raw bench output or driver wrapper) into
    {metric: float}. Non-numeric / missing values are skipped."""
    flat: dict[str, object] = {}
    if "parsed" in doc or "tail" in doc:
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            flat.update(parsed)
        # The wrapper's tail often carries the full headline JSON line —
        # recover it so wrapper files compare as richly as raw ones.
        tail = doc.get("tail")
        if isinstance(tail, str):
            for line in tail.splitlines():
                line = line.strip()
                if line.startswith("{") and '"metric"' in line:
                    try:
                        flat.update(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    else:
        flat.update(doc)
    if isinstance(flat.get("ledger_overhead"), dict):
        pct = flat["ledger_overhead"].get("overhead_pct")
        if pct is not None:
            flat["ledger_overhead_pct"] = pct
    if isinstance(flat.get("history_overhead"), dict):
        pct = flat["history_overhead"].get("overhead_pct")
        if pct is not None:
            flat["history_overhead_pct"] = pct
    out: dict[str, float] = {}
    for name in THRESHOLDS:
        value = flat.get(name)
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out[name] = float(value)
    return out


def _regression(
    base: float, cand: float, direction: str, unit: str
) -> Optional[float]:
    """How far ``cand`` regressed past ``base`` (same units as the
    threshold: a baseline fraction for "rel", metric units for "abs");
    negative = improved. None when a relative comparison is meaningless
    (non-positive baseline — dividing by it inverts the verdict)."""
    worse_by = (base - cand) if direction == "higher" else (cand - base)
    if unit == "abs":
        return worse_by
    if base <= 0:
        return None
    return worse_by / base


def load(path: str) -> dict[str, float]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return extract_metrics(doc)


def baseline_value(
    values: list[float], mode: str, direction: str
) -> float:
    if mode == "prev":
        return values[-1]
    if mode == "median":
        return statistics.median(values)
    # best: the strongest bar the trajectory ever set.
    return max(values) if direction == "higher" else min(values)


def compare(
    baselines: list[dict[str, float]],
    candidate: dict[str, float],
    mode: str = "prev",
    scale: float = 1.0,
) -> list[dict]:
    """Per-metric comparison rows; ``row["regressed"]`` marks failures."""
    rows: list[dict] = []
    for name, (direction, threshold, unit) in THRESHOLDS.items():
        cand = candidate.get(name)
        history = [b[name] for b in baselines if name in b]
        if cand is None or not history:
            continue
        base = baseline_value(history, mode, direction)
        allowed = threshold * scale
        delta = _regression(base, cand, direction, unit)
        rows.append(
            {
                "metric": name,
                "direction": direction,
                "unit": unit,
                "baseline": base,
                "candidate": cand,
                "regression": None if delta is None else round(delta, 4),
                "allowed": allowed,
                "regressed": delta is not None and delta > allowed,
            }
        )
    return rows


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "files", nargs="+", help="2+ BENCH json files, oldest..newest"
    )
    parser.add_argument(
        "--baseline",
        choices=("prev", "best", "median"),
        default="prev",
        help="how baseline files aggregate (default: the newest one)",
    )
    parser.add_argument(
        "--scale",
        type=float,
        default=1.0,
        help="multiply every regression threshold (noisy hosts)",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    if len(args.files) < 2:
        print("bench_compare: need at least two files", file=sys.stderr)
        return 2
    try:
        records = [(path, load(path)) for path in args.files]
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"bench_compare: {exc}", file=sys.stderr)
        return 2
    *base_records, (cand_path, candidate) = records
    empty = [path for path, rec in base_records if not rec]
    if not candidate:
        print(
            f"bench_compare: {cand_path} carries no headline metrics "
            "(crashed round?)",
            file=sys.stderr,
        )
        return 2
    rows = compare(
        [rec for _, rec in base_records],
        candidate,
        mode=args.baseline,
        scale=args.scale,
    )
    regressed = [row for row in rows if row["regressed"]]
    if args.json:
        print(
            json.dumps(
                {
                    "candidate": cand_path,
                    "baselines": [p for p, _ in base_records],
                    "mode": args.baseline,
                    "rows": rows,
                    "regressed": [row["metric"] for row in regressed],
                    "empty_baselines": empty,
                }
            )
        )
    else:
        for path in empty:
            print(f"# {path}: no headline metrics (skipped)")
        width = max((len(r["metric"]) for r in rows), default=10)
        for row in rows:
            mark = "REGRESSED" if row["regressed"] else "ok"
            arrow = "^" if row["direction"] == "higher" else "v"
            if row["regression"] is None:
                move = "n/a (non-positive baseline)"
            elif row["unit"] == "abs":
                move = (
                    f"{row['regression']:+.4g} vs {row['allowed']:.4g} "
                    "abs budget"
                )
            else:
                move = f"{row['regression']:+.1%} vs {row['allowed']:.0%} budget"
            print(
                f"{row['metric']:<{width}} {arrow} "
                f"{row['baseline']:>10.4g} -> {row['candidate']:>10.4g} "
                f"({move})  {mark}"
            )
        print(
            f"bench_compare: {len(rows)} metric(s) compared, "
            f"{len(regressed)} regression(s) "
            f"[{cand_path} vs {args.baseline} of "
            f"{len(base_records)} baseline(s)]"
        )
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
