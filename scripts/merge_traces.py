#!/usr/bin/env python
"""Merge per-process torchstore Chrome-trace files into one timeline.

Every torchstore process writes its own trace file when
``TORCHSTORE_TPU_TRACE=/path/trace.json`` is set (the base path, claimed by
the first process to flush, plus ``trace.<pid>.json`` siblings). This tool
stitches them into ONE Perfetto-loadable file with labeled process tracks;
the cross-process ``trace_id`` args (propagated over the actor RPC layer)
let you follow a single put from the client span through the controller
notify to every volume write.

Usage:
    python scripts/merge_traces.py /tmp/run/trace.json
    python scripts/merge_traces.py /tmp/run/trace.json -o merged.json
    python scripts/merge_traces.py a.json b.json c.json -o merged.json

With one argument the base path's whole sibling set is discovered; with
several, exactly those files are merged. In-process equivalent:
``ts.collect_trace()``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from torchstore_tpu.observability.tracing import merge_traces, trace_files


def main() -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process torchstore trace files"
    )
    ap.add_argument(
        "paths",
        nargs="+",
        help="one TORCHSTORE_TPU_TRACE base path (siblings auto-discovered) "
        "or an explicit list of trace files",
    )
    ap.add_argument(
        "-o",
        "--out",
        default=None,
        help="output path (default: <root>.merged<ext> of the first input)",
    )
    args = ap.parse_args()

    if len(args.paths) == 1:
        files = trace_files(args.paths[0])
        if not files:
            print(f"no trace files found for base {args.paths[0]!r}", file=sys.stderr)
            return 1
    else:
        files = args.paths
        missing = [p for p in files if not os.path.exists(p)]
        if missing:
            print(f"missing trace files: {missing}", file=sys.stderr)
            return 1
    out = args.out
    if out is None:
        root, ext = os.path.splitext(args.paths[0])
        out = f"{root}.merged{ext or '.json'}"
    result = merge_traces(files, out)
    print(
        f"merged {result['events']} events from {len(result['files'])} "
        f"file(s) -> {result['path']} "
        f"({len(result['trace_ids'])} distinct trace ids)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
