#!/bin/bash
# Single CI/pre-PR entry point: everything fast that must be green before a
# change ships, in the order that fails fastest.
#
#   scripts/check.sh            # the full fast gate
#   scripts/check.sh --quick    # static analysis only (skip pytest)
#
# Stages:
#   1. tslint --fail-on-new     repo-specific static analysis (21 rules:
#                               17 syntactic + the 4 flow-aware CFG rules
#                               bracket/epoch/await-atomicity/decision-flow;
#                               incl. env-registry + metric-discipline docs
#                               drift — regen with --regen-env-docs /
#                               --regen-metric-docs after editing knobs or
#                               instruments). Also emits tslint.sarif for
#                               CI code-scanning upload.
#   2. metric namespace shim    scripts/check_metric_names.py (historical
#                               entry point; same checker as tslint)
#   3. bench + trajectory smoke pytest over test_bench_smoke.py (the REAL
#                               bench.py code path at KB scale, incl. the
#                               ledger_overhead telemetry-cost section,
#                               the history_overhead sampler+detector
#                               cost section (<= 1% budget at scale),
#                               the relay fanout section's O(1)-egress
#                               bound, the tiered-capacity section's
#                               spill/fault-in/warm-leased-get gates,
#                               the delta_sync quant/delta wire-tier
#                               section's compression + error bounds,
#                               the metadata_scale section's 1-vs-N-shard
#                               controller throughput scaling, and the
#                               fleet_scale loadgen section's p99-vs-SLO
#                               gate + under-load telemetry budget +
#                               induced-violation stage attribution, and
#                               the placement section's skewed-loadgen
#                               control loop: plan non-empty on skew,
#                               decisions applied, zero failed gets
#                               mid-migration, and the autoscale section's
#                               diurnal elasticity loop: fleet 1 -> N ->
#                               back, volume-seconds vs a fixed fleet,
#                               blob checkpoint -> cold restore, and the
#                               cross_host section's one-sided tier:
#                               push-vs-doorbell first-layer speedup,
#                               zero warm metadata RPCs against the
#                               local mirror, relay-tree egress bound)
#                               and
#                               test_bench_compare.py (the BENCH_r*
#                               regression gate itself)
#
# The full tier-1 suite stays `python -m pytest tests/ -q -m 'not slow'`.
set -u
cd "$(dirname "$0")/.."
rc=0

run() {
    echo "== $*"
    "$@" || rc=$?
}

run python scripts/tslint.py --fail-on-new --sarif tslint.sarif
run python scripts/check_metric_names.py
if [ "${1:-}" != "--quick" ]; then
    run env JAX_PLATFORMS=cpu python -m pytest \
        tests/test_bench_smoke.py tests/test_bench_compare.py \
        -q -p no:cacheprovider
fi

if [ "$rc" -ne 0 ]; then
    echo "check.sh: FAILED (first failing stage's exit code: $rc)"
else
    echo "check.sh: OK"
fi
exit "$rc"
