#!/usr/bin/env python
"""ts-top: live terminal console for a torchstore_tpu fleet.

Renders, once per refresh, from the fleet's retained time-series history
(``ts.history()``) and live scoreboards:

- ops/s and get-p99 sparklines (last ~2 minutes, 1s buckets),
- per-volume heat: open landing brackets, resident doorbell plans,
  rolling window ops — with trend markers when a sustained/ramp detector
  is firing on that volume,
- the elastic fleet pane: fleet-size sparkline (``ts_fleet_volumes`` /
  ``ts_fleet_draining`` gauges), tier residency (memory / disk-spill /
  blob bytes summed across volumes), and the autoscaler's dry-run plan
  (``ts.autoscale_plan()``),
- the SLO scoreboard with trend arrows (^ ramping, ~ drifting, ! sustained
  over threshold, = quiet),
- the control-plane decision tail (planned actions + recent decision /
  fault / slo flight events).

No dependencies beyond the repo: plain ANSI clear-and-redraw, stdlib only.

Two ways to attach:

- ``--store NAME`` (default ``torchstore_tpu``): join the fleet as a
  client and read ``ts.history()`` / ``ts.slo_report()`` /
  ``ts.control_plan()`` / ``ts.flight_record()``.
- ``--url http://host:port``: poll one process's HTTP exporter
  (``/history.json`` + ``/slo.json``; TORCHSTORE_TPU_METRICS_PORT) —
  no store membership needed, single-process view.

``--once`` renders a single frame and exits (non-interactive capture, CI
smoke); otherwise refreshes every ``--interval`` seconds until Ctrl-C.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
import urllib.request

SPARK_CHARS = " ▁▂▃▄▅▆▇█"
CSI_CLEAR = "\x1b[2J\x1b[H"

TREND_MARKS = {"sustained": "!", "ramp": "^", "drift": "~"}


# --------------------------------------------------------------------------
# pure rendering (unit-testable: data dict in, string out)
# --------------------------------------------------------------------------


def spark(values: list[float], width: int = 60) -> str:
    """A unicode sparkline of the last ``width`` values, min-max scaled."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return "(no data)"
    lo, hi = min(vals), max(vals)
    span = hi - lo
    out = []
    for v in vals:
        frac = (v - lo) / span if span > 0 else 0.5
        out.append(SPARK_CHARS[1 + int(frac * (len(SPARK_CHARS) - 2))])
    return "".join(out)


def fleet_rate_series(history_doc: dict, name: str) -> list[list]:
    """Fleet ops/s per 1s bucket from a ``ts.history()`` doc: exact
    cumulative-counter diffs per process/label series, summed per bucket."""
    from torchstore_tpu.observability import history as obs_history

    merged: dict[float, float] = {}
    for proc_doc in (history_doc.get("processes") or {}).values():
        for sid, entry in (proc_doc.get("series") or {}).items():
            if sid == name or sid.startswith(name + "{"):
                for ts, rate in obs_history.counter_rate_points(
                    entry["points"]
                ):
                    merged[ts] = merged.get(ts, 0.0) + rate
    return [[ts, merged[ts]] for ts in sorted(merged)]


def fleet_gauge_series(history_doc: dict, sid_exact: str) -> list[list]:
    """Worst per-bucket value of one gauge series across processes."""
    from torchstore_tpu.observability import history as obs_history

    rows = [
        entry["points"]
        for proc_doc in (history_doc.get("processes") or {}).values()
        for sid, entry in (proc_doc.get("series") or {}).items()
        if sid == sid_exact
    ]
    return [[r[0], r[2]] for r in obs_history.merge_points(rows, how="max")]


def fleet_gauge_sum_series(history_doc: dict, name: str) -> list[list]:
    """Per-bucket sum of one gauge's closing values across processes —
    fleet totals for per-volume residency gauges (``ts_blob_bytes``,
    ``ts_tier_resident_bytes``, ...)."""
    from torchstore_tpu.observability import history as obs_history

    rows = [
        entry["points"]
        for proc_doc in (history_doc.get("processes") or {}).values()
        for sid, entry in (proc_doc.get("series") or {}).items()
        if sid == name or sid.startswith(name + "{")
    ]
    return [[r[0], r[3]] for r in obs_history.merge_points(rows, how="sum")]


def fmt_bytes(n: float) -> str:
    """Human-scaled byte count (``1.5M``); exact below 1 KiB."""
    n = float(n)
    for unit in ("", "K", "M", "G", "T"):
        if abs(n) < 1024.0 or unit == "T":
            return f"{n:.0f}{unit}" if unit == "" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}T"


def trend_arrow(trends: dict) -> str:
    """One status mark summarizing a process's active detectors."""
    marks = [
        TREND_MARKS.get(result.get("kind"), "?")
        for result in (trends or {}).values()
        if result.get("active")
    ]
    return "".join(sorted(set(marks))) or "="


def render_frame(data: dict, width: int = 72) -> str:
    """One full console frame from collected fleet data (see
    ``collect_store`` / ``collect_url`` for the dict shape — every key is
    optional; absent sections render as absent, never crash)."""
    lines: list[str] = []
    now = data.get("generated_ts") or time.time()
    source = data.get("source", "?")
    lines.append(
        f"ts-top — {source} — "
        f"{time.strftime('%H:%M:%S', time.localtime(now))}"
    )
    lines.append("─" * width)

    history_doc = data.get("history") or {}
    ops = fleet_rate_series(history_doc, "ts_client_ops_total")
    p99 = fleet_gauge_series(history_doc, 'ts_op_p99_seconds{op="get"}')
    ops_now = ops[-1][1] if ops else 0.0
    p99_now_ms = p99[-1][1] * 1e3 if p99 else 0.0
    lines.append(
        f"  ops/s   {spark([v for _t, v in ops])}  {ops_now:8.1f}"
    )
    lines.append(
        f"  get p99 {spark([v for _t, v in p99])}  {p99_now_ms:6.2f}ms"
    )

    slo = data.get("slo") or {}
    trends = slo.get("trends") or {}
    lines.append("")
    lines.append(f"SLOs [{trend_arrow(trends)}]")
    for name, row in sorted((slo.get("slos") or {}).items()):
        mark = "VIOLATED" if row.get("violated") else "ok"
        current = row.get("current")
        cur = f"{current:g}" if current is not None else "-"
        lines.append(
            f"  {name:<24} {cur:>10} / {row.get('threshold'):g}"
            f"  [{mark}]  x{row.get('violations', 0)}"
        )
    for name, result in sorted(trends.items()):
        if result.get("active"):
            detail = (
                f"{result.get('duration_s', 0):.0f}s"
                if result.get("kind") == "sustained"
                else f"z={result.get('z', 0):.1f}"
                if result.get("kind") == "drift"
                else f"slope={result.get('slope', 0):.2f}/s"
            )
            lines.append(
                f"  trend {TREND_MARKS.get(result.get('kind'), '?')} "
                f"{name}: {result.get('series')} ({detail})"
            )

    volumes = (data.get("overload") or {}).get("volumes") or {}
    if volumes:
        lines.append("")
        lines.append("volumes")
        max_ops = max(
            (int(v.get("window_ops") or 0) for v in volumes.values()),
            default=0,
        )
        for vid, v in sorted(volumes.items()):
            w_ops = int(v.get("window_ops") or 0)
            bar_w = int(20 * w_ops / max_ops) if max_ops else 0
            lines.append(
                f"  {vid:<14} land={int(v.get('landing_inflight') or 0):<4}"
                f" plans={int(v.get('doorbell_plans') or 0):<4}"
                f" ops={w_ops:<8} {'#' * bar_w:<20}"
                f" [{trend_arrow(v.get('trends'))}]"
            )

    # Elastic fleet + cold tier: size history from the engine's gauges,
    # residency totals summed across volume processes, and the
    # autoscaler's dry-run view of what it would do next.
    size_hist = fleet_gauge_series(history_doc, "ts_fleet_volumes")
    autoscale = data.get("autoscale") or {}
    afleet = autoscale.get("fleet") or {}
    if size_hist or afleet:
        lines.append("")
        lines.append("fleet")
        draining_hist = fleet_gauge_series(history_doc, "ts_fleet_draining")
        size_now = afleet.get(
            "volumes", int(size_hist[-1][1]) if size_hist else 0
        )
        draining_now = len(afleet.get("draining") or ()) or (
            int(draining_hist[-1][1]) if draining_hist else 0
        )
        lines.append(
            f"  size    {spark([v for _t, v in size_hist])}  "
            f"{size_now} vol ({draining_now} draining, "
            f"idle {afleet.get('idle_rounds', 0)} round(s))"
        )
        mem = fleet_gauge_sum_series(history_doc, "ts_tier_resident_bytes")
        spill = fleet_gauge_sum_series(history_doc, "ts_tier_spilled_bytes")
        blob = fleet_gauge_sum_series(history_doc, "ts_blob_bytes")
        if mem or spill or blob:
            backlog = sum((afleet.get("spilled_keys") or {}).values())
            lines.append(
                f"  tier    mem {fmt_bytes(mem[-1][1] if mem else 0)}"
                f" | spill {fmt_bytes(spill[-1][1] if spill else 0)}"
                f" | blob {fmt_bytes(blob[-1][1] if blob else 0)}"
                + (f" ({backlog} key(s) blob-eligible)" if backlog else "")
            )
        for action in (autoscale.get("actions") or [])[-4:]:
            lines.append(
                f"  plan {action.get('kind')} {action.get('subject')}: "
                f"{action.get('reason', '')[:48]}"
            )

    plan = data.get("plan") or {}
    actions = plan.get("actions") or []
    sustained = (plan.get("snapshot") or {}).get("sustained_overload") or {}
    if actions or sustained:
        lines.append("")
        lines.append("control plane")
        for vid, dets in sorted(sustained.items()):
            lines.append(f"  sustained_overload {vid}: {', '.join(dets)}")
        for action in actions[-6:]:
            lines.append(
                f"  plan {action.get('kind')} {action.get('subject')}: "
                f"{action.get('reason', '')[:48]}"
            )

    events = data.get("events") or []
    if events:
        lines.append("")
        lines.append("recent decisions / faults")
        for event in events[-6:]:
            ts_s = time.strftime(
                "%H:%M:%S", time.localtime(event.get("ts") or 0)
            )
            lines.append(
                f"  {ts_s} [{event.get('kind')}] {event.get('name')} "
                f"({event.get('process', '?')})"
            )

    errors = (data.get("history") or {}).get("errors") or {}
    if errors:
        lines.append("")
        lines.append(
            "unreachable: " + ", ".join(sorted(errors)) + ""
        )
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------
# collectors
# --------------------------------------------------------------------------


async def collect_store(store_name: str) -> dict:
    """One refresh's data via store membership (fleet view)."""
    import torchstore_tpu as ts

    history_doc = await ts.history(
        series=(
            "ts_client_ops_total*",
            "ts_op_p99_seconds*",
            "ts_landing_inflight*",
            "ts_fleet_volumes",
            "ts_fleet_draining",
            "ts_tier_resident_bytes*",
            "ts_tier_spilled_bytes*",
            "ts_blob_bytes*",
        ),
        since=120.0,
        store_name=store_name,
    )
    slo = await ts.slo_report(store_name=store_name)
    plan = await ts.control_plan(store_name=store_name)
    autoscale = await ts.autoscale_plan(store_name=store_name)
    record = await ts.flight_record(store_name=store_name)
    events = [
        e
        for e in record.get("events") or []
        if e.get("kind") in ("decision", "fault", "slo", "health")
    ]
    return {
        "source": f"store:{store_name}",
        "generated_ts": time.time(),
        "history": history_doc,
        "slo": slo,
        "overload": slo.get("overload") or {},
        "plan": plan,
        "autoscale": autoscale,
        "events": events,
    }


def collect_url(url: str, timeout: float = 5.0) -> dict:
    """One refresh's data from a single process's HTTP exporter."""
    base = url.rstrip("/")

    def fetch(path: str) -> dict:
        with urllib.request.urlopen(base + path, timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    history_local = fetch(
        "/history.json?series=ts_client_ops_total*,ts_op_p99_seconds*,"
        "ts_landing_inflight*,ts_fleet_volumes,ts_fleet_draining,"
        "ts_tier_resident_bytes*,ts_tier_spilled_bytes*,ts_blob_bytes*"
        "&since=120"
    )
    try:
        slo = fetch("/slo.json")
    except Exception:  # noqa: BLE001 - older exporters: history still renders
        slo = {}
    return {
        "source": url,
        "generated_ts": time.time(),
        # Same shape as ts.history() so the renderer doesn't care which
        # attach mode produced the frame.
        "history": {"processes": {"local": history_local}, "errors": {}},
        "slo": slo,
    }


async def main() -> int:
    parser = argparse.ArgumentParser(
        description="live terminal console for a torchstore_tpu fleet"
    )
    parser.add_argument("--store", default=None, help="store name to join")
    parser.add_argument(
        "--url", default=None, help="poll an HTTP exporter instead"
    )
    parser.add_argument(
        "--interval", type=float, default=2.0, help="refresh seconds"
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (no screen clear)",
    )
    args = parser.parse_args()
    if args.url and args.store:
        parser.error("--store and --url are mutually exclusive")
    store_name = args.store or "torchstore_tpu"

    while True:
        if args.url:
            data = collect_url(args.url)
        else:
            data = await collect_store(store_name)
        frame = render_frame(data)
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write(CSI_CLEAR + frame)
        sys.stdout.flush()
        await asyncio.sleep(max(0.2, args.interval))


if __name__ == "__main__":
    try:
        sys.exit(asyncio.run(main()))
    except KeyboardInterrupt:
        sys.exit(0)
