#!/usr/bin/env python
"""Static lint for the metric namespace — THIN SHIM.

The implementation moved into the repo's static-analysis suite:
``torchstore_tpu/analysis/checkers/metric_discipline.py`` (which also adds
ts_-prefix, label-cardinality, and span-name rules — run
``python scripts/tslint.py`` for the full set). This shim keeps the
historical entry point and its ``collect_sites(root)`` / ``check(root,
sites=None)`` API working for tests/test_metric_lint.py and any external
callers.

Run standalone (``python scripts/check_metric_names.py``) or through the
tier-1 test (tests/test_metric_lint.py). Exit 0 clean, 1 on findings.
"""

from __future__ import annotations

import os
import sys
import types

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

if "torchstore_tpu" not in sys.modules:
    # Preserve the old script's stdlib-only contract: load the analysis
    # subpackage without executing torchstore_tpu/__init__.py (the full
    # store runtime + numpy).
    _pkg = types.ModuleType("torchstore_tpu")
    _pkg.__path__ = [os.path.join(_REPO_ROOT, "torchstore_tpu")]
    sys.modules["torchstore_tpu"] = _pkg

from torchstore_tpu.analysis.checkers import metric_discipline as _impl  # noqa: E402

NAME_RE = _impl.NAME_RE
INSTRUMENT_CALLS = _impl.INSTRUMENT_CALLS


def collect_sites(root: str):
    """Every (file, line, metric_name, kind) instrument call site with a
    string-literal first argument under the scanned tree."""
    return _impl.collect_sites(root)


def check(root: str, sites=None) -> list[str]:
    """All namespace violations in the tree (empty list = clean). Pass
    pre-collected ``sites`` to avoid re-walking the tree."""
    return _impl.check_names(root, sites)


def main() -> int:
    return _impl.main()


if __name__ == "__main__":
    sys.exit(main())
