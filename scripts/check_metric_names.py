#!/usr/bin/env python
"""Static lint for the metric namespace: names can't silently fork.

AST-walks every ``counter("name", ...)`` / ``gauge(...)`` / ``histogram(...)``
call site (module-level functions AND registry methods) across the package
and benches, then fails on:

- **kind conflicts** — the same metric name registered as two different
  instrument kinds anywhere in the tree. The runtime raises on this too,
  but only when both call sites execute in ONE process; two processes
  registering ``ts_foo`` as a counter here and a gauge there would each run
  fine and corrupt the merged fleet document (observability/aggregate.py
  drops the conflicting side and reports it — this lint keeps it from ever
  landing).
- **non-snake-case names** — anything not matching ``[a-z][a-z0-9_]*``
  breaks Prometheus exposition and grep-ability.

Run standalone (``python scripts/check_metric_names.py``) or through the
tier-1 test (tests/test_metric_lint.py). Exit 0 clean, 1 on findings.
"""

from __future__ import annotations

import ast
import os
import re
import sys

NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
INSTRUMENT_CALLS = {"counter", "gauge", "histogram"}

# Directories scanned relative to the repo root. Tests are deliberately
# excluded: they register throwaway names (and one intentionally conflicting
# pair) on PRIVATE registries to test the runtime guard itself.
SCAN_DIRS = ("torchstore_tpu", "benchmarks", "scripts")
SCAN_FILES = ("bench.py", "__graft_entry__.py")


def _call_name(node: ast.Call) -> str | None:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def collect_sites(root: str) -> list[tuple[str, int, str, str]]:
    """Every (file, line, metric_name, kind) instrument call site with a
    string-literal first argument under the scanned tree."""
    paths: list[str] = []
    for rel in SCAN_DIRS:
        base = os.path.join(root, rel)
        for dirpath, _dirnames, filenames in os.walk(base):
            paths.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith(".py")
            )
    for rel in SCAN_FILES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            paths.append(path)
    sites: list[tuple[str, int, str, str]] = []
    for path in sorted(paths):
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except (OSError, SyntaxError) as exc:
            print(f"check_metric_names: cannot parse {path}: {exc}", file=sys.stderr)
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_name(node)
            if kind not in INSTRUMENT_CALLS or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
                continue  # dynamic names (registry internals) are not sites
            sites.append(
                (os.path.relpath(path, root), node.lineno, first.value, kind)
            )
    return sites


def check(root: str, sites=None) -> list[str]:
    """All namespace violations in the tree (empty list = clean). Pass
    pre-collected ``sites`` to avoid re-walking the tree."""
    if sites is None:
        sites = collect_sites(root)
    problems: list[str] = []
    by_name: dict[str, dict[str, list[str]]] = {}
    for path, line, name, kind in sites:
        if not NAME_RE.match(name):
            problems.append(
                f"{path}:{line}: metric name {name!r} is not snake_case "
                "([a-z][a-z0-9_]*)"
            )
        by_name.setdefault(name, {}).setdefault(kind, []).append(
            f"{path}:{line}"
        )
    for name, kinds in sorted(by_name.items()):
        if len(kinds) > 1:
            detail = "; ".join(
                f"{kind} at {', '.join(locs)}" for kind, locs in sorted(kinds.items())
            )
            problems.append(
                f"metric {name!r} registered with conflicting kinds: {detail}"
            )
    return problems


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sites = collect_sites(root)
    problems = check(root, sites)
    if problems:
        for problem in problems:
            print(f"check_metric_names: {problem}", file=sys.stderr)
        print(
            f"check_metric_names: FAILED ({len(problems)} problem(s) across "
            f"{len(sites)} instrument call sites)",
            file=sys.stderr,
        )
        return 1
    names = {name for _, _, name, _ in sites}
    print(
        f"check_metric_names: OK — {len(sites)} call sites, "
        f"{len(names)} distinct metric names, no conflicts"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
