#!/bin/bash
# TPU tunnel watcher (BASELINE.md "Device (ICI) rung status"): the axon
# backend fails or hangs for hours at a time, so instead of serializing the
# session behind it, this probes every INTERVAL seconds and — the first time
# jax init succeeds against a real device — captures every chip-blocked
# benchmark into OUTDIR, then exits. Run it in the background at round
# start; if the tunnel ever comes up, the hardware rows are waiting.
#
#   nohup scripts/tpu_watch.sh >/tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
OUTDIR=${OUTDIR:-/tmp/tpu_capture}
INTERVAL=${INTERVAL:-300}
mkdir -p "$OUTDIR"

while true; do
    echo "[$(date +%H:%M:%S)] probing tpu tunnel..."
    if timeout 90 python -c "import jax; d = jax.devices()[0]; assert d.platform in ('tpu', 'axon'), d.platform; print('platform', d.platform, d.device_kind)"; then
        echo "[$(date +%H:%M:%S)] TUNNEL UP — capturing"
        # Capture the observability registry alongside the bench output:
        # every process in the run dumps its counters (per-transport bytes,
        # ICI pull ops, ...) into OUTDIR as pid-claimed JSON files.
        timeout 400 env TORCHSTORE_TPU_METRICS_DUMP="$OUTDIR/device_metrics.json" \
            python bench.py --device-section \
            >"$OUTDIR/device_section.out" 2>&1
        echo "device section exit: $?"
        timeout 600 python benchmarks/flash_kernel_bench.py \
            >"$OUTDIR/flash_kernel.out" 2>&1
        echo "flash kernel exit: $?"
        timeout 600 python benchmarks/ring_attention_bench.py --per-device-seq 2048 \
            >"$OUTDIR/ring_attention.out" 2>&1
        echo "ring attention exit: $?"
        touch "$OUTDIR/CAPTURED"
        echo "[$(date +%H:%M:%S)] capture complete -> $OUTDIR"
        exit 0
    fi
    echo "[$(date +%H:%M:%S)] tunnel down; sleeping ${INTERVAL}s"
    sleep "$INTERVAL"
done
