#!/bin/bash
# TPU tunnel watcher (BASELINE.md "Device (ICI) rung status"): the axon
# backend fails or hangs for hours at a time, so instead of serializing the
# session behind it, this probes every INTERVAL seconds and — the first time
# jax init succeeds against a real device — captures every chip-blocked
# benchmark into OUTDIR, then exits. Run it in the background at round
# start; if the tunnel ever comes up, the hardware rows are waiting.
#
#   nohup scripts/tpu_watch.sh >/tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
OUTDIR=${OUTDIR:-/tmp/tpu_capture}
INTERVAL=${INTERVAL:-300}
METRICS_PORT=${METRICS_PORT:-8377}
mkdir -p "$OUTDIR"

while true; do
    echo "[$(date +%H:%M:%S)] probing tpu tunnel..."
    # The probe shares torchstore_tpu.utils.is_device_platform with
    # bench.py / flash_kernel_bench.py, so 'tpu' and tunneled 'axon'
    # devices pass and nothing else does.
    if timeout 90 python -c "import jax; from torchstore_tpu.utils import is_device_platform; d = jax.devices()[0]; assert is_device_platform(d.platform), d.platform; print('platform', d.platform, d.device_kind)"; then
        echo "[$(date +%H:%M:%S)] TUNNEL UP — capturing"
        # Capture the full observability plane alongside the bench output:
        # per-process metrics dumps (pid-claimed JSON), a distributed trace
        # merged into one Perfetto timeline, and a LIVE /metrics scrape of
        # the run through the HTTP exporter while it executes. Stale trace
        # files AND the .owner claim sidecar from a previous capture in
        # this OUTDIR must not pollute the merge or divert the new run's
        # claim arbitration.
        rm -f "$OUTDIR"/device_trace*
        # Flight-recorder post-mortems from every process of the runs below
        # land here (quarantines, injected faults, unclean exits).
        mkdir -p "$OUTDIR/flight"
        timeout 400 env TORCHSTORE_TPU_METRICS_DUMP="$OUTDIR/device_metrics.json" \
            TORCHSTORE_TPU_TRACE="$OUTDIR/device_trace.json" \
            TORCHSTORE_TPU_METRICS_PORT="$METRICS_PORT" \
            TORCHSTORE_TPU_FLIGHT_DIR="$OUTDIR/flight" \
            python bench.py --device-section \
            >"$OUTDIR/device_section.out" 2>&1 &
        BENCH_PID=$!
        # Poll the live endpoint until the run answers (or exits): proof
        # the scrape path works on hardware, and a mid-run counter snapshot.
        for _ in $(seq 1 60); do
            if curl -sf "http://127.0.0.1:$METRICS_PORT/metrics" \
                >"$OUTDIR/live_metrics.prom" 2>/dev/null; then
                curl -sf "http://127.0.0.1:$METRICS_PORT/healthz" \
                    >"$OUTDIR/live_healthz.json" 2>/dev/null || true
                echo "live /metrics scraped mid-run"
                break
            fi
            kill -0 "$BENCH_PID" 2>/dev/null || break
            sleep 2
        done
        wait "$BENCH_PID"
        echo "device section exit: $?"
        # Stitch every process's trace file into one timeline.
        python scripts/merge_traces.py "$OUTDIR/device_trace.json" \
            -o "$OUTDIR/device_trace.merged.json" \
            && echo "merged trace -> $OUTDIR/device_trace.merged.json"
        # Cold-path capture on the DEVICE HOST: first-sync vs steady GB/s
        # with and without ts.prewarm (one JSON line + iteration log). The
        # host-side numbers in BENCH_r* come from the shared CPU box; this
        # row shows what the provisioning subsystem buys on real TPU-host
        # tmpfs/DRAM. Working set stays modest (256 MB) so the capture
        # finishes even on a busy tunnel window.
        timeout 600 env TORCHSTORE_TPU_BENCH_COLD_MB=256 \
            TORCHSTORE_TPU_FLIGHT_DIR="$OUTDIR/flight" \
            python bench.py --cold-path \
            >"$OUTDIR/cold_path.out" 2>&1
        echo "cold path exit: $?"
        # Decision telemetry on the DEVICE HOST: drive a small store round
        # trip and capture the traffic matrix + the merged flight-recorder
        # timeline (one JSON each). Proof the ledger/recorder plane works
        # where placement decisions will actually run.
        timeout 300 env TORCHSTORE_TPU_FLIGHT_DIR="$OUTDIR/flight" \
            python scripts/capture_telemetry.py \
            >"$OUTDIR/traffic_matrix.json" 2>"$OUTDIR/telemetry_capture.log"
        echo "telemetry capture exit: $? (matrix -> $OUTDIR/traffic_matrix.json, flight -> $OUTDIR/flight_record.json)"
        mv -f /tmp/ts_flight_record.json "$OUTDIR/flight_record.json" 2>/dev/null || true
        timeout 600 python benchmarks/flash_kernel_bench.py \
            >"$OUTDIR/flash_kernel.out" 2>&1
        echo "flash kernel exit: $?"
        timeout 600 python benchmarks/ring_attention_bench.py --per-device-seq 2048 \
            >"$OUTDIR/ring_attention.out" 2>&1
        echo "ring attention exit: $?"
        touch "$OUTDIR/CAPTURED"
        echo "[$(date +%H:%M:%S)] capture complete -> $OUTDIR"
        exit 0
    fi
    echo "[$(date +%H:%M:%S)] tunnel down; sleeping ${INTERVAL}s"
    sleep "$INTERVAL"
done
