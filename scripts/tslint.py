#!/usr/bin/env python
"""tslint — the repo's static-analysis suite (torchstore_tpu/analysis/).

Twenty checkers grounded in real shipped bug classes — sixteen syntactic
single-node rules (endpoint-drift, async-blocking, cancellation-swallow,
orphan-task, fork-safety, env-registry, metric-discipline, landing-copy,
retry-discipline, one-sided-discipline, stream/quant/shard/stage/control/
history discipline) plus four flow-aware rules built on the per-function
CFG in analysis/flow.py (bracket-discipline, epoch-discipline,
await-atomicity, decision-flow). See docs/ARCHITECTURE.md ("Static
analysis") for the rule catalog and the baseline workflow.

Usage:
    python scripts/tslint.py                 # report; exit 1 on NEW findings
    python scripts/tslint.py --json          # machine-readable report (incl.
                                             # per-rule timing)
    python scripts/tslint.py --fail-on-new   # gate mode: print only new findings
    python scripts/tslint.py --sarif out.sarif  # also write a SARIF 2.1.0 log
    python scripts/tslint.py --rules orphan-task,cancellation-swallow
    python scripts/tslint.py --write-baseline  # re-grandfather current findings
    python scripts/tslint.py --regen-env-docs  # rewrite docs/API.md env table
    python scripts/tslint.py --list-rules

Suppression: ``# tslint: disable=<rule>[,<rule>]`` on the offending line or
the line above (add a comment saying WHY); ``# tslint: disable-file=<rule>``
in the first 20 lines of a file. Grandfathered findings live in
tslint_baseline.json — the gate fails only on findings absent from it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import types

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

if "torchstore_tpu" not in sys.modules:
    # Keep the linter stdlib-only: importing the analysis subpackage must
    # not execute torchstore_tpu/__init__.py (which pulls the whole store
    # runtime + numpy). Register a minimal parent package pointing at the
    # real directory so only analysis/* modules load.
    _pkg = types.ModuleType("torchstore_tpu")
    _pkg.__path__ = [os.path.join(REPO_ROOT, "torchstore_tpu")]
    sys.modules["torchstore_tpu"] = _pkg

from torchstore_tpu.analysis import (  # noqa: E402
    DEFAULT_BASELINE,
    run_checks,
    save_baseline,
)
from torchstore_tpu.analysis.checkers import CHECKERS  # noqa: E402


def regen_env_docs(root: str) -> int:
    """Rewrite the generated env-var table in docs/API.md from the registry
    parsed out of config.py (static — same parse the checker uses)."""
    from torchstore_tpu.analysis.checkers.env_registry import (
        DOCS_BEGIN,
        DOCS_END,
        parse_registry,
        render_env_table,
    )

    config_path = os.path.join(root, "torchstore_tpu", "config.py")
    with open(config_path, encoding="utf-8") as f:
        entries, _prefixes, _span = parse_registry(f.read())
    if not entries:
        print("tslint: config.py defines no ENV_REGISTRY", file=sys.stderr)
        return 1
    docs_path = os.path.join(root, "docs", "API.md")
    with open(docs_path, encoding="utf-8") as f:
        docs = f.read()
    table = render_env_table(entries)
    block = f"{DOCS_BEGIN}\n{table}\n{DOCS_END}"
    if DOCS_BEGIN in docs and DOCS_END in docs:
        head = docs.split(DOCS_BEGIN, 1)[0]
        tail = docs.split(DOCS_END, 1)[1]
        docs = head + block + tail
    else:
        docs = docs.rstrip() + "\n\n## Environment variables\n\n" + block + "\n"
    with open(docs_path, "w", encoding="utf-8") as f:
        f.write(docs)
    print(f"tslint: regenerated env-var table ({len(entries)} entries) in docs/API.md")
    return 0


def regen_metric_docs(root: str) -> int:
    """Rewrite the generated metrics reference table in docs/API.md from a
    static scan of every instrument registration site (same scan the
    metric-discipline drift rule validates against)."""
    from torchstore_tpu.analysis.checkers.metric_discipline import (
        METRIC_DOCS_BEGIN,
        METRIC_DOCS_END,
        collect_instruments,
        render_metric_table,
    )

    instruments = collect_instruments(root)
    if not instruments:
        print("tslint: no metric registration sites found", file=sys.stderr)
        return 1
    docs_path = os.path.join(root, "docs", "API.md")
    with open(docs_path, encoding="utf-8") as f:
        docs = f.read()
    table = render_metric_table(instruments)
    block = f"{METRIC_DOCS_BEGIN}\n{table}\n{METRIC_DOCS_END}"
    if METRIC_DOCS_BEGIN in docs and METRIC_DOCS_END in docs:
        head = docs.split(METRIC_DOCS_BEGIN, 1)[0]
        tail = docs.split(METRIC_DOCS_END, 1)[1]
        docs = head + block + tail
    else:
        docs = docs.rstrip() + "\n\n## Metrics reference\n\n" + block + "\n"
    with open(docs_path, "w", encoding="utf-8") as f:
        f.write(docs)
    names = {name for _, _, name, _, _ in instruments}
    print(
        f"tslint: regenerated metrics table ({len(names)} metrics, "
        f"{len(instruments)} registration sites) in docs/API.md"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--json", action="store_true", help="JSON report")
    parser.add_argument(
        "--fail-on-new",
        action="store_true",
        help="gate mode: print only findings absent from the baseline",
    )
    parser.add_argument(
        "--rules", help="comma-separated subset of rules (default: all)"
    )
    parser.add_argument(
        "--baseline",
        default=os.path.join(REPO_ROOT, DEFAULT_BASELINE),
        help="baseline file (default: tslint_baseline.json at the repo root)",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="treat every finding as new (ignore the baseline)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline from current findings and exit 0",
    )
    parser.add_argument(
        "--sarif",
        metavar="PATH",
        help="also write a SARIF 2.1.0 log to PATH ('-' for stdout); exit "
        "code is unchanged",
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--regen-env-docs",
        action="store_true",
        help="regenerate the env-var table in docs/API.md from config.ENV_REGISTRY",
    )
    parser.add_argument(
        "--regen-metric-docs",
        action="store_true",
        help="regenerate the metrics reference table in docs/API.md from "
        "a static scan of instrument registration sites",
    )
    parser.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(CHECKERS):
            print(rule)
        return 0
    if args.regen_env_docs:
        return regen_env_docs(args.root)
    if args.regen_metric_docs:
        return regen_metric_docs(args.root)

    rules = args.rules.split(",") if args.rules else None
    baseline = None if args.no_baseline else args.baseline
    result = run_checks(args.root, rules=rules, baseline_path=baseline)

    if args.write_baseline:
        save_baseline(args.baseline, result.findings)
        print(
            f"tslint: wrote {len(result.findings)} finding(s) to "
            f"{os.path.relpath(args.baseline, args.root)}"
        )
        return 0

    if args.sarif:
        from torchstore_tpu.analysis.sarif import to_sarif

        doc = json.dumps(to_sarif(result, CHECKERS), indent=2)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                f.write(doc)
                f.write("\n")

    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
        return 1 if result.new else 0

    new_keys = {f.key for f in result.new}
    shown = result.new if args.fail_on_new else result.findings
    for f in shown:
        tag = "" if f.key in new_keys else " [baselined]"
        print(f"{f.render()}{tag}")
    n_rules = len(result.rules)
    if result.new:
        print(
            f"\ntslint: FAILED — {len(result.new)} NEW finding(s) "
            f"({len(result.baselined)} baselined) across {n_rules} rule(s). "
            "Fix them, pragma with justification, or (last resort) "
            "--write-baseline."
        )
        return 1
    print(
        f"tslint: OK — 0 new findings ({len(result.baselined)} baselined) "
        f"across {n_rules} rule(s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
