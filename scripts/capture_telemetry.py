#!/usr/bin/env python
"""Drive a small store round trip and capture the decision-telemetry plane:
prints one JSON doc to stdout holding the traffic matrix, the SLO
scoreboard (``ts.slo_report()``), and the control plane's dry-run view
(``ts.control_plan()`` — what the policy engine WOULD do over this
traffic), and writes the merged flight record to
/tmp/ts_flight_record.json (tpu_watch.sh moves both into its OUTDIR
during a device capture). Safe to run anywhere a store can boot."""

import asyncio
import json
import sys

import numpy as np


async def main() -> int:
    import torchstore_tpu as ts

    await ts.initialize(
        store_name="telemetry_capture",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        items = {
            f"cap/{i}": np.random.rand(65536).astype(np.float32)
            for i in range(16)
        }
        await ts.put_batch(items, store_name="telemetry_capture")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        matrix = await ts.traffic_matrix(store_name="telemetry_capture")
        slo = await ts.slo_report(store_name="telemetry_capture")
        plan = await ts.control_plan(store_name="telemetry_capture")
        record = await ts.flight_record(store_name="telemetry_capture")
        print(
            json.dumps(
                {"traffic": matrix, "slo": slo, "control_plan": plan}
            )
        )
        # One-shot CLI at capture end: nothing else runs on this loop, so
        # a synchronous write cannot stall concurrent work.
        with open("/tmp/ts_flight_record.json", "w") as f:  # tslint: disable=async-blocking
            json.dump(record, f)
        print(
            f"# captured {len(record['events'])} flight event(s), "
            f"{len(matrix['edges'])} matrix source host(s), "
            f"{len(plan.get('actions') or ())} planned control action(s)",
            file=sys.stderr,
        )
        return 0
    finally:
        await ts.shutdown("telemetry_capture")


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
