#!/usr/bin/env python
"""Drive a small store round trip and capture the decision-telemetry plane:
prints one JSON doc to stdout holding the traffic matrix, the SLO
scoreboard (``ts.slo_report()``), the control plane's dry-run view
(``ts.control_plan()`` — what the policy engine WOULD do over this
traffic), the elastic plane's dry-run view (``ts.autoscale_plan()`` plus
the live fleet size it solved against — a ``--watch`` run leaves a
fleet-size time series), and the fleet's retained time-series history
(``ts.history()``),
and writes the merged flight record to /tmp/ts_flight_record.json
(tpu_watch.sh moves both into its OUTDIR during a device capture). Safe to
run anywhere a store can boot.

``--watch N`` keeps the store up and re-captures N times at ``--interval``
seconds, appending one JSON doc per line (JSONL) to ``--out`` (default
stdout) — a device run leaves a time-series artifact, not just a final
snapshot."""

import argparse
import asyncio
import json
import sys
import time

import numpy as np


async def _capture(ts, include_record: bool) -> dict:
    matrix = await ts.traffic_matrix(store_name="telemetry_capture")
    slo = await ts.slo_report(store_name="telemetry_capture")
    plan = await ts.control_plan(store_name="telemetry_capture")
    scale = await ts.autoscale_plan(store_name="telemetry_capture")
    doc = {
        "captured_ts": time.time(),
        "traffic": matrix,
        "slo": slo,
        "control_plan": plan,
        # The elastic plane's dry run: what the autoscaler WOULD do over
        # this traffic, plus the fleet view it solved against (live/
        # draining counts, idle-round hysteresis, blob-spill backlog). A
        # --watch run therefore leaves a fleet-size time series — one
        # fleet.volumes sample per capture line.
        "autoscale_plan": scale,
        "fleet_size": (scale.get("fleet") or {}).get("volumes"),
        "history": await ts.history(store_name="telemetry_capture"),
    }
    if include_record:
        doc["flight_record"] = await ts.flight_record(
            store_name="telemetry_capture"
        )
    return doc


async def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--watch", type=int, default=0, metavar="N",
        help="re-capture N times after the first (JSONL, one doc/line)",
    )
    parser.add_argument(
        "--interval", type=float, default=5.0, metavar="S",
        help="seconds between --watch captures (default 5)",
    )
    parser.add_argument(
        "--out", default=None, metavar="PATH",
        help="append captures to this file instead of stdout",
    )
    args = parser.parse_args()

    import torchstore_tpu as ts

    await ts.initialize(
        store_name="telemetry_capture",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        items = {
            f"cap/{i}": np.random.rand(65536).astype(np.float32)
            for i in range(16)
        }
        await ts.put_batch(items, store_name="telemetry_capture")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        record = await ts.flight_record(store_name="telemetry_capture")

        # One-shot CLI between captures: nothing else runs on this loop,
        # so synchronous writes cannot stall concurrent work.
        def emit(doc: dict) -> None:
            line = json.dumps(doc)
            if args.out:
                with open(args.out, "a") as f:  # tslint: disable=async-blocking
                    f.write(line + "\n")
            else:
                print(line)

        doc = await _capture(ts, include_record=False)
        emit(doc)
        for i in range(max(0, args.watch)):
            # Keep traffic flowing so each re-capture sees a live window,
            # not a decaying ledger of the boot-time batch.
            await ts.get_batch(dict(dests), store_name="telemetry_capture")
            await asyncio.sleep(max(0.0, args.interval))
            emit(await _capture(ts, include_record=False))
        with open("/tmp/ts_flight_record.json", "w") as f:  # tslint: disable=async-blocking
            json.dump(record, f)
        n_hist = len(
            (doc["history"]["processes"].get("client") or {}).get("series")
            or {}
        )
        print(
            f"# captured {len(record['events'])} flight event(s), "
            f"{len(doc['traffic']['edges'])} matrix source host(s), "
            f"{len(doc['control_plan'].get('actions') or ())} planned "
            f"control action(s), {len(doc['autoscale_plan'].get('actions') or ())} "
            f"planned autoscale action(s) over {doc['fleet_size']} volume(s), "
            f"{n_hist} client history series, "
            f"{1 + max(0, args.watch)} capture(s)",
            file=sys.stderr,
        )
        return 0
    finally:
        await ts.shutdown("telemetry_capture")


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
