#!/usr/bin/env python
"""Drive a small store round trip and capture the decision-telemetry plane:
prints the traffic matrix JSON to stdout and writes the merged flight
record to /tmp/ts_flight_record.json (tpu_watch.sh moves both into its
OUTDIR during a device capture). Safe to run anywhere a store can boot."""

import asyncio
import json
import sys

import numpy as np


async def main() -> int:
    import torchstore_tpu as ts

    await ts.initialize(
        store_name="telemetry_capture",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        items = {
            f"cap/{i}": np.random.rand(65536).astype(np.float32)
            for i in range(16)
        }
        await ts.put_batch(items, store_name="telemetry_capture")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        await ts.get_batch(dict(dests), store_name="telemetry_capture")
        matrix = await ts.traffic_matrix(store_name="telemetry_capture")
        record = await ts.flight_record(store_name="telemetry_capture")
        print(json.dumps(matrix))
        # One-shot CLI at capture end: nothing else runs on this loop, so
        # a synchronous write cannot stall concurrent work.
        with open("/tmp/ts_flight_record.json", "w") as f:  # tslint: disable=async-blocking
            json.dump(record, f)
        print(
            f"# captured {len(record['events'])} flight event(s), "
            f"{len(matrix['edges'])} matrix source host(s)",
            file=sys.stderr,
        )
        return 0
    finally:
        await ts.shutdown("telemetry_capture")


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
