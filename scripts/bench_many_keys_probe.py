"""Quick many-small-keys probe used to record the pre/post-PR per-key cost
for the steady-state sync pipeline PR (ISSUE 5 acceptance: the many_keys
bench section must be >= 2x faster than the pre-PR per-key path).

Usage: JAX_PLATFORMS=cpu python scripts/bench_many_keys_probe.py [n_keys] [key_kb]
Prints one JSON line: {"n_keys", "key_kb", "put_s", "get_s",
"per_key_put_us", "gbps"} (medians over warm iterations).
"""

import asyncio
import json
import statistics
import sys
import time

import numpy as np


async def main(n_keys: int, key_kb: int, iters: int = 3) -> dict:
    import torchstore_tpu as ts

    await ts.initialize(
        store_name="probe",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        n_elem = max(1, key_kb * 1024 // 4)
        sd = {
            "params": {
                str(i): np.random.rand(n_elem).astype(np.float32)
                for i in range(n_keys)
            }
        }
        total = sum(v.nbytes for v in sd["params"].values())
        puts, gets = [], []
        for it in range(iters + 1):  # iter 0 cold, rest warm
            stamp = float(it + 1)
            for arr in sd["params"].values():
                arr[0] = stamp
            t0 = time.perf_counter()
            await ts.put_state_dict("probe/sd", sd, store_name="probe")
            t1 = time.perf_counter()
            out = await ts.get_state_dict("probe/sd", store_name="probe")
            t2 = time.perf_counter()
            assert out["params"]["0"][0] == stamp
            if it > 0:
                puts.append(t1 - t0)
                gets.append(t2 - t1)
            print(
                f"# iter {it}: put {t1-t0:.3f}s get {t2-t1:.3f}s",
                file=sys.stderr,
            )
        put_s = statistics.median(puts)
        get_s = statistics.median(gets)
        return {
            "n_keys": n_keys,
            "key_kb": key_kb,
            "put_s": round(put_s, 4),
            "get_s": round(get_s, 4),
            "per_key_put_us": round(put_s / n_keys * 1e6, 2),
            "gbps": round(2 * total / 1e9 / (put_s + get_s), 3),
        }
    finally:
        await ts.shutdown("probe")


if __name__ == "__main__":
    n_keys = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    key_kb = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    print(json.dumps(asyncio.run(main(n_keys, key_kb))))
