"""Headline benchmark: full state_dict weight-sync throughput.

Measures the BASELINE.md north-star flow — a trainer publishing a model-scale
state dict and a consumer pulling all of it back (put_state_dict +
get_state_dict round trip) through real storage-volume processes over the
same-host SHM transport. This is the store's data plane end to end: flatten,
commit-marker protocol, metadata RPCs, segment handshakes, and the hot
memcpys.

Host-resident arrays are used deliberately: on this image the TPU chip is
reached through a tunnel whose device->host path measures ~0.01 GB/s, which
would benchmark the tunnel, not the framework. The store's TPU coupling
(NamedSharding put/get) is exercised by the test suite and dryrun_multichip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"host_memcpy_gbps", "calib_ratio", "sections", "p50_put_ms", "p50_get_ms",
"p50_get_1kb_ms" (warm one-sided 1KB get, zero RPCs), "per_key_get_us",
"many_keys_get_gbps", "get_memcpy_ratio", "ledger_overhead_pct" (always-on
decision-telemetry cost on the warm get leg, budget <= 2%), "metrics",
"fleet"}. ``fleet`` is the run's merged, process-labeled fleet
registry (``ts.fleet_snapshot()``: client + controller + every volume
process, plus per-process hot keys). ``vs_baseline`` is value / (REFERENCE_GBPS * calib_ratio):
REFERENCE_GBPS approximates the reference's CUDA+RDMA same-host weight-sync
path (no number is published by the reference — see BASELINE.md; 10 GB/s is
the proxy the north star's ">=80% of the CUDA+RDMA path" is scored against),
and calib_ratio scales it down on degraded hosts (a per-run single-thread
memcpy calibration against CALIB_MEMCPY_ANCHOR_GBPS). ``sections`` carries
each headline section's full stats (median/best/warm_min/warm_cv/warn/
reruns — the bounded rerun-on-WARN policy); ``metrics`` is the process's
observability-registry snapshot (per-transport byte counters, op latency
histograms, SHM pool economics — see torchstore_tpu/observability/).

Metric definition: DELIVERED bytes per second — each round trip hands N
logical bytes to the store and N to the consumer (2N per iteration),
independent of how many physical copies that took. Zero-copy snapshot gets
and copy-free registered publishes deliver without moving every byte; that
reduction is exactly the optimization under measurement (an RDMA one-sided
read is credited the same way). Physical per-direction rates are printed
on every iteration line so the copy count is never hidden.
"""

import asyncio
import json
import sys
import time

import numpy as np

REFERENCE_GBPS = 10.0
# Single-thread memcpy ceiling of the host class the 10 GB/s proxy was set
# against (~8 GB/s measured when the r2/r3 numbers were recorded,
# BASELINE.md "Large-tier transport sweep"). A per-run calibration against
# this anchor makes a degraded host VISIBLE in the JSON and scales the
# proxy down with it: the bench asserts a bar the reference only logs
# (/root/reference/torchstore/logging.py:39-66), so it must control for
# host weather (VERDICT r4 weak #1 — every section ran uniformly ~30%
# slower than r3 and the record had no way to show why).
CALIB_MEMCPY_ANCHOR_GBPS = 8.0

N_TENSORS = 32
TENSOR_MB = 32  # 32 x 32MB = 1 GiB per direction
ITERS = 6  # iter 0 is cold; iters 1+ are the warm set the headline reports
RERUNS_ON_WARN = 2  # bounded: headline sections rerun at most this many times


def calibrate_memcpy_gbps(size_mb: float = 256, reps: int = 5) -> float:
    """Best-of-N single-thread memcpy rate on THIS run's host.

    Best (not median) is deliberate: the calibration estimates the host's
    *ceiling*, and transient contention can only push individual reps down.
    256 MB per rep is large enough to defeat caches and small enough to
    stay out of the bench's own tmpfs budget.
    """
    src = np.random.rand(max(1, int(size_mb * 1024 * 1024 // 8)))  # f64: 8 B
    dst = np.empty_like(src)
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        best = max(best, src.nbytes / 1e9 / dt)
    return best


async def _device_section_child() -> int:
    """Runs INSIDE the isolated subprocess (``bench.py --device-section``).

    Benches the flagship device (ICI) rung: a jax state dict registered on
    the real chip via the device-mode direct sync, pulled HBM->HBM through
    the XLA transfer engine (the re-architecture of the reference's
    one-sided RDMA reads, monarch_rdma.py:158-219). Also measures the
    legacy host-staging comparison (bare D2H) so the tunnel/PCIe floor is
    attributable. Exit codes: 0 = measured, 3 = no TPU in this jax world.
    """
    import os

    import jax

    allow_cpu = os.environ.get("TORCHSTORE_TPU_BENCH_DEVICE_ALLOW_CPU") == "1"
    if allow_cpu:
        # Validation mode: force the CPU backend BEFORE any device init —
        # this image's sitecustomize routes jax at the TPU tunnel, which
        # hangs indefinitely when the tunnel is down (the exact failure
        # this child's subprocess isolation exists for).
        jax.config.update("jax_platforms", "cpu")
    from torchstore_tpu.utils import is_device_platform

    devs = jax.devices()
    if not is_device_platform(devs[0].platform) and not allow_cpu:
        print(f"# device section: no TPU (platform={devs[0].platform})")
        return 3
    dev = devs[0]
    from torchstore_tpu.direct_weight_sync import (
        DirectWeightSyncDest,
        DirectWeightSyncSource,
    )

    n_t, elems = 8, 8 * 1024 * 1024  # 8 x 32 MB fp32 = 256 MB on chip
    host = [np.random.rand(elems).astype(np.float32) for _ in range(n_t)]
    sd = {str(i): jax.device_put(h, dev) for i, h in enumerate(host)}
    jax.block_until_ready(list(sd.values()))
    total = sum(h.nbytes for h in host)

    source = DirectWeightSyncSource()
    dest = DirectWeightSyncDest()
    try:
        await source.register(sd)
        if source.device_info is None:
            print("# device section: device path did not engage")
            return 3
        target = {
            str(i): jax.ShapeDtypeStruct(
                (elems,),
                np.float32,
                sharding=jax.sharding.SingleDeviceSharding(dev),
            )
            for i in range(n_t)
        }
        rates = []
        for it in range(4):
            # Republish current weights (device mode: metadata-only bump;
            # staging happens per pull, so every iter moves fresh bytes).
            stamp = float(it + 1)
            sd = {
                k: v.at[0].set(stamp) for k, v in sd.items()
            }
            jax.block_until_ready(list(sd.values()))
            source.update_sources(sd)
            await source.refresh()
            t0 = time.perf_counter()
            out = await dest.pull_device([source.device_info], dict(target))
            jax.block_until_ready(list(out.values()))
            dt = time.perf_counter() - t0
            gbps = total / 1e9 / dt
            rates.append(gbps)
            first = float(np.asarray(out["0"][0]))
            assert first == stamp, f"stale device pull: {first} != {stamp}"
            print(
                f"# device-path iter {it}: pull {dt*1e3:.0f} ms "
                f"({gbps:.2f} GB/s HBM->HBM via transfer engine)"
            )
        warm = rates[1:] or rates
        import statistics

        print(
            f"# device-path direct sync ({total/1e6:.0f} MB on "
            f"{dev.platform}): warm median {statistics.median(warm):.2f} "
            f"GB/s, best {max(rates):.2f} GB/s  [delivered == physical: "
            "each byte moves once, device to device]"
        )
        # Tunnel floor for context: bare serial D2H of one tensor.
        t0 = time.perf_counter()
        np.asarray(sd["0"])
        d2h = time.perf_counter() - t0
        print(
            f"# context: bare D2H of one 32 MB tensor {d2h*1e3:.0f} ms "
            f"({host[0].nbytes/1e9/d2h:.3f} GB/s tunnel/PCIe floor)"
        )
        return 0
    finally:
        await dest.close()
        await source.close()


def device_section_subprocess() -> None:
    """Run the device bench in a FRESH subprocess with one retry (VERDICT
    r3 item 1): a wedged or failing TPU backend (axon tunnel) can hang or
    crash jax init, and in-process that erased the round's only hardware
    evidence (BENCH_r03). The subprocess is killed on timeout and the
    failure documented; the host sections above are never at risk."""
    import os
    import subprocess

    if os.environ.get("TORCHSTORE_TPU_BENCH_DEVICE", "1") in ("0", "false"):
        print("# device section disabled (TORCHSTORE_TPU_BENCH_DEVICE=0)", file=sys.stderr)
        return
    env = dict(os.environ)
    # The child must see the REAL platform: undo any CPU forcing —
    # including a leftover ALLOW_CPU validation flag, which would silently
    # bench the CPU backend on a TPU host.
    env.pop("JAX_PLATFORMS", None)
    env.pop("TORCHSTORE_TPU_BENCH_DEVICE_ALLOW_CPU", None)
    for attempt in (1, 2):
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--device-section"],
                capture_output=True,
                text=True,
                timeout=180,
                env=env,
            )
        except subprocess.TimeoutExpired:
            print(
                f"# device section attempt {attempt}: TIMED OUT after 180s "
                "(TPU backend hung — axon tunnel down?)",
                file=sys.stderr,
            )
            continue
        for line in (proc.stdout + proc.stderr).splitlines():
            if line.startswith("#"):
                print(line, file=sys.stderr)
        if proc.returncode == 0:
            return
        if proc.returncode == 3:
            # Deterministic outcome (this host has no TPU) — a retry would
            # just pay another interpreter + jax init for the same answer.
            print(
                "# device-path section skipped: no usable TPU on this host",
                file=sys.stderr,
            )
            return
        tail = "; ".join(proc.stderr.strip().splitlines()[-2:])
        print(
            f"# device section attempt {attempt} failed "
            f"(exit {proc.returncode}): {tail}",
            file=sys.stderr,
        )
    print(
        "# device-path section SKIPPED after 2 attempts — no hardware "
        "numbers this run (subprocess-isolated; host sections unaffected)",
        file=sys.stderr,
    )


async def cold_path_section(
    n_tensors: int = N_TENSORS,
    tensor_mb: float = TENSOR_MB,
    steady_iters: int = 4,
) -> dict:
    """Cold-start section: how much of steady-state throughput does the
    FIRST sync of a fresh fleet deliver, with and without ``ts.prewarm``?

    Two fresh fleets (auto-prewarm disabled so the baseline is honestly
    lazy): fleet A measures the un-provisioned first put+get round trip —
    every segment cold-allocates and faults on the critical path — then its
    steady state; fleet B runs ``ts.prewarm(sd)`` first (manifest-driven
    pool pre-sizing + prefault, off the critical path as in real use, its
    wall time reported separately) and measures the same first sync. The
    working set scales via TORCHSTORE_TPU_BENCH_COLD_MB (total MB).

    Emits ``cold_vs_steady`` and ``cold_prewarmed_vs_steady`` — the
    ISSUE-3 acceptance ratios (VERDICT r5 weak #3: first-sync at 2-3% of
    steady was the one axis the reference has no answer for)."""
    import statistics

    import torchstore_tpu as ts
    from torchstore_tpu.config import StoreConfig

    n_elem = max(1, int(tensor_mb * 1024 * 1024 // 4))
    total_bytes = n_tensors * n_elem * 4
    config = StoreConfig(prewarm_auto=False)

    def fresh_sd() -> dict:
        return {
            "layers": {
                str(i): np.random.rand(n_elem).astype(np.float32)
                for i in range(n_tensors)
            }
        }

    async def first_sync(store: str, sd: dict) -> float:
        for arr in sd["layers"].values():
            arr[0] = 0.5
        t0 = time.perf_counter()
        await ts.put_state_dict(f"{store}/sd", sd, store_name=store)
        out = await ts.get_state_dict(f"{store}/sd", store_name=store)
        dt = time.perf_counter() - t0
        assert out["layers"]["0"][0] == 0.5, "cold sync served stale data"
        return 2 * total_bytes / 1e9 / dt

    async def steady(store: str, sd: dict) -> list[float]:
        rates = []
        for it in range(steady_iters):
            stamp = float(it + 1)
            for arr in sd["layers"].values():
                arr[0] = stamp
            t0 = time.perf_counter()
            await ts.put_state_dict(f"{store}/sd", sd, store_name=store)
            out = await ts.get_state_dict(f"{store}/sd", store_name=store)
            dt = time.perf_counter() - t0
            assert out["layers"]["0"][0] == stamp, "steady sync stale data"
            rates.append(2 * total_bytes / 1e9 / dt)
        return rates

    # Warmup fleet: a KB-scale sync through a throwaway fleet pays the
    # PROCESS-one-time costs (imports, native lib load, first-RPC code
    # paths) so neither measured fleet gets them — fleet A's cold number
    # must be segment provisioning, not interpreter warmup.
    await ts.initialize(
        store_name="bench_cold_warmup",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
        config=config,
    )
    try:
        tiny = {"layers": {"0": np.zeros(65536, np.float32)}}
        await ts.put_state_dict("w/sd", tiny, store_name="bench_cold_warmup")
        await ts.get_state_dict("w/sd", store_name="bench_cold_warmup")
    finally:
        await ts.shutdown("bench_cold_warmup")
    # Fleet A: lazy cold path.
    await ts.initialize(
        store_name="bench_cold",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
        config=config,
    )
    try:
        sd = fresh_sd()
        cold_gbps = await first_sync("bench_cold", sd)
        steady_rates = await steady("bench_cold", sd)
    finally:
        await ts.shutdown("bench_cold")
    # Fleet B: provisioned cold path.
    await ts.initialize(
        store_name="bench_coldp",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
        config=config,
    )
    try:
        sd = fresh_sd()
        t0 = time.perf_counter()
        prewarm_report = await ts.prewarm(sd, store_name="bench_coldp")
        prewarm_s = time.perf_counter() - t0
        prewarmed_gbps = await first_sync("bench_coldp", sd)
        steady_rates += await steady("bench_coldp", sd)
    finally:
        await ts.shutdown("bench_coldp")
    steady_gbps = statistics.median(steady_rates)
    out = {
        "total_mb": round(total_bytes / 1e6, 1),
        "cold_gbps": round(cold_gbps, 3),
        "cold_prewarmed_gbps": round(prewarmed_gbps, 3),
        "steady_gbps": round(steady_gbps, 3),
        "cold_vs_steady": round(cold_gbps / steady_gbps, 3),
        "cold_prewarmed_vs_steady": round(prewarmed_gbps / steady_gbps, 3),
        "prewarm_seconds": round(prewarm_s, 3),
        "prewarm": {
            key: prewarm_report.get(key)
            for key in (
                "ok",
                "segments",
                "bytes",
                "dials",
                "clamped_bytes",
                "errors",
            )
        },
    }
    print(
        f"# cold path ({out['total_mb']:.0f} MB): first sync "
        f"{cold_gbps:.2f} GB/s lazy vs {prewarmed_gbps:.2f} GB/s prewarmed "
        f"(steady {steady_gbps:.2f}; ratios {out['cold_vs_steady']:.2f} -> "
        f"{out['cold_prewarmed_vs_steady']:.2f}; prewarm took "
        f"{prewarm_s*1e3:.0f} ms off the critical path)",
        file=sys.stderr,
    )
    return out


async def many_keys_section(
    n_keys: int = 2048,
    key_kb: float = 64,
    iters: int = 5,
) -> dict:
    """Many-small-keys section (ISSUE 5 + ISSUE 7): a realistic state dict
    is thousands of parameters, not 32 big blocks — per-key overhead
    (request building, handshake entries, volume indexing, notify
    metadata) dominates long before bandwidth does. This section measures
    the steady-state sync pipeline's answer: small-key arena packing (one
    segment + one index pass per batch), overlapped landing copies, the
    iteration-stable transfer-plan cache, and — on the get side — the
    one-sided data plane (warm gets are a stamped memcpy loop on the
    landing pool, zero per-key RPCs).

    Emits ``many_keys_gbps`` (delivered, warm median), ``per_key_put_us``
    / ``per_key_get_us`` (warm-median wall time / key), ``get_gbps``
    (delivered get-leg rate), and ``get_memcpy_ratio`` — host single-
    thread memcpy rate / get_gbps, the ROADMAP "~memcpy bound" acceptance
    (<= 2.5 at full scale), calibrated against a same-mood-window local
    memcpy measurement."""
    import statistics

    import torchstore_tpu as ts

    await ts.initialize(
        store_name="bench_keys",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        n_elem = max(1, int(key_kb * 1024 // 4))
        sd = {
            "params": {
                str(i): np.random.rand(n_elem).astype(np.float32)
                for i in range(n_keys)
            }
        }
        total = sum(v.nbytes for v in sd["params"].values())
        puts, gets, rates = [], [], []
        for it in range(iters + 1):  # iter 0 is the cold start
            stamp = float(it + 1)
            for arr in sd["params"].values():
                arr[0] = stamp
            t0 = time.perf_counter()
            await ts.put_state_dict("mk/sd", sd, store_name="bench_keys")
            t1 = time.perf_counter()
            out = await ts.get_state_dict("mk/sd", store_name="bench_keys")
            t2 = time.perf_counter()
            assert out["params"]["0"][0] == stamp, "many_keys stale data"
            assert out["params"][str(n_keys - 1)][0] == stamp
            if it > 0:
                puts.append(t1 - t0)
                gets.append(t2 - t1)
                rates.append(2 * total / 1e9 / (t2 - t0))
            print(
                f"# many_keys iter {it}: put {(t1-t0)*1e3:.0f} ms "
                f"({(t1-t0)/n_keys*1e6:.0f} us/key), "
                f"get {(t2-t1)*1e3:.0f} ms",
                file=sys.stderr,
            )
        # Warm one-sided get leg (the ISSUE 7 acceptance shape): the
        # alternating loop above can never be warm — every put moves the
        # per-entry stamps, so its gets pay the RPC recording pass. The
        # steady-state consumer (an RL trainer pulling weights each
        # iteration) holds REUSED destination buffers and repeats the same
        # covered batch: one recording get re-records plans after the last
        # put (and warms the destination pages), then every timed rep is a
        # zero-RPC stamped scatter-memcpy over the flat stored keys
        # (ts.get_batch — the per-leaf surface the one-sided path serves;
        # the state-dict wrapper's flatten/signature/unflatten walk is
        # measured by the recording leg above). Min-of-reps is the
        # interference-free estimate (median also reported).
        from torchstore_tpu.state_dict_utils import (
            _store_key,
            flatten_state_dict,
        )

        flat, _ = flatten_state_dict(sd)
        dests = {
            _store_key("mk/sd", fk): np.empty_like(v)
            for fk, v in flat.items()
        }
        await ts.get_batch(dict(dests), store_name="bench_keys")
        warm = []
        for _ in range(max(8, iters)):
            t0 = time.perf_counter()
            await ts.get_batch(dict(dests), store_name="bench_keys")
            warm.append(time.perf_counter() - t0)
        assert next(iter(dests.values()))[0] == stamp, "warm get stale data"
        # Re-calibrate memcpy ADJACENT to the warm reps: the acceptance
        # ratio compares two ceiling estimates, and on a shared host the
        # memcpy rate itself drifts 2x between the run-level calibration
        # and this section — a ratio built from different mood windows
        # measures the host, not the store. 64 MB per rep: large enough
        # that src+dst defeat L3 (a cache-resident calibration would
        # overstate the ceiling), small enough to stay quick.
        local_memcpy = calibrate_memcpy_gbps(size_mb=64, reps=3)
        put_s = statistics.median(puts)
        get_s = min(warm)
        get_gbps = total / 1e9 / get_s if get_s > 0 else 0.0
        out = {
            "n_keys": n_keys,
            "key_kb": key_kb,
            "total_mb": round(total / 1e6, 1),
            "many_keys_gbps": round(statistics.median(rates), 3),
            "per_key_put_us": round(put_s / n_keys * 1e6, 2),
            "per_key_get_us": round(get_s / n_keys * 1e6, 2),
            "put_s": round(put_s, 4),
            "get_s": round(get_s, 4),
            "get_s_median": round(statistics.median(warm), 4),
            # The cold (recording) get of the alternating loop above, for
            # the warm-vs-recording contrast.
            "get_s_recording": round(statistics.median(gets), 4),
            # The one-sided acceptance pair: the warm get leg's delivered
            # rate and how far it sits from the host's single-thread
            # memcpy ceiling (lower ratio = closer to memcpy-bound), both
            # measured in the same mood window (local re-calibration).
            "get_gbps": round(get_gbps, 3),
            "host_memcpy_gbps_local": round(local_memcpy, 2),
            "get_memcpy_ratio": round(local_memcpy / get_gbps, 2)
            if get_gbps > 0
            else None,
        }
        print(
            f"# many_keys ({n_keys} x {key_kb:.0f} KB): "
            f"{out['many_keys_gbps']:.3f} GB/s delivered, "
            f"{out['per_key_put_us']:.0f} us/key put, "
            f"{out['per_key_get_us']:.0f} us/key get "
            f"(get {out['get_gbps']:.3f} GB/s, "
            f"{out['get_memcpy_ratio']}x off memcpy)",
            file=sys.stderr,
        )
        return out
    finally:
        await ts.shutdown("bench_keys")


async def ledger_overhead_section(
    n_keys: int = 1024,
    key_kb: float = 4,
    reps: int = 16,
) -> dict:
    """Always-on decision-telemetry cost (ISSUE 10 acceptance): the warm
    zero-RPC many-keys get leg — the store's hottest per-key path — timed
    with the traffic ledger + flight recorder ENABLED vs DISABLED,
    interleaved rep-for-rep so both sides see the same host mood.
    Min-of-reps on each side (interference can only slow a rep down);
    ``overhead_pct`` is the acceptance number (budget: <= 2% at full
    scale; KB-scale smoke runs only assert structure)."""
    import torchstore_tpu as ts
    from torchstore_tpu.observability import ledger as obs_ledger
    from torchstore_tpu.observability import recorder as obs_recorder

    await ts.initialize(
        store_name="bench_ledger",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    led = obs_ledger.ledger()
    rec = obs_recorder.recorder()
    led_was, rec_was = led.enabled, rec.enabled
    try:
        n_elem = max(1, int(key_kb * 1024 // 4))
        items = {
            f"lo/{i}": np.random.rand(n_elem).astype(np.float32)
            for i in range(n_keys)
        }
        total = sum(v.nbytes for v in items.values())
        await ts.put_batch(items, store_name="bench_ledger")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        # Recording get: re-records the one-sided plans so every timed rep
        # below is the pure warm stamped-memcpy shape.
        await ts.get_batch(dict(dests), store_name="bench_ledger")

        async def one_rep() -> float:
            t0 = time.perf_counter()
            await ts.get_batch(dict(dests), store_name="bench_ledger")
            return time.perf_counter() - t0

        on_times: list[float] = []
        off_times: list[float] = []
        for _ in range(max(2, reps)):
            led.set_enabled(True)
            rec.set_enabled(True)
            on_times.append(await one_rep())
            led.set_enabled(False)
            rec.set_enabled(False)
            off_times.append(await one_rep())
        on_s, off_s = min(on_times), min(off_times)
        overhead_pct = (on_s / off_s - 1.0) * 100.0 if off_s > 0 else 0.0
        out = {
            "n_keys": n_keys,
            "key_kb": key_kb,
            "total_mb": round(total / 1e6, 2),
            "reps": max(2, reps),
            "on_us_per_key": round(on_s / n_keys * 1e6, 3),
            "off_us_per_key": round(off_s / n_keys * 1e6, 3),
            # Can be slightly negative under host noise — reported raw so
            # the record is honest about measurement resolution.
            "overhead_pct": round(overhead_pct, 2),
        }
        print(
            f"# ledger_overhead ({n_keys} x {key_kb:.0f} KB warm one-sided "
            f"gets): {out['on_us_per_key']:.2f} us/key telemetry-on vs "
            f"{out['off_us_per_key']:.2f} off ({out['overhead_pct']:+.2f}% "
            "— budget <= 2%)",
            file=sys.stderr,
        )
        return out
    finally:
        # Restore the PRE-SECTION state (an operator running the bench
        # with TORCHSTORE_TPU_LEDGER=0 must not get telemetry force-
        # enabled for every later section).
        led.set_enabled(led_was)
        rec.set_enabled(rec_was)
        await ts.shutdown("bench_ledger")


async def history_overhead_section(
    n_keys: int = 1024,
    key_kb: float = 4,
    reps: int = 16,
) -> dict:
    """Time-series history cost (ISSUE 17 acceptance): the warm zero-RPC
    many-keys get leg timed with the history sampler + trend detectors
    running HOT (50 ms sweeps — 20x the production default, so a real
    deployment sits well inside whatever this measures) vs history
    DISABLED, interleaved rep-for-rep so both sides see the same host
    mood. Min-of-reps on each side; ``overhead_pct`` is the acceptance
    number (budget: <= 1% at full scale; KB-scale smoke runs only assert
    structure)."""
    import os

    import torchstore_tpu as ts
    from torchstore_tpu.observability import history as obs_history

    await ts.initialize(
        store_name="bench_history",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    store = obs_history.series_store()
    was_enabled = store.enabled
    interval_was = os.environ.get(obs_history.ENV_HISTORY_INTERVAL)
    try:
        # The sampler re-reads the interval env every sweep — but it may be
        # mid-way through a sleep at the OLD (1 s default) interval, longer
        # than a KB-scale section's whole life. Restart it so the 50 ms
        # cadence takes effect now: the ON legs then sample (and run every
        # detector) 20x harder than production.
        os.environ[obs_history.ENV_HISTORY_INTERVAL] = "0.05"
        obs_history.stop_history()
        obs_history.maybe_start_history()
        # Prime one sweep synchronously so the rings are warm (and
        # retained_series below is deterministic) before any timed rep.
        store.sample()

        n_elem = max(1, int(key_kb * 1024 // 4))
        items = {
            f"ho/{i}": np.random.rand(n_elem).astype(np.float32)
            for i in range(n_keys)
        }
        total = sum(v.nbytes for v in items.values())
        await ts.put_batch(items, store_name="bench_history")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        # Recording get: re-records the one-sided plans so every timed rep
        # below is the pure warm stamped-memcpy shape.
        await ts.get_batch(dict(dests), store_name="bench_history")

        async def one_rep() -> float:
            t0 = time.perf_counter()
            await ts.get_batch(dict(dests), store_name="bench_history")
            return time.perf_counter() - t0

        on_times: list[float] = []
        off_times: list[float] = []
        for _ in range(max(2, reps)):
            store.set_enabled(True)
            on_times.append(await one_rep())
            store.set_enabled(False)
            off_times.append(await one_rep())
        on_s, off_s = min(on_times), min(off_times)
        overhead_pct = (on_s / off_s - 1.0) * 100.0 if off_s > 0 else 0.0
        out = {
            "n_keys": n_keys,
            "key_kb": key_kb,
            "total_mb": round(total / 1e6, 2),
            "reps": max(2, reps),
            "sample_interval_s": 0.05,
            "retained_series": len(store),
            "on_us_per_key": round(on_s / n_keys * 1e6, 3),
            "off_us_per_key": round(off_s / n_keys * 1e6, 3),
            # Can be slightly negative under host noise — reported raw so
            # the record is honest about measurement resolution.
            "overhead_pct": round(overhead_pct, 2),
        }
        print(
            f"# history_overhead ({n_keys} x {key_kb:.0f} KB warm one-sided "
            f"gets, 50ms sweeps over {out['retained_series']} series): "
            f"{out['on_us_per_key']:.2f} us/key history-on vs "
            f"{out['off_us_per_key']:.2f} off ({out['overhead_pct']:+.2f}% "
            "— budget <= 1%)",
            file=sys.stderr,
        )
        return out
    finally:
        # Restore the PRE-SECTION state (an operator running the bench
        # with TORCHSTORE_TPU_HISTORY=0 must not get sampling force-
        # enabled for every later section).
        if interval_was is None:
            os.environ.pop(obs_history.ENV_HISTORY_INTERVAL, None)
        else:
            os.environ[obs_history.ENV_HISTORY_INTERVAL] = interval_was
        # Re-arm the sampler at the production cadence, then restore the
        # exact pre-section enabled flag.
        obs_history.stop_history()
        obs_history.maybe_start_history()
        store.set_enabled(was_enabled)
        await ts.shutdown("bench_history")


async def streamed_sync_section(
    n_layers: int = 16,
    layer_kb: float = 256,
    train_ms: float = 15.0,
    decode_ms: float = 15.0,
    iters: int = 3,
) -> dict:
    """Layer-streamed weight sync (ISSUE 9): the simulated RL
    train→publish→decode loop, barrier vs streamed.

    Barrier leg: train every layer (simulated compute sleep per layer),
    publish the whole dict, acquire the whole dict, decode every layer —
    iteration time is train + sync + decode with zero overlap. Streamed
    leg: each layer is stream-published the moment it is "trained"
    (``ts.state_dict_stream``), while a concurrent consumer acquires
    layer-by-layer in forward order (``ts.get_state_dict_streamed``) and
    "decodes" each layer as it lands — decode starts long before the last
    layer is published. Emits ``barrier_s``/``streamed_s`` wall clocks,
    ``overlap_ratio`` (fraction of the publish window the acquire ran
    inside — 0 by construction on the barrier path, the ISSUE-9
    acceptance is > 0 here) and ``first_token_after_publish_ms`` (first
    decoded layer relative to publish completion; negative when decode
    beat the seal)."""
    import statistics

    import torchstore_tpu as ts

    train_s = train_ms / 1e3
    decode_s = decode_ms / 1e3
    n_elem = max(1, int(layer_kb * 1024 // 4))
    await ts.initialize(
        store_name="bench_stream",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        layers = {
            str(i): np.random.rand(n_elem).astype(np.float32)
            for i in range(n_layers)
        }
        order = [f"layers/{i}" for i in range(n_layers)]
        barrier_walls, streamed_walls = [], []
        overlaps, ftap_s, ftap_b = [], [], []
        for it in range(iters):
            stamp = float(it + 1)
            # ---- barrier leg --------------------------------------------
            t0 = time.perf_counter()
            for i in range(n_layers):
                await asyncio.sleep(train_s)
                layers[str(i)][0] = stamp
            await ts.put_state_dict(
                "st/sd", {"layers": layers}, store_name="bench_stream"
            )
            t_pub_end = time.perf_counter()
            out = await ts.get_state_dict("st/sd", store_name="bench_stream")
            first_token = None
            for i in range(n_layers):
                assert out["layers"][str(i)][0] == stamp, "barrier stale"
                await asyncio.sleep(decode_s)
                if first_token is None:
                    first_token = time.perf_counter()
            barrier_walls.append(time.perf_counter() - t0)
            ftap_b.append((first_token - t_pub_end) * 1e3)

            # ---- streamed leg -------------------------------------------
            stamp = stamp + 0.5
            marks: dict = {}

            async def publisher():
                stream = ts.state_dict_stream(
                    "st/sds", store_name="bench_stream"
                )
                await stream.begin()
                marks["pub_begin"] = time.perf_counter()
                for i in range(n_layers):
                    await asyncio.sleep(train_s)
                    layers[str(i)][0] = stamp
                    await stream.put({"layers": {str(i): layers[str(i)]}})
                await stream.seal()
                marks["pub_end"] = time.perf_counter()

            async def on_layer(fk, v):
                marks.setdefault("first_serve", time.perf_counter())
                assert np.asarray(v)[0] == stamp, f"streamed stale {fk}"
                await asyncio.sleep(decode_s)
                marks.setdefault("first_token", time.perf_counter())

            t0 = time.perf_counter()
            _, sd = await asyncio.gather(
                publisher(),
                ts.get_state_dict_streamed(
                    "st/sds",
                    key_order=order,
                    on_layer=on_layer,
                    wait_for_stream_s=60,
                    timeout=300,
                    store_name="bench_stream",
                ),
            )
            t_end = time.perf_counter()
            for i in range(n_layers):
                assert sd["layers"][str(i)][0] == stamp, "streamed mixed"
            streamed_walls.append(t_end - t0)
            pub_span = max(1e-9, marks["pub_end"] - marks["pub_begin"])
            overlap = max(
                0.0,
                min(marks["pub_end"], t_end)
                - max(marks["pub_begin"], marks["first_serve"]),
            )
            overlaps.append(overlap / pub_span)
            ftap_s.append((marks["first_token"] - marks["pub_end"]) * 1e3)
            print(
                f"# streamed_sync iter {it}: barrier {barrier_walls[-1]*1e3:.0f} ms, "
                f"streamed {streamed_walls[-1]*1e3:.0f} ms, "
                f"overlap {overlaps[-1]:.2f}, "
                f"first token {ftap_s[-1]:+.0f} ms after publish "
                f"(barrier {ftap_b[-1]:+.0f} ms)",
                file=sys.stderr,
            )
        barrier_s = statistics.median(barrier_walls)
        streamed_s = statistics.median(streamed_walls)
        out = {
            "n_layers": n_layers,
            "layer_kb": layer_kb,
            "train_ms": train_ms,
            "decode_ms": decode_ms,
            "barrier_s": round(barrier_s, 4),
            "streamed_s": round(streamed_s, 4),
            "wall_clock_win_s": round(barrier_s - streamed_s, 4),
            "speedup": round(barrier_s / streamed_s, 3)
            if streamed_s > 0
            else None,
            # Fraction of the publish window the acquire overlapped (the
            # ISSUE-9 acceptance: > 0, i.e. sync hides under compute).
            "overlap_ratio": round(statistics.median(overlaps), 3),
            # First decoded layer relative to publish completion: negative
            # = decode beat the seal (the pipeline's whole point).
            "first_token_after_publish_ms": round(
                statistics.median(ftap_s), 1
            ),
            "barrier_first_token_after_publish_ms": round(
                statistics.median(ftap_b), 1
            ),
        }
        print(
            f"# streamed_sync ({n_layers} x {layer_kb:.0f} KB, "
            f"{train_ms:.0f}/{decode_ms:.0f} ms train/decode per layer): "
            f"barrier {barrier_s*1e3:.0f} ms -> streamed "
            f"{streamed_s*1e3:.0f} ms ({out['speedup']}x), overlap "
            f"{out['overlap_ratio']:.2f}, first token "
            f"{out['first_token_after_publish_ms']:+.0f} ms vs publish end",
            file=sys.stderr,
        )
        return out
    finally:
        await ts.shutdown("bench_stream")


async def delta_sync_section(
    n_tensors: int = 8,
    tensor_kb: float = 4096,
    versions: int = 6,
    churn_frac: float = 0.125,
    dcn_gbps: float = 0.2,
) -> dict:
    """Quantized + delta wire tier (ISSUE 13): a steady-state RL publish
    loop at none / int8_block / int4_block+delta over the BULK (DCN) path,
    low-churn workload (``churn_frac`` of tensors move per step, the rest
    are frozen — the regime delta encoding exists for).

    ``dcn_gbps`` emulates the cross-host link this transport targets
    (TORCHSTORE_TPU_BULK_EMULATE_GBPS pacing on every payload frame, both
    directions): on loopback the wire is memcpy-fast and NOTHING would be
    wire-bound, so the tier's whole effect would vanish into codec CPU
    noise. 0.2 GB/s ~ 1.6 Gbit/s, a conservative per-flow DCN share;
    0 disables the emulation (raw loopback numbers).

    Per leg: ``effective_gbps`` (full-precision dict bytes delivered per
    wall second through publish+acquire — the quantized legs move the same
    LOGICAL bytes over fewer wire bytes), ``wire_compression_ratio``
    (logical/wire from the quant metrics), and ``max_dequant_abs_err``
    (measured against the true weights and ASSERTED under the analytic
    bound: one keyframe step per block — the tier's whole contract)."""
    import os as _os
    import statistics

    import torchstore_tpu as ts
    from torchstore_tpu.observability import metrics as obs_metrics
    from torchstore_tpu.transport import bulk as _bulk

    n_elem = max(1, int(tensor_kb * 1024 // 4))
    churn = max(1, int(round(n_tensors * churn_frac)))
    prev_env = _os.environ.get("TORCHSTORE_TPU_BULK_EMULATE_GBPS")
    prev_pace = None
    if dcn_gbps > 0:
        # Children (volumes) read the env at spawn; this process's sender
        # side adopts it directly.
        _os.environ["TORCHSTORE_TPU_BULK_EMULATE_GBPS"] = str(dcn_gbps)
        prev_pace = _bulk.set_emulated_gbps(dcn_gbps)
    await ts.initialize(
        store_name="bench_delta",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )

    def _quant_counters() -> tuple[float, float]:
        snap = obs_metrics.metrics_snapshot()
        def total(name):
            m = snap.get(name) or {"series": []}
            return float(sum(s["value"] for s in m["series"]))
        return total("ts_quant_bytes_in_total"), total(
            "ts_quant_bytes_wire_total"
        )

    try:
        src = {
            str(i): np.random.randn(n_elem).astype(np.float32)
            for i in range(n_tensors)
        }
        total_bytes = sum(v.nbytes for v in src.values())
        legs = [
            ("none", None, False),
            ("int8_block", "int8_block", False),
            ("int4_delta", "int4_block", True),
        ]
        out: dict = {
            "n_tensors": n_tensors,
            "tensor_kb": tensor_kb,
            "versions": versions,
            "churn_frac": churn_frac,
        }
        gbps_of: dict[str, float] = {}
        for label, quant, delta in legs:
            pub = ts.WeightPublisher(
                f"ds_{label}",
                store_name="bench_delta",
                keep=5,
                transfer_quant=quant,
                delta=delta,
                keyframe_every=4,
            )
            sub = ts.WeightSubscriber(f"ds_{label}", store_name="bench_delta")
            user = {
                str(i): np.zeros(n_elem, np.float32) for i in range(n_tensors)
            }
            walls: list[float] = []
            in0, wire0 = _quant_counters()
            for v in range(versions):
                for i in range(churn):
                    src[str(i)][: n_elem // 4] += np.float32(0.01)
                t0 = time.perf_counter()
                await pub.publish(src)
                sd, _ = await sub.acquire(
                    user_state_dict=user, timeout=120.0
                )
                walls.append(time.perf_counter() - t0)
            in1, wire1 = _quant_counters()
            # Warm median (iter 0 carries plan building + pool warmup).
            warm = walls[1:] or walls
            wall = statistics.median(warm)
            # One publish + one acquire move the dict twice per iteration.
            gbps = 2 * total_bytes / 1e9 / wall
            gbps_of[label] = gbps
            err = max(
                float(np.max(np.abs(user[str(i)] - src[str(i)])))
                for i in range(n_tensors)
            )
            if quant is not None:
                from torchstore_tpu import state_dict_utils as sdu

                qmax = sdu._QMAX[quant]
                # Analytic contract: within one keyframe-step per block
                # (delta skip threshold is HALF a step; shipped residuals
                # add at most half a residual step on top).
                bound = max(
                    float(np.max(np.abs(src[str(i)]))) for i in range(n_tensors)
                ) / qmax + 1e-6
                assert err <= bound, (
                    f"delta_sync[{label}]: dequant err {err} exceeds the "
                    f"analytic bound {bound}"
                )
                compression = (in1 - in0) / max(1.0, wire1 - wire0)
            else:
                assert err == 0.0, f"delta_sync[none]: lossless leg drifted ({err})"
                compression = 1.0
            out[f"delta_{label}_gbps"] = round(gbps, 3)
            out[f"delta_wire_compression_{label}"] = round(compression, 2)
            out[f"delta_max_abs_err_{label}"] = float(err)
            print(
                f"# delta_sync[{label}]: effective {gbps:.2f} GB/s, "
                f"wire compression {compression:.1f}x, max abs err {err:.5f}",
                file=sys.stderr,
            )
        out["delta_speedup_int8_block"] = round(
            gbps_of["int8_block"] / gbps_of["none"], 3
        )
        out["delta_speedup_delta"] = round(
            gbps_of["int4_delta"] / gbps_of["none"], 3
        )
        out["delta_max_abs_err"] = out["delta_max_abs_err_int4_delta"]
        out["dcn_gbps_emulated"] = dcn_gbps
        print(
            f"# delta_sync ({n_tensors} x {tensor_kb:.0f} KB, "
            f"{versions} versions, churn {churn}/{n_tensors}, emulated DCN "
            f"{dcn_gbps} GB/s): none "
            f"{gbps_of['none']:.2f} -> int8_block {gbps_of['int8_block']:.2f} "
            f"({out['delta_speedup_int8_block']}x) -> int4+delta "
            f"{gbps_of['int4_delta']:.2f} GB/s "
            f"({out['delta_speedup_delta']}x)",
            file=sys.stderr,
        )
        return out
    finally:
        await ts.shutdown("bench_delta")
        if dcn_gbps > 0:
            if prev_env is None:
                _os.environ.pop("TORCHSTORE_TPU_BULK_EMULATE_GBPS", None)
            else:
                _os.environ["TORCHSTORE_TPU_BULK_EMULATE_GBPS"] = prev_env
            _bulk.set_emulated_gbps(prev_pace)


async def recovery_section(
    n_keys: int = 64,
    key_kb: float = 256,
    load_hz: float = 20.0,
) -> dict:
    """Time-to-heal after a volume kill under load (ISSUE 6): its own
    3-volume replication-2 fleet publishes a working set, background
    put/get traffic keeps flowing, one data-holding volume is SIGKILLed,
    and the section times the self-healing pipeline:

    - ``detect_s``: kill -> the health supervisor quarantines the volume
      (consecutive-miss heartbeat threshold);
    - ``first_get_s``: kill -> first successful get of a key the dead
      volume held (client replica failover — should be near-instant,
      long before repair);
    - ``rereplicate_s``: kill -> every working-set key restored to full
      replication on healthy volumes (automatic, no ts.repair());
    - ``heal_s``: the total (== rereplicate_s, the last stage to finish).
    """
    import os as _os

    import torchstore_tpu as ts
    from torchstore_tpu import api as ts_api
    from torchstore_tpu.strategy import LocalRankStrategy

    saved = {
        k: _os.environ.get(k)
        for k in (
            "TORCHSTORE_TPU_HEALTH_INTERVAL_S",
            "TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD",
        )
    }
    _os.environ["TORCHSTORE_TPU_HEALTH_INTERVAL_S"] = "0.25"
    _os.environ["TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD"] = "2"
    try:
        await ts.initialize(
            num_storage_volumes=3,
            strategy=LocalRankStrategy(replication=2),
            store_name="bench_recovery",
        )
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    stop_load = asyncio.Event()
    load_task = None
    try:
        client = ts.client("bench_recovery")
        n_elem = max(1, int(key_kb * 1024 // 4))
        keys = [f"rec/w{i}" for i in range(n_keys)]
        total = n_keys * n_elem * 4
        await ts.put_batch(
            {
                k: np.random.rand(n_elem).astype(np.float32)
                for k in keys
            },
            store_name="bench_recovery",
        )
        located = await client.controller.locate_volumes.call_one(keys)
        victim = sorted(located[keys[0]])[0]
        victim_keys = [k for k in keys if victim in located[k]]

        async def load_loop():
            i = 0
            while not stop_load.is_set():
                k = keys[i % n_keys]
                await ts.put(
                    k,
                    np.random.rand(n_elem).astype(np.float32),
                    store_name="bench_recovery",
                )
                await ts.get(k, store_name="bench_recovery")
                i += 1
                await asyncio.sleep(1.0 / load_hz)

        load_task = asyncio.ensure_future(load_loop())
        # Kill the victim the same way tests do: match the mesh process.
        handle = ts_api._stores["bench_recovery"]
        vmap = await client.controller.get_volume_map.call_one()
        target = vmap[victim]["ref"]
        for idx, ref in enumerate(handle.volume_mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = handle.volume_mesh._processes[idx]
                t_kill = time.perf_counter()
                proc.kill()
                proc.join(5)
                break
        else:
            raise AssertionError(f"no process for volume {victim!r}")

        # One deadline for the whole healing pipeline: a self-healing
        # regression must FAIL the section (and the tier-1 smoke test),
        # not hang it until an opaque outer CI timeout.
        deadline = time.monotonic() + 120.0

        # First successful post-kill get of a key the victim held.
        first_get_s = None
        probe = victim_keys[0]
        while first_get_s is None:
            try:
                await ts.get(probe, store_name="bench_recovery")
                first_get_s = time.perf_counter() - t_kill
            except Exception:
                if time.monotonic() > deadline:
                    raise AssertionError(
                        "post-kill get never succeeded (failover broken)"
                    )
                await asyncio.sleep(0.02)

        detect_s = None
        while detect_s is None:
            vh = await ts.volume_health("bench_recovery")
            if vh[victim]["state"] == "quarantined":
                detect_s = time.perf_counter() - t_kill
            elif time.monotonic() > deadline:
                raise AssertionError(
                    "supervisor never quarantined the killed volume"
                )
            else:
                await asyncio.sleep(0.05)

        rereplicate_s = None
        while rereplicate_s is None:
            loc = await client.controller.locate_volumes.call_one(keys)
            if all(
                victim not in loc[k] and len(loc[k]) == 2 for k in keys
            ):
                rereplicate_s = time.perf_counter() - t_kill
            elif time.monotonic() > deadline:
                raise AssertionError("re-replication did not converge")
            else:
                await asyncio.sleep(0.1)

        stop_load.set()
        await asyncio.gather(load_task, return_exceptions=True)
        out = {
            "n_keys": n_keys,
            "key_kb": key_kb,
            "total_mb": round(total / 1e6, 1),
            "victim_keys": len(victim_keys),
            "detect_s": round(detect_s, 3),
            "first_get_s": round(first_get_s, 4),
            "rereplicate_s": round(rereplicate_s, 3),
            "heal_s": round(rereplicate_s, 3),
        }
        print(
            f"# recovery ({n_keys} x {key_kb:.0f} KB, kill under load): "
            f"failover get {out['first_get_s']*1e3:.0f} ms, "
            f"detect {out['detect_s']:.2f} s, "
            f"heal {out['heal_s']:.2f} s",
            file=sys.stderr,
        )
        return out
    finally:
        # A deadline AssertionError above must not leak the load loop into
        # shutdown (puts/gets against a torn-down fleet, unretrieved-task
        # noise bleeding into the next bench section).
        stop_load.set()
        if load_task is not None:
            await asyncio.gather(load_task, return_exceptions=True)
        await ts.shutdown("bench_recovery")


async def fanout_section(
    k_fleets: int = 4,
    n_layers: int = 8,
    layer_kb: float = 128,
    train_ms: float = 10.0,
) -> dict:
    """Broadcast fan-out (ISSUE 11): K simulated generator fleets acquire
    every published version, point-to-point vs relay tree.

    The fleet is K+1 volumes with per-volume emulated hostnames
    (``bench-trainer`` + ``bench-gen{i}``), so ``ts.traffic_matrix()``
    attributes every transfer to real host edges. The point-to-point leg
    has every fleet pull the streamed version straight from the trainer's
    volume (K x dict bytes of trainer-host egress); the tree leg
    subscribes each fleet to the channel's relay tree (root out-degree 1,
    interior fanout 2), so the trainer's volume serves ONE copy however
    large K grows and leaves land their layers from their local relay
    copy as per-hop watermarks arrive.

    Emits ``fanout_egress_ratio`` (tree/p2p trainer-host egress — the
    ISSUE-11 acceptance is <= 1.5/K) and ``fanout_overlap_ratio`` (the
    DEEPEST fleet, >= 2 relay hops from the origin, must still overlap
    the publish window: first layers before the seal)."""
    import os as _os

    import torchstore_tpu as ts
    from torchstore_tpu import relay as relay_mod
    from torchstore_tpu.strategy import LocalRankStrategy
    from torchstore_tpu.weight_channel import WeightPublisher, WeightSubscriber

    saved = _os.environ.get("TORCHSTORE_TPU_RELAY_FANOUT")
    _os.environ["TORCHSTORE_TPU_RELAY_FANOUT"] = "2"
    try:
        await ts.initialize(
            num_storage_volumes=k_fleets + 1,
            strategy=LocalRankStrategy(),
            store_name="bench_fanout",
            volume_env_fn=lambda rank: {
                "TORCHSTORE_TPU_HOSTNAME": (
                    "bench-trainer" if rank == 0 else f"bench-gen{rank}"
                )
            },
        )
    finally:
        if saved is None:
            _os.environ.pop("TORCHSTORE_TPU_RELAY_FANOUT", None)
        else:
            _os.environ["TORCHSTORE_TPU_RELAY_FANOUT"] = saved
    try:
        client = ts.client("bench_fanout")
        n_elem = max(1, int(layer_kb * 1024 // 4))
        layers = {
            str(i): np.random.rand(n_elem).astype(np.float32)
            for i in range(n_layers)
        }
        nbytes = sum(v.nbytes for v in layers.values())
        train_s = train_ms / 1e3
        # With root out-degree 1 and interior fanout 2, volume "2" sits at
        # least two hops deep for any K >= 2 (0 -> 1 -> 2).
        deep = "2" if k_fleets >= 2 else "1"

        async def trainer_egress() -> int:
            matrix = await ts.traffic_matrix("bench_fanout")
            return int(matrix["egress"].get("bench-trainer", 0))

        async def leg(channel: str, relay: bool) -> dict:
            pub = WeightPublisher(channel, store_name="bench_fanout")
            if relay:
                # Register the whole fleet BEFORE the publish so the very
                # first layer already rides the tree.
                for i in range(1, k_fleets + 1):
                    await client.relay_subscribe(channel, volume_id=str(i))
            subs = {
                str(i): WeightSubscriber(
                    channel,
                    store_name="bench_fanout",
                    relay=relay,
                    relay_volume=str(i) if relay else None,
                )
                for i in range(1, k_fleets + 1)
            }
            marks: dict = {}

            async def publish() -> int:
                stream = pub.stream()  # opens + announces on the first put
                marks["pub_begin"] = time.perf_counter()
                for k, v in layers.items():
                    await asyncio.sleep(train_s)
                    await stream.put({k: v})
                version = await stream.seal()
                marks["pub_end"] = time.perf_counter()
                return version

            async def on_layer(fk, v):
                marks.setdefault("first_serve", time.perf_counter())

            async def acquire(vid: str, sub) -> tuple:
                res = await sub.acquire_streamed(
                    on_layer=on_layer if vid == deep else None, timeout=300
                )
                if vid == deep:
                    marks["deep_done"] = time.perf_counter()
                return res

            # Two publish/acquire cycles; the SECOND is the measurement.
            # Iteration 0 pays every cold cost (bulk dials along each tree
            # hop, subscriber plan warmup) — the RL steady state the
            # section characterizes republishes every step, so egress and
            # overlap are read from a warm cycle, exactly like the other
            # warm-leg sections.
            version = None
            egress = 0
            for cycle in range(2):
                marks.clear()
                e0 = await trainer_egress()
                results = await asyncio.gather(
                    publish(),
                    *(acquire(vid, sub) for vid, sub in subs.items()),
                )
                version = results[0]
                for sd_, v in results[1:]:
                    assert v == version, "fleet acquired a different version"
                    for k, arr in layers.items():
                        assert np.array_equal(np.asarray(sd_[k]), arr), (
                            f"fleet served wrong bytes for layer {k}"
                        )
                egress = await trainer_egress() - e0
            pub_span = max(1e-9, marks["pub_end"] - marks["pub_begin"])
            overlap = max(
                0.0,
                min(marks["pub_end"], marks.get("deep_done", 0.0))
                - max(marks["pub_begin"], marks.get("first_serve", 1e18)),
            )
            return {
                "egress_bytes": egress,
                "overlap_ratio": overlap / pub_span,
                "version": version,
            }

        p2p = await leg("fan_p2p", relay=False)
        tree = await leg("fan_tree", relay=True)

        topo = await ts.relay_topology("bench_fanout")
        run_views = topo.get("fan_tree", {}).get("runs", {})
        run_view = run_views.get(f"fan_tree/v{tree['version']}", {})
        hops = relay_mod.depth_of(
            run_view.get("parents", {}), run_view.get("root", "0"), deep
        )
        ratio = (
            tree["egress_bytes"] / p2p["egress_bytes"]
            if p2p["egress_bytes"]
            else None
        )
        out = {
            "k_fleets": k_fleets,
            "n_layers": n_layers,
            "layer_kb": layer_kb,
            "dict_mb": round(nbytes / 1e6, 3),
            "p2p_trainer_egress_mb": round(p2p["egress_bytes"] / 1e6, 4),
            "tree_trainer_egress_mb": round(tree["egress_bytes"] / 1e6, 4),
            # ISSUE-11 acceptance: tree/p2p trainer-host egress <= 1.5/K.
            "fanout_egress_ratio": (
                None if ratio is None else round(ratio, 4)
            ),
            "egress_bound": round(1.5 / k_fleets, 4),
            # The deepest fleet's overlap with the publish window (> 0 =
            # first layers landed through >= 2 relay hops before the seal).
            "fanout_overlap_ratio": round(tree["overlap_ratio"], 3),
            "p2p_overlap_ratio": round(p2p["overlap_ratio"], 3),
            "relay_hops": hops,
        }
        print(
            f"# fanout (K={k_fleets} fleets, {n_layers} x {layer_kb:.0f} KB): "
            f"trainer egress p2p {out['p2p_trainer_egress_mb']:.3f} MB -> "
            f"tree {out['tree_trainer_egress_mb']:.3f} MB "
            f"(ratio {out['fanout_egress_ratio']}, bound "
            f"{out['egress_bound']}); deep fleet {hops} hop(s), overlap "
            f"{out['fanout_overlap_ratio']:.2f}",
            file=sys.stderr,
        )
        if ratio is not None and ratio > 1.5 / k_fleets:
            print(
                "# fanout WARN: tree egress ratio above the 1.5/K bound — "
                "relay hops are not absorbing the fan-out",
                file=sys.stderr,
            )
        return out
    finally:
        await ts.shutdown("bench_fanout")


async def cross_host_section(
    k_hosts: int = 4,
    layer_kb: float = 4096,
    rounds: int = 5,
    emulate_gbps: float = 1.0,
) -> dict:
    """Cross-host one-sided tier (ISSUE 20): emulated ``k_hosts``-host
    topology (``TORCHSTORE_TPU_HOSTNAME`` overlays) over a paced DCN
    (``TORCHSTORE_TPU_BULK_EMULATE_GBPS``), measuring the two tentpole
    claims against their pull-side baselines:

    - **Push-on-publish first-layer latency**: after each publish, the
      subscribed client's get serves from the push-staged arena (local
      memcpy) vs the doorbell-pull leg that pays the paced wire at read
      time. Acceptance: ``push_speedup`` >= 2x.
    - **Metadata-relay egress**: ``k_hosts`` mirrors fan through the relay
      tree (root out-degree 1), so the index host serves ONE image copy
      per update however many hosts subscribe. Acceptance:
      ``meta_egress_ratio`` (root egress / fleet-delivered bytes, the
      all-subscribers-pull baseline) <= 1.5 / k_hosts.
    - **Zero metadata RPCs warm**: a block of warm remote gets moves no
      ``traffic_matrix()["metadata"]["rpcs"]`` cell (the scrape's own
      "stats" RPC excepted) — locations, epochs, and write-gen validation
      all serve from the mirrored stamped replica."""
    import os as _os

    import torchstore_tpu as ts
    from torchstore_tpu.metadata import mirror as mirror_mod
    from torchstore_tpu.transport import bulk as bulk_mod

    saved_env = {
        k: _os.environ.get(k)
        for k in (
            "TORCHSTORE_TPU_HOSTNAME",
            "TORCHSTORE_TPU_BULK_EMULATE_GBPS",
            "TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS",
        )
    }
    _os.environ["TORCHSTORE_TPU_HOSTNAME"] = "xh-vol"
    _os.environ["TORCHSTORE_TPU_BULK_EMULATE_GBPS"] = str(emulate_gbps)
    _os.environ["TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS"] = "10"
    extra_mirrors: list = []
    try:
        await ts.initialize(
            store_name="bench_xhost",
            strategy=ts.SingletonStrategy(default_transport_type="bulk"),
        )
        # The bench process itself must NOT pace: the client-side put is
        # the publisher's local hand-off; only the volume's serves (push
        # frames, doorbell replies) model the DCN hop under measurement.
        bulk_mod.set_emulated_gbps(0)
        client = ts.client("bench_xhost")
        coordinator = client._controller.coordinator
        topo = await coordinator.metadata_topology.call_one()
        feed = topo.get("meta_feed")
        assert feed, "metadata feed did not start"

        # k_hosts - 1 extra subscriber hosts + the measuring client: the
        # controller fans them through the relay tree (root serves ONE).
        for i in range(1, k_hosts):
            _os.environ["TORCHSTORE_TPU_HOSTNAME"] = f"xh-sub{i}"
            m = mirror_mod.MetadataMirror(
                coordinator, (feed["host"], feed["port"])
            )
            await m.start()
            assert await m.wait_ready(10.0), f"mirror xh-sub{i} never ready"
            extra_mirrors.append(m)
        _os.environ["TORCHSTORE_TPU_HOSTNAME"] = "xh-client"
        await client._load_volumes()
        router = client._controller
        assert router._mirror is not None, "client mirror did not arm"

        n_elem = max(1, int(layer_kb * 1024 // 4))
        key = "xh/layer"
        await ts.put(
            key, np.zeros(n_elem, np.float32), store_name="bench_xhost"
        )
        # Cold get: doorbell-plan registration + push subscription.
        await ts.get(key, store_name="bench_xhost")
        deadline = time.monotonic() + 10.0
        while router.stamped_locate([key]) is None:
            assert time.monotonic() < deadline, "mirror never caught up"
            await asyncio.sleep(0.01)
        cache = client._ctx.get_cache(bulk_mod.BulkClientCache)

        def _staged_gen() -> int:
            gens = [
                max(e["gens"])
                for e in cache.push_staging.values()
                if e.get("gens")
            ]
            return max(gens, default=-1)

        def _meta_flow() -> tuple[int, int]:
            # Every mirror (the client's + the K-1 extras) lives in THIS
            # process, so the local ledger holds the whole fleet's feed
            # ingress cells WITH the transport dimension the folded
            # matrix drops: total = fleet-delivered image bytes (the
            # all-subscribers-pull baseline), root = the slice the index
            # host actually served (everything else rode subscriber->
            # subscriber relay hops).
            from torchstore_tpu.observability import ledger as obs_ledger

            root = total = 0
            for cell in obs_ledger.snapshot()["cells"]:
                if cell["transport"] != mirror_mod.MIRROR_TRANSPORT:
                    continue
                total += cell["bytes"]
                if cell["peer_host"] == "xh-vol":
                    root += cell["bytes"]
            return root, total

        root0, total0 = _meta_flow()

        async def timed_get(expect: float) -> float:
            t0 = time.perf_counter()
            got = await ts.get(key, store_name="bench_xhost")
            dt = time.perf_counter() - t0
            arr = np.asarray(got)
            assert arr[0] == expect and arr[-1] == expect, "wrong bytes"
            return dt

        # Push leg: publish, wait for the watermark-time push to stage,
        # then read — the wire crossing happened BEFORE the read.
        push_lat: list[float] = []
        for r in range(rounds):
            fill = float(r + 1)
            seen = _staged_gen()
            await ts.put(
                key, np.full(n_elem, fill, np.float32),
                store_name="bench_xhost",
            )
            deadline = time.monotonic() + 10.0
            while _staged_gen() <= seen:
                assert (
                    time.monotonic() < deadline
                ), "push session never staged the publish"
                await asyncio.sleep(0.005)
            push_lat.append(await timed_get(fill))

        # Zero-metadata-RPC warm block (no puts interleaved).
        meta0 = (await ts.traffic_matrix("bench_xhost"))["metadata"]
        for _ in range(3):
            await timed_get(float(rounds))
        meta1 = (await ts.traffic_matrix("bench_xhost"))["metadata"]
        rpc_moves = {
            op: meta1["rpcs"].get(op, 0) - meta0["rpcs"].get(op, 0)
            for op in set(meta1["rpcs"]) | set(meta0["rpcs"])
        }
        rpc_moves = {
            op: n for op, n in rpc_moves.items() if n and op != "stats"
        }

        # Doorbell-pull baseline: same publishes, but the read pays the
        # paced wire (push serving disabled at read time).
        _os.environ["TORCHSTORE_TPU_PUSH_SESSIONS"] = "0"
        try:
            bell_lat: list[float] = []
            for r in range(rounds):
                fill = float(rounds + r + 1)
                await ts.put(
                    key, np.full(n_elem, fill, np.float32),
                    store_name="bench_xhost",
                )
                bell_lat.append(await timed_get(fill))
        finally:
            _os.environ.pop("TORCHSTORE_TPU_PUSH_SESSIONS", None)

        root1, total1 = _meta_flow()
        meta_total = max(1, total1 - total0)
        meta_root = root1 - root0
        push_p50 = float(np.median(push_lat))
        bell_p50 = float(np.median(bell_lat))
        out = {
            "k_hosts": k_hosts,
            "layer_kb": layer_kb,
            "emulate_gbps": emulate_gbps,
            "push_first_layer_ms": round(push_p50 * 1e3, 3),
            "doorbell_first_layer_ms": round(bell_p50 * 1e3, 3),
            # ISSUE-20 acceptance: >= 2x lower first-layer latency.
            "push_speedup": round(bell_p50 / max(push_p50, 1e-9), 3),
            "meta_delivered_mb": round(meta_total / 1e6, 4),
            # ISSUE-20 acceptance: <= 1.5 / k_hosts of the all-subscribers-
            # pull baseline (every mirror pulling straight from the root).
            "meta_egress_ratio": round(meta_root / meta_total, 4),
            "meta_egress_bound": round(1.5 / k_hosts, 4),
            "warm_metadata_rpcs": rpc_moves,
            "push_serves": int(bulk_mod._PUSH_SERVES.total()),
        }
        print(
            f"# cross_host (K={k_hosts} hosts, {layer_kb:.0f} KB layers, "
            f"{emulate_gbps} GB/s emulated): first layer push "
            f"{out['push_first_layer_ms']:.2f} ms vs doorbell "
            f"{out['doorbell_first_layer_ms']:.2f} ms "
            f"(speedup {out['push_speedup']}x); meta egress ratio "
            f"{out['meta_egress_ratio']} (bound {out['meta_egress_bound']}); "
            f"warm metadata RPCs {rpc_moves or 'none'}",
            file=sys.stderr,
        )
        if rpc_moves:
            print(
                "# cross_host WARN: warm remote gets issued metadata RPCs — "
                "the mirrored stamped plane is not serving the warm path",
                file=sys.stderr,
            )
        if out["push_speedup"] < 2.0:
            print(
                "# cross_host WARN: push-on-publish first-layer speedup "
                "below the 2x acceptance bound",
                file=sys.stderr,
            )
        if out["meta_egress_ratio"] > out["meta_egress_bound"]:
            print(
                "# cross_host WARN: metadata relay egress above the 1.5/K "
                "bound — the feed tree is not absorbing the fan-out",
                file=sys.stderr,
            )
        return out
    finally:
        for m in extra_mirrors:
            m.close()
        await ts.shutdown("bench_xhost")
        for k, v in saved_env.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        bulk_mod.set_emulated_gbps(None)


async def capacity_section(
    n_versions: int = 8,
    n_keys: int = 16,
    key_kb: float = 256,
    hot_version: int = 1,
    warm_reps: int = 8,
) -> dict:
    """Tiered capacity (ISSUE 12): the working set exceeds the memory-tier
    pool budget 2x, one version is pinned hot by a cohort lease, and the
    spill writer demotes the cold rest to disk.

    Its own fleet with the tier knobs set so ``n_versions`` published
    channel versions total exactly TWICE the configured pool budget. After
    a deterministic ``ts.tier_sweep()``:

    - ``warm_get_after_spill_us``: per-key warm get of the LEASED version
      (min-of-reps, one-sided stamped reads) — the acceptance is that warm
      leased-version latency is unchanged by the spill tier, measured with
      ``warm_get_rpcs`` (volume get-RPC delta across the warm reps; 0 =
      the warm path stayed zero-RPC);
    - ``fault_in_p50_ms``: per-key first-get latency of cold SPILLED
      versions — the disk->memory promotion through the normal transport
      ladder (no new per-get RPC: the fault-in rides the same get the
      one-sided miss path already falls back to);
    - ``spilled_bytes_ratio``: spilled / (resident + spilled) volume bytes
      after the sweep (> 0.5 by construction when the policy works).
    """
    import os as _os
    import shutil as _shutil
    import statistics
    import tempfile as _tempfile

    import torchstore_tpu as ts

    n_elem = max(1, int(key_kb * 1024 // 4))
    version_bytes = n_keys * n_elem * 4
    # Working set (n_versions x version_bytes) = 2x the pool budget.
    budget = max(1, n_versions * version_bytes // 2)
    tier_dir = _tempfile.mkdtemp(prefix="ts_bench_tier_")
    knobs = {
        "TORCHSTORE_TPU_TIER_ENABLED": "1",
        "TORCHSTORE_TPU_TIER_DIR": tier_dir,
        "TORCHSTORE_TPU_TIER_BUDGET_BYTES": str(budget),
        "TORCHSTORE_TPU_TIER_HIGH_PCT": "0.70",
        "TORCHSTORE_TPU_TIER_LOW_PCT": "0.40",
        # Deterministic: the section triggers its own sweep.
        "TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S": "0",
    }
    saved = {k: _os.environ.get(k) for k in knobs}
    _os.environ.update(knobs)
    try:
        await ts.initialize(
            store_name="bench_capacity",
            strategy=ts.SingletonStrategy(default_transport_type="shm"),
        )
    finally:
        for k, v in saved.items():
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
    lease = None
    client = ts.client("bench_capacity")
    try:
        pub = ts.WeightPublisher(
            "cap", store_name="bench_capacity", keep=n_versions + 1
        )
        for v in range(n_versions):
            await pub.publish(
                {
                    f"w{i}": np.full(n_elem, float(v), np.float32)
                    for i in range(n_keys)
                }
            )
        lease = await client.lease_acquire(
            "bench-hot", "cap", hot_version, ttl_s=600
        )
        assert lease["resident_keys"] > 0, lease
        await client.tier_sweep()
        vid = sorted(client._volume_refs)[0]
        vstats = await client._volume_refs[vid].actor.stats.call_one()
        tier = vstats.get("tier") or {}
        resident = int(tier.get("resident_bytes", 0))
        spilled = int(tier.get("spilled_bytes", 0))
        spilled_ratio = spilled / max(1, resident + spilled)
        catalog = await ts.version_catalog("cap", store_name="bench_capacity")
        hot_rec = catalog["cap"][hot_version]
        assert hot_rec["spilled_keys"] == 0, (
            f"leased-hot v{hot_version} was demoted: {hot_rec}"
        )

        def _get_rpcs(stats: dict) -> float:
            series = (
                (stats.get("metrics") or {})
                .get("ts_volume_get_ops_total", {})
                .get("series", [])
            )
            return sum(s["value"] for s in series)

        # Warm leg: the leased-hot version through reused destinations —
        # one recording get re-records the one-sided plans, then every
        # timed rep is a zero-RPC stamped read.
        hot_keys = [f"cap/v{hot_version}/w{i}" for i in range(n_keys)]
        dests = {sk: np.empty(n_elem, np.float32) for sk in hot_keys}
        await ts.get_batch(dict(dests), store_name="bench_capacity")
        rpcs0 = _get_rpcs(
            await client._volume_refs[vid].actor.stats.call_one()
        )
        warm = []
        for _ in range(max(2, warm_reps)):
            t0 = time.perf_counter()
            await ts.get_batch(dict(dests), store_name="bench_capacity")
            warm.append(time.perf_counter() - t0)
        assert float(next(iter(dests.values()))[0]) == float(hot_version)
        warm_rpcs = (
            _get_rpcs(await client._volume_refs[vid].actor.stats.call_one())
            - rpcs0
        )
        # Fault-in leg: first gets of cold SPILLED versions promote each
        # key from disk through the normal get path.
        cold = sorted(
            v
            for v, rec in catalog["cap"].items()
            if rec["keys"] and rec["spilled_keys"] == rec["keys"]
        )
        fault_ms: list[float] = []
        for v in cold[:2]:
            for i in range(n_keys):
                t0 = time.perf_counter()
                arr = await ts.get(
                    f"cap/v{v}/w{i}", store_name="bench_capacity"
                )
                fault_ms.append((time.perf_counter() - t0) * 1e3)
                assert float(np.asarray(arr)[0]) == float(v), (
                    f"fault-in served wrong generation for v{v}/w{i}"
                )
        out = {
            "n_versions": n_versions,
            "n_keys": n_keys,
            "key_kb": key_kb,
            "working_set_mb": round(n_versions * version_bytes / 1e6, 2),
            "budget_mb": round(budget / 1e6, 2),
            "resident_bytes": resident,
            "spilled_bytes": spilled,
            "spilled_bytes_ratio": round(spilled_ratio, 3),
            "warm_get_after_spill_us": round(
                min(warm) / n_keys * 1e6, 2
            ),
            "warm_get_rpcs": warm_rpcs,
            "fault_in_p50_ms": round(statistics.median(fault_ms), 3),
            "fault_in_keys": len(fault_ms),
            "cold_versions_measured": cold[:2],
        }
        print(
            f"# capacity ({out['working_set_mb']:.1f} MB working set vs "
            f"{out['budget_mb']:.1f} MB budget): spilled ratio "
            f"{out['spilled_bytes_ratio']:.2f}, warm leased get "
            f"{out['warm_get_after_spill_us']:.1f} us/key "
            f"({warm_rpcs:+.0f} get RPCs across warm reps), fault-in p50 "
            f"{out['fault_in_p50_ms']:.2f} ms/key over {len(fault_ms)} "
            "cold key(s)",
            file=sys.stderr,
        )
        if warm_rpcs:
            print(
                "# capacity WARN: warm leased-version reps issued get "
                "RPCs — the zero-RPC one-sided path regressed",
                file=sys.stderr,
            )
        return out
    finally:
        if lease is not None:
            try:
                await client.lease_release(lease["lease_id"])
            except Exception:  # noqa: BLE001 - teardown clears leases too
                pass
        await ts.shutdown("bench_capacity")
        _shutil.rmtree(tier_dir, ignore_errors=True)



def _meta_driver(env: dict, store_name: str, n_logical: int,
                 duration_s: float, seed: int, conn) -> None:
    """Driver PROCESS for the metadata_scale section: ``n_logical``
    concurrent logical clients hammering the metadata plane with the warm
    locate/notify/stream-poll mix, for ``duration_s``. Runs with stamped
    metadata DISABLED so every op is a real controller RPC — the section
    measures how the RPC plane scales with shard count; the one-sided path
    (whose throughput is a memcpy, not a queue) is measured by its
    zero-RPC assertions in tier-1 instead. Reports op counts via
    ``conn``."""
    import asyncio as _asyncio
    import os as _os
    import time as _time

    # ``env`` is the COMPLETE framework environment for this driver: the
    # forkserver's snapshot can carry stale TORCHSTORE_TPU_* values from
    # whatever test/store first spawned an actor (e.g. an auth secret set
    # since unset — the driver would then demand a challenge the fleet
    # never issues). Same rule as runtime.actors._child_main.
    for key in list(_os.environ):
        if key.startswith("TORCHSTORE_TPU_") and key not in env:
            del _os.environ[key]
    _os.environ.update(env)
    _os.environ["TORCHSTORE_TPU_META_STAMPED"] = "0"
    _os.environ["TORCHSTORE_TPU_LOG_LEVEL"] = "ERROR"
    from torchstore_tpu import config as _config_mod

    _config_mod._default_config = None

    async def _drive() -> dict:
        import numpy as _np

        import torchstore_tpu as _ts
        from torchstore_tpu.transport.types import Request as _Request

        client = _ts.client(store_name)
        await client._ensure_setup()
        router = client.controller
        stream_key = f"meta_bench/{seed}"
        version = await router.stream_begin.call_one(stream_key)
        counts = {"locate": 0, "notify": 0, "poll": 0}
        # The counting window opens HERE, after boot/attach/seed: the
        # section divides by the drivers' own measured windows, so
        # process-spawn and import time never deflate the gated ops/s.
        t_start = _time.monotonic()
        stop_at = t_start + duration_s

        # The hot loop fires PRE-RESOLVED raw endpoint RPCs: the owning
        # actor is computed once per key (the router's shard_of math,
        # hoisted), so each counted op is exactly one RPC on one
        # controller queue in BOTH topologies and the measurement is the
        # metadata ACTORS' service capacity — not the driver's per-op
        # client bookkeeping, which is what saturates first on a single
        # box once four shards outrun it.
        from torchstore_tpu.metadata import shard_of as _shard_of

        shard_refs = list(router.shard_refs)
        n_shards = max(1, len(shard_refs))

        def _owner(key: str):
            if not shard_refs:
                return router.coordinator
            return shard_refs[_shard_of(key, n_shards)]

        async def one_client(idx: int) -> None:
            keys = [f"meta/{seed}/{idx}/{i}" for i in range(16)]
            metas = [
                _Request.from_tensor(k, _np.zeros((8,), _np.float32)).meta_only()
                for k in keys
            ]
            vid = next(iter(client._volume_refs))
            # Seed once THROUGH THE ROUTER (structural notify + the stream
            # watermark protocol, so later polls return instantly); the
            # loop then re-notifies the SAME metas — the steady-state
            # publish shape (no epoch churn, no per-iteration watermark
            # hop). The warm mix is locate-heavy with SINGLE-KEY locates —
            # the many-small-clients shape this plane exists for
            # ("millions of users" each resolving their own keys).
            await router.notify_put_batch.call_one(
                metas, vid, watermark=(stream_key, version)
            )
            locate_eps = [_owner(k).locate_volumes for k in keys]
            notify_eps = [_owner(m.key).notify_put_batch for m in metas]
            poll_ep = router.coordinator.wait_for_stream
            i = 0
            while _time.monotonic() < stop_at:
                await notify_eps[i % len(metas)].call_one(
                    [metas[i % len(metas)]], vid
                )
                counts["notify"] += 1
                for _ in range(12):
                    await locate_eps[i % len(keys)].call_one(
                        [keys[i % len(keys)]]
                    )
                    i += 1
                    counts["locate"] += 1
                await poll_ep.call_one(stream_key, version, 0, 5.0)
                counts["poll"] += 1

        await _asyncio.gather(*(one_client(i) for i in range(n_logical)))
        counts["window_s"] = _time.monotonic() - t_start
        return counts

    counts = _asyncio.run(_drive())
    conn.send(counts)
    conn.close()


async def metadata_scale_section(
    shard_counts: tuple = (1, 4),
    n_drivers: int = 16,
    n_logical: int = 6,
    duration_s: float = 3.0,
    n_volumes: int = 2,
) -> dict:
    """Scale-out metadata plane (ISSUE 14 / ROADMAP items 4+6): hundreds
    of logical clients' locate/notify/stream-poll load against 1 vs N
    controller shards.

    Each leg boots its own fleet (``controller_shards=k``), then spawns
    ``n_drivers`` OS processes x ``n_logical`` asyncio clients each —
    enough concurrent RPC pressure to saturate a single controller actor's
    queue — and counts completed metadata ops over a fixed window. The
    drivers disable stamped metadata so every op is a real RPC: the
    section measures the RPC plane's horizontal scaling (the acceptance
    is >= 2.5x from 1 -> 4 shards); the zero-RPC one-sided path is
    asserted separately in tier-1 via ``ts.traffic_matrix()["metadata"]``.

    Emits ``metadata_scale_x`` (ops/s at max shards / ops/s at 1 shard)
    and per-leg ``ops_per_s``."""
    import os as _os

    import torchstore_tpu as ts
    from torchstore_tpu.runtime.actors import _mp_context

    legs: dict = {}
    for shards in shard_counts:
        store = f"bench_meta{shards}"
        await ts.initialize(
            num_storage_volumes=n_volumes,
            store_name=store,
            controller_shards=shards,
        )
        try:
            env = {
                k: v
                for k, v in _os.environ.items()
                if k.startswith("TORCHSTORE_TPU_")
            }
            ctx = _mp_context()
            procs = []
            for d in range(n_drivers):
                parent, child = ctx.Pipe()
                proc = ctx.Process(
                    target=_meta_driver,
                    args=(env, store, n_logical, duration_s, d, child),
                    daemon=True,
                    name=f"ts-metabench-{d}",
                )
                proc.start()
                child.close()
                procs.append((proc, parent))
            totals = {"locate": 0, "notify": 0, "poll": 0}
            windows = []
            failed = 0
            for proc, parent in procs:
                try:
                    if parent.poll(duration_s + 120):
                        counts = parent.recv()
                        windows.append(counts.pop("window_s", duration_s))
                        for k, v in counts.items():
                            totals[k] += v
                    else:
                        failed += 1
                except (EOFError, OSError):
                    failed += 1
            # The rate divides by the drivers' own measured op windows
            # (max across drivers — they run concurrently), never the
            # spawn/import/attach time that precedes them.
            wall = max(windows) if windows else duration_s
            for proc, _ in procs:
                proc.join(10)
                if proc.is_alive():
                    proc.terminate()
            ops = sum(totals.values())
            legs[str(shards)] = {
                "shards": shards,
                "ops": ops,
                "ops_per_s": round(ops / max(wall, 1e-9), 1),
                "wall_s": round(wall, 3),
                "mix": totals,
                "drivers": n_drivers,
                "logical_clients": n_drivers * n_logical,
                "failed_drivers": failed,
            }
            print(
                f"# metadata_scale: {shards} shard(s) -> "
                f"{legs[str(shards)]['ops_per_s']:.0f} metadata ops/s "
                f"({n_drivers * n_logical} logical clients)",
                file=sys.stderr,
            )
        finally:
            await ts.shutdown(store)
    lo = legs[str(shard_counts[0])]["ops_per_s"]
    hi = legs[str(shard_counts[-1])]["ops_per_s"]
    return {
        "legs": legs,
        "metadata_ops_per_s_1shard": lo,
        "metadata_ops_per_s_sharded": hi,
        "metadata_scale_x": round(hi / max(lo, 1e-9), 3),
        "shard_counts": list(shard_counts),
    }


async def fleet_scale_section(
    n_drivers: int = 8,
    n_logical: int = 128,
    duration_s: float = 4.0,
    n_volumes: int = 4,
    value_kb: float = 4.0,
    shared_keys: int = 128,
    # Per-client baseline rate. Production generators poll weights at
    # ~single-digit Hz; 1 Hz x 1024 clients (bursting to 4x) sustains
    # ~1.5k ops/s on this box with p99 ~110-240 ms (the spread is the
    # parent's concurrent under-load telemetry measurement contending
    # for the same cores). Driving every client at RPC-benchmark rates
    # would measure event-loop saturation collapse, not the store.
    rate_hz: float = 1.0,
    # The pass/fail SLO: sub-second p99 while 1k clients hammer one
    # shared box, with headroom for host weather (measured p99 110-242
    # ms across runs; collapses land far past this line).
    get_p99_gate_ms: float = 500.0,
    overhead_reps: int = 16,
    overhead_keys: int = 1024,
    overhead_budget_pct: float = 2.0,
    violation_duration_s: float = 1.5,
) -> dict:
    """Fleet-scale load harness (ISSUE 15 / ROADMAP item 6): sustained
    ops/s with p99 under the SLO gate at >= 1k logical clients, asserted.

    Three legs against one multi-volume fleet:

    1. **Gate leg** — ``n_drivers`` OS processes x ``n_logical`` asyncio
       clients (defaults: 8 x 128 = 1024 logical clients) drive a
       bursty get/put mix (``loadgen`` burst pattern) for ``duration_s``;
       the merged report must show ZERO failed drivers, zero op errors,
       and fleet get p99 under ``get_p99_gate_ms`` — the pass/fail line.
       While the storm runs, the PARENT process re-measures the
       ledger+recorder cost on its own warm one-sided get leg
       (interleaved min-of-reps, the ledger_overhead methodology) — the
       <= 2% telemetry budget re-verified UNDER load, asserted.
    2. **Violation leg** — a short rerun with ``shm.landing_stamp``
       armed as a client-scope delay in every driver (the landing-copy
       window of the warm one-sided get) under a deliberately tight GET
       p99 SLO: the merged scoreboard must show the violated SLO naming
       ``landing`` as its dominant stage — the stage-attribution
       acceptance, asserted.

    Emits ``fleet_ops_per_s`` / ``fleet_get_p99_ms`` /
    ``fleet_ledger_overhead_pct`` headline keys (gated by
    bench_compare)."""
    import asyncio as _asyncio

    import torchstore_tpu as ts
    from torchstore_tpu.loadgen import LoadSpec, run_fleet_load
    from torchstore_tpu.observability import ledger as obs_ledger
    from torchstore_tpu.observability import recorder as obs_recorder

    store = "bench_fleet"
    await ts.initialize(num_storage_volumes=n_volumes, store_name=store)
    led = obs_ledger.ledger()
    rec = obs_recorder.recorder()
    led_was, rec_was = led.enabled, rec.enabled
    try:
        gate_spec = LoadSpec(
            store_name=store,
            duration_s=duration_s,
            processes=n_drivers,
            clients_per_process=n_logical,
            pattern={
                "kind": "burst",
                "rate_hz": rate_hz,
                "peak_rate_hz": rate_hz * 4,
                "period_s": max(1.0, duration_s / 3),
                "burst_frac": 0.25,
            },
            rate_hz=rate_hz,
            mix={"get": 0.85, "put": 0.15},
            value_kb=value_kb,
            shared_keys=shared_keys,
            slow_reader_frac=0.05,
            slow_reader_ms=2.0,
            seed=15,
            env={"TORCHSTORE_TPU_SLO_GET_P99_MS": str(get_p99_gate_ms)},
        )
        # The telemetry-budget re-measurement rides INSIDE the load storm:
        # the parent's own warm one-sided leg, ledger+recorder on vs off,
        # interleaved min-of-reps (both modes see the same storm). The
        # working set matches the ledger_overhead section's shape — the
        # <= 2% budget is a per-key amortized figure; the fixed per-batch
        # cost would read as tens of percent on a tiny batch.
        n_elem = max(1, int(value_kb * 1024 // 4))
        own = {
            f"{store}/ov/{i}": np.random.rand(n_elem).astype(np.float32)
            for i in range(overhead_keys)
        }
        await ts.put_batch(own, store_name=store)
        dests = {k: np.empty_like(v) for k, v in own.items()}
        await ts.get_batch(dict(dests), store_name=store)  # record plans

        async def one_rep() -> float:
            t0 = time.perf_counter()
            await ts.get_batch(dict(dests), store_name=store)
            return time.perf_counter() - t0

        async def overhead_under_load() -> dict:
            # Drift-cancelling triples: each rep measures OFF -> ON -> OFF
            # back-to-back (min-of-2 per slot trims upper-tail jitter) and
            # scores the ON slot against the mean of its OFF neighbors, so
            # slow host/storm drift cancels within the triple. The SAME
            # triples yield a NULL contrast (off2 vs off1 — two identical
            # configurations) whose median deviation IS this run's
            # measurement-noise floor: the budget assert widens by exactly
            # that demonstrated noise, so a quiet box enforces the bare
            # <= 2% budget while a storming shared box can't flake the
            # gate — and a real telemetry regression (tens of percent)
            # still fails loudly on either.
            import statistics as _stats

            def toggle(enabled: bool) -> None:
                led.set_enabled(enabled)
                rec.set_enabled(enabled)

            ratios: list[float] = []
            nulls: list[float] = []
            on_times: list[float] = []
            off_times: list[float] = []

            async def slot(enabled: bool) -> float:
                toggle(enabled)
                return min([await one_rep(), await one_rep()])

            toggle(True)
            await one_rep()  # cold rep: plan re-records, pages warm
            for _ in range(max(4, overhead_reps)):
                off1 = await slot(False)
                on_s = await slot(True)
                off2 = await slot(False)
                on_times.append(on_s)
                off_times.extend((off1, off2))
                base = (off1 + off2) / 2
                if base > 0:
                    ratios.append(on_s / base)
                if off1 > 0:
                    nulls.append(off2 / off1)
                await _asyncio.sleep(0.02)  # let driver traffic breathe
            toggle(True)
            overhead_pct = (
                (_stats.median(ratios) - 1.0) * 100.0 if ratios else 0.0
            )
            noise_floor_pct = (
                abs(_stats.median(nulls) - 1.0) * 100.0 if nulls else 0.0
            )
            return {
                "on_us_per_key": round(min(on_times) / len(own) * 1e6, 3),
                "off_us_per_key": round(
                    min(off_times) / len(own) * 1e6, 3
                ),
                "overhead_pct": round(overhead_pct, 2),
                "noise_floor_pct": round(noise_floor_pct, 2),
                "reps": max(4, overhead_reps),
            }

        load_task = _asyncio.ensure_future(run_fleet_load(gate_spec))
        # Let the drivers boot + warm their plans before measuring.
        await _asyncio.sleep(min(1.0, duration_s / 4))
        overhead = await overhead_under_load()
        gate = await load_task
        get_row = gate["by_op"].get("get") or {}
        gate_p99 = get_row.get("p99_ms")
        assert gate["failed_drivers"] == 0, gate.get("driver_errors")
        assert gate["errors"] == 0, gate["by_op"]
        assert gate["logical_clients"] == n_drivers * n_logical
        assert gate_p99 is not None and gate_p99 < get_p99_gate_ms, (
            f"fleet get p99 {gate_p99} ms >= SLO gate {get_p99_gate_ms} ms"
        )
        effective_budget = overhead_budget_pct + overhead["noise_floor_pct"]
        assert overhead["overhead_pct"] <= effective_budget, (
            f"telemetry overhead under load {overhead['overhead_pct']}% > "
            f"{overhead_budget_pct}% budget + {overhead['noise_floor_pct']}% "
            "demonstrated measurement noise"
        )
        print(
            f"# fleet_scale gate: {gate['logical_clients']} logical clients "
            f"/ {n_drivers} drivers -> {gate['ops_per_s']:.0f} ops/s, get "
            f"p50 {get_row.get('p50_ms'):.2f} ms p99 {gate_p99:.2f} ms "
            f"(gate {get_p99_gate_ms:.0f} ms); telemetry overhead "
            f"{overhead['overhead_pct']:+.2f}% (budget <= "
            f"{overhead_budget_pct}% + {overhead['noise_floor_pct']:.2f}% "
            "noise floor)",
            file=sys.stderr,
        )

        # Violation leg: hold the landing-copy window open (client-scope
        # delay) under a deliberately tight GET p99 SLO — the scoreboard
        # must blame the landing stage.
        tight_ms = 5.0
        violation_spec = LoadSpec(
            store_name=store,
            duration_s=violation_duration_s,
            processes=2,
            clients_per_process=max(4, n_logical // 8),
            pattern="poisson",
            rate_hz=max(8.0, rate_hz * 2),
            mix={"get": 1.0},
            value_kb=value_kb,
            shared_keys=min(shared_keys, 32),
            seed=16,
            env={
                "TORCHSTORE_TPU_SLO_GET_P99_MS": str(tight_ms),
                "TORCHSTORE_TPU_FAULTPOINTS": (
                    "shm.landing_stamp=delay:delay_ms=25"
                ),
            },
        )
        violation = await run_fleet_load(violation_spec)
        board = (violation.get("slo") or {}).get("slos") or {}
        row = board.get("get_p99_ms") or {}
        assert violation["failed_drivers"] == 0, violation.get(
            "driver_errors"
        )
        assert row.get("violations", 0) > 0, board
        assert row.get("dominant_stage") == "landing", row
        print(
            f"# fleet_scale violation leg: get_p99_ms violated "
            f"{row['violations']}x under a {tight_ms} ms SLO with injected "
            f"landing delays; dominant stage = {row['dominant_stage']} "
            "(stage attribution confirmed)",
            file=sys.stderr,
        )
        return {
            "drivers": n_drivers,
            "logical_clients": gate["logical_clients"],
            "duration_s": duration_s,
            "value_kb": value_kb,
            "fleet_ops_per_s": gate["ops_per_s"],
            "fleet_get_p50_ms": round(get_row.get("p50_ms") or 0.0, 3),
            "fleet_get_p99_ms": round(gate_p99, 3),
            "get_p99_gate_ms": get_p99_gate_ms,
            "by_op": gate["by_op"],
            "window_s": gate["window_s"],
            "fleet_ledger_overhead_pct": overhead["overhead_pct"],
            "ledger_overhead_under_load": overhead,
            "scoreboard": gate.get("slo"),
            "violation": {
                "slo": "get_p99_ms",
                "threshold_ms": tight_ms,
                "violations": row.get("violations", 0),
                "dominant_stage": row.get("dominant_stage"),
                "stages": row.get("stages"),
            },
        }
    finally:
        led.set_enabled(led_was)
        rec.set_enabled(rec_was)
        await ts.shutdown(store)


async def placement_section(
    n_drivers: int = 4,
    n_logical: int = 64,
    duration_s: float = 3.0,
    n_volumes: int = 4,
    value_kb: float = 16.0,
    shared_keys: int = 32,
    rate_hz: float = 4.0,
    tenants: int = 4,
    zipf_alpha: float = 1.5,
    rebalance_rounds: int = 3,
) -> dict:
    """Traffic-aware placement section (ISSUE 16): the control plane's
    closed loop, measured. Three loadgen legs against one multi-volume
    fleet, all on the RPC plane (``one_sided=False``) so every get lands
    in a volume ledger the control engine can actually see:

    1. **Uniform leg** — poisson arrivals, uniform key pick: the
       throughput and per-tenant get-p99 baseline.
    2. **Skewed leg, engine idle** — Zipf key popularity (a few keys soak
       most reads) plus one bursting tenant cohort (t0). Afterward,
       ``ts.control_plan()`` (the dry run) MUST name at least one action
       — the solver sees the skew even when nothing acts on it, asserted.
    3. **Rebalance + skewed leg, engine acting** — ``ts.rebalance()``
       rounds apply the plan (migrations/splits through the index
       authority, every one a ``decision`` event), then the skewed leg
       reruns WITH a mid-leg rebalance riding inside it: zero failed
       drivers and zero op errors while keys migrate under load,
       asserted.

    Emits ``rebalance_recovery_ratio`` (skewed-with-engine ops/s over the
    uniform baseline), ``tenant_isolation_p99_ratio`` (worst non-bursting
    tenant's get p99 vs the uniform baseline — what admission control
    buys the quiet tenants), and ``migration_bytes`` (the controller's
    ``ts_control_migration_bytes_total``) — gated by bench_compare."""
    import asyncio as _asyncio
    import os as _os

    import torchstore_tpu as ts
    from torchstore_tpu.loadgen import LoadSpec, run_fleet_load

    store = "bench_placement"
    # Bench-scale policy thresholds: the defaults are sized for fleets
    # moving MBs per window; this section moves KBs. Set BEFORE
    # initialize (the controller's engine reads them at spawn) and
    # inherited by every driver (admission control on fleet-wide).
    ctl_env = {
        "TORCHSTORE_TPU_CONTROL_MIN_WINDOW_BYTES": "4096",
        "TORCHSTORE_TPU_CONTROL_HOT_KEY_MIN_BYTES": "8192",
        "TORCHSTORE_TPU_CONTROL_MIN_EDGE_BYTES": "8192",
        "TORCHSTORE_TPU_CONTROL_COOLDOWN_S": "0.5",
        "TORCHSTORE_TPU_CONTROL_ADMISSION": "1",
    }
    saved = {k: _os.environ.get(k) for k in ctl_env}
    _os.environ.update(ctl_env)

    def leg_spec(pattern, seed: int) -> LoadSpec:
        return LoadSpec(
            store_name=store,
            duration_s=duration_s,
            processes=n_drivers,
            clients_per_process=n_logical,
            pattern=pattern,
            rate_hz=rate_hz,
            mix={"get": 0.9, "put": 0.1},
            value_kb=value_kb,
            shared_keys=shared_keys,
            tenants=tenants,
            seed=seed,
            config_overrides={"one_sided": False},
        )

    def leg_ok(label: str, rep: dict) -> None:
        assert rep["failed_drivers"] == 0, (label, rep.get("driver_errors"))
        assert rep["errors"] == 0, (label, rep["by_op"])

    skew_pattern = {
        "kind": "skewed",
        "rate_hz": rate_hz,
        "peak_rate_hz": rate_hz * 4,
        "period_s": max(1.0, duration_s / 3),
        "burst_frac": 0.3,
        "zipf_alpha": zipf_alpha,
    }
    try:
        await ts.initialize(num_storage_volumes=n_volumes, store_name=store)
        uniform = await run_fleet_load(leg_spec("poisson", 160))
        leg_ok("uniform", uniform)
        skewed_off = await run_fleet_load(leg_spec(skew_pattern, 161))
        leg_ok("skewed_off", skewed_off)
        plan = await ts.control_plan(store)
        assert plan["actions"], (
            "control_plan saw a skewed workload but planned nothing: "
            f"{plan['snapshot']}"
        )
        print(
            f"# placement plan (engine idle): "
            f"{[a['kind'] for a in plan['actions']]}",
            file=sys.stderr,
        )
        decisions: list[dict] = []
        for _ in range(rebalance_rounds):
            rep = await ts.rebalance(store)
            decisions.extend(rep.get("actions") or [])
            await _asyncio.sleep(0.6)  # let the shortened cooldown lapse
        acted = [
            d
            for d in decisions
            if str(d.get("outcome", "")).startswith(("applied", "deferred"))
        ]
        assert acted, (
            f"no decision landed across {rebalance_rounds} rebalance "
            f"rounds: {decisions}"
        )
        # The engine-on leg, with a live migration riding inside it: the
        # zero-failed-gets-during-migration acceptance.
        load_task = _asyncio.ensure_future(
            run_fleet_load(leg_spec(skew_pattern, 162))
        )
        await _asyncio.sleep(min(1.0, duration_s / 3))
        mid = await ts.rebalance(store)
        decisions.extend(mid.get("actions") or [])
        skewed_on = await load_task
        leg_ok("skewed_on", skewed_on)

        fleet = await ts.fleet_snapshot(store_name=store)
        series = (
            (fleet.get("metrics") or {}).get(
                "ts_control_migration_bytes_total"
            )
            or {}
        ).get("series") or []
        migration_bytes = int(sum(s.get("value") or 0 for s in series))

        uniform_get = uniform["by_op"].get("get") or {}
        baseline_p99 = uniform_get.get("p99_ms") or 0.0
        worst_quiet_p99 = 0.0
        for tenant, row in (skewed_on.get("by_tenant") or {}).items():
            if tenant == "t0":  # the bursting cohort pays for itself
                continue
            p99 = ((row.get("by_op") or {}).get("get") or {}).get("p99_ms")
            if p99:
                worst_quiet_p99 = max(worst_quiet_p99, p99)
        isolation = (
            round(worst_quiet_p99 / baseline_p99, 3)
            if baseline_p99 > 0 and worst_quiet_p99 > 0
            else None
        )
        recovery = round(
            skewed_on["ops_per_s"] / max(uniform["ops_per_s"], 1e-9), 3
        )
        print(
            f"# placement: uniform {uniform['ops_per_s']:.0f} ops/s, "
            f"skewed idle {skewed_off['ops_per_s']:.0f}, skewed+engine "
            f"{skewed_on['ops_per_s']:.0f} (recovery {recovery:.2f}); "
            f"{len(acted)} decision(s) acted, {migration_bytes}B migrated; "
            f"quiet-tenant p99 ratio {isolation}",
            file=sys.stderr,
        )
        return {
            "drivers": n_drivers,
            "logical_clients": n_drivers * n_logical,
            "tenants": tenants,
            "zipf_alpha": zipf_alpha,
            "uniform_ops_per_s": uniform["ops_per_s"],
            "skewed_off_ops_per_s": skewed_off["ops_per_s"],
            "skewed_on_ops_per_s": skewed_on["ops_per_s"],
            "rebalance_recovery_ratio": recovery,
            "tenant_isolation_p99_ratio": isolation,
            "migration_bytes": migration_bytes,
            "uniform_get_p99_ms": round(baseline_p99, 3),
            "worst_quiet_tenant_p99_ms": round(worst_quiet_p99, 3),
            "plan_actions": plan["actions"],
            "decisions": decisions,
            "by_tenant_skewed_on": skewed_on.get("by_tenant"),
        }
    finally:
        for key, val in saved.items():
            if val is None:
                _os.environ.pop(key, None)
            else:
                _os.environ[key] = val
        await ts.shutdown(store)


async def autoscale_section(
    n_drivers: int = 4,
    n_logical: int = 32,
    period_s: float = 8.0,
    periods: float = 2.0,
    n_volumes_fixed: int = 4,
    value_kb: float = 16.0,
    shared_keys: int = 32,
    base_rate_hz: float = 0.5,
    peak_rate_hz: float = 16.0,
    get_p99_gate_ms: float = 500.0,
    out_window_mb: float = 8.0,
    idle_window_mb: float = 4.0,
    ledger_window_s: float = 2.0,
    volume_seconds_gate: float = 0.60,
    autoscale_tick_s: float = 0.4,
    settle_s: float = 4.0,
) -> dict:
    """Elastic fleet autoscaling + cold tier (ISSUE 18), gated behind
    ``--autoscale``. Two diurnal loadgen legs plus a scale-to-zero leg:

    1. **Fixed fleet** — ``n_volumes_fixed`` volumes provisioned for the
       diurnal peak run the whole window (the static-provisioning cost
       baseline); a 5 Hz sampler integrates live-volume-seconds.
    2. **Autoscaled fleet** — ONE volume plus the autoscale engine
       (``ts.autoscale()`` driven at ``autoscale_tick_s``) rides the
       same sinusoid: scale-out at the crest, graceful drain + retire in
       the trough. Asserted: zero failed drivers / op errors, get p99
       under ``get_p99_gate_ms``, the fleet actually breathed (peak size
       > 1, post-settle size back to 1), and live-volume-seconds at most
       ``volume_seconds_gate`` of the fixed leg's — the elasticity
       dividend.
    3. **Scale-to-zero** — ``ts.blob_checkpoint()`` the surviving fleet,
       shut EVERYTHING down, cold-start a fresh fleet and time
       ``ts.blob_restore()`` until every committed key is re-landed and
       a sample key verifies byte-identical.

    Emits ``autoscale_volume_seconds_ratio``, ``autoscale_get_p99_ms``,
    and ``cold_restore_s`` headline keys (gated by bench_compare)."""
    import asyncio as _asyncio
    import os as _os
    import shutil as _shutil
    import tempfile as _tempfile

    import torchstore_tpu as ts
    from torchstore_tpu.loadgen import LoadSpec, run_fleet_load

    duration_s = period_s * periods
    pattern = {
        "kind": "diurnal",
        "rate_hz": base_rate_hz,
        "peak_rate_hz": peak_rate_hz,
        "period_s": period_s,
    }

    def _spec(store: str, seed: int) -> "LoadSpec":
        return LoadSpec(
            store_name=store,
            duration_s=duration_s,
            processes=n_drivers,
            clients_per_process=n_logical,
            pattern=pattern,
            rate_hz=base_rate_hz,
            mix={"get": 0.8, "put": 0.2},
            value_kb=value_kb,
            shared_keys=shared_keys,
            seed=seed,
            env={"TORCHSTORE_TPU_SLO_GET_P99_MS": str(get_p99_gate_ms)},
        )

    async def _sampled_leg(store: str, spec, tick_autoscale: bool) -> dict:
        """Run one loadgen leg while sampling live fleet size (and, on
        the autoscaled leg, driving ``ts.autoscale()`` rounds)."""
        client = ts.client(store)
        await client._ensure_setup()
        samples: list[tuple[float, int]] = []
        vol_seconds = 0.0
        stop = _asyncio.Event()

        async def sampler():
            nonlocal vol_seconds
            last = time.monotonic()
            while not stop.is_set():
                if tick_autoscale:
                    try:
                        await ts.autoscale(store_name=store)
                    except Exception as exc:  # noqa: BLE001 - a failed
                        # round must not kill the sampler mid-leg; the
                        # leg's own assertions judge the outcome
                        print(
                            f"# autoscale round failed: {exc}",
                            file=sys.stderr,
                        )
                vmap = await client.controller.get_volume_map.call_one()
                live = sum(
                    1
                    for info in vmap.values()
                    if info.get("health") != "quarantined"
                )
                now = time.monotonic()
                vol_seconds += live * (now - last)
                last = now
                samples.append((round(now, 3), live))
                try:
                    await _asyncio.wait_for(
                        stop.wait(), timeout=autoscale_tick_s / 2
                    )
                except _asyncio.TimeoutError:
                    pass

        sampler_task = _asyncio.ensure_future(sampler())
        try:
            report = await run_fleet_load(spec)
        finally:
            stop.set()
            await sampler_task
        get_row = report["by_op"].get("get") or {}
        assert report["failed_drivers"] == 0, report.get("driver_errors")
        assert report["errors"] == 0, report["by_op"]
        return {
            "report": report,
            "get_p99_ms": get_row.get("p99_ms"),
            "volume_seconds": vol_seconds,
            "fleet_sizes": [n for _t, n in samples],
        }

    # ---- leg 1: fixed fleet provisioned for the peak --------------------
    fixed_store = "bench_as_fixed"
    await ts.initialize(
        num_storage_volumes=n_volumes_fixed, store_name=fixed_store
    )
    try:
        fixed = await _sampled_leg(
            fixed_store, _spec(fixed_store, seed=18), tick_autoscale=False
        )
    finally:
        await ts.shutdown(fixed_store)
    print(
        f"# autoscale fixed leg: {n_volumes_fixed} volumes x "
        f"{duration_s:.0f} s -> {fixed['volume_seconds']:.1f} vol-s, "
        f"{fixed['report']['ops_per_s']:.0f} ops/s, get p99 "
        f"{fixed['get_p99_ms']:.2f} ms",
        file=sys.stderr,
    )

    # ---- leg 2: elastic fleet under the same sinusoid -------------------
    blob_dir = _tempfile.mkdtemp(prefix="ts_bench_blob_")
    knobs = {
        "TORCHSTORE_TPU_AUTOSCALE_MAX_VOLUMES": str(n_volumes_fixed),
        "TORCHSTORE_TPU_AUTOSCALE_OUT_WINDOW_BYTES": str(
            int(out_window_mb * 1024 * 1024)
        ),
        "TORCHSTORE_TPU_AUTOSCALE_IDLE_WINDOW_BYTES": str(
            int(idle_window_mb * 1024 * 1024)
        ),
        "TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS": "2",
        "TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S": str(
            max(0.2, period_s / 10)
        ),
        "TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND": "64",
        "TORCHSTORE_TPU_LEDGER_WINDOW_S": str(ledger_window_s),
        "TORCHSTORE_TPU_BLOB_ENABLED": "1",
        "TORCHSTORE_TPU_BLOB_DIR": blob_dir,
    }
    saved = {k: _os.environ.get(k) for k in knobs}
    _os.environ.update(knobs)
    auto_store = "bench_as_auto"
    cold_store = "bench_as_cold"
    try:
        await ts.initialize(num_storage_volumes=1, store_name=auto_store)
        try:
            auto = await _sampled_leg(
                auto_store, _spec(auto_store, seed=19), tick_autoscale=True
            )
            peak_fleet = max(auto["fleet_sizes"] or [1])
            # Settle: keep ticking with no load until the trough drains
            # the fleet back to its floor.
            deadline = time.monotonic() + settle_s + period_s
            final_fleet = peak_fleet
            while time.monotonic() < deadline:
                rep = await ts.autoscale(store_name=auto_store)
                for act in rep.get("actions", []):
                    print(
                        f"# autoscale settle: {act['kind']} "
                        f"[{act.get('reason')}] -> {act.get('outcome')}",
                        file=sys.stderr,
                    )
                vmap = await ts.client(
                    auto_store
                ).controller.get_volume_map.call_one()
                final_fleet = len(vmap)
                if final_fleet <= 1:
                    break
                await _asyncio.sleep(autoscale_tick_s)
            # The scale-to-zero leg: checkpoint, tear the world down.
            ckpt = await ts.blob_checkpoint(store_name=auto_store)
            assert not ckpt["errors"], ckpt
        finally:
            await ts.shutdown(auto_store)
            ts.reset_client()

        assert peak_fleet > 1, (
            f"autoscaler never scaled out (fleet sizes {auto['fleet_sizes']})"
        )
        assert final_fleet < peak_fleet, (
            f"fleet never drained back: peak {peak_fleet}, "
            f"final {final_fleet}"
        )
        ratio = (
            auto["volume_seconds"] / fixed["volume_seconds"]
            if fixed["volume_seconds"] > 0
            else 0.0
        )
        assert ratio <= volume_seconds_gate, (
            f"autoscaled fleet burned {ratio:.2f}x the fixed fleet's "
            f"volume-seconds (gate {volume_seconds_gate})"
        )
        auto_p99 = auto["get_p99_ms"]
        assert auto_p99 is not None and auto_p99 < get_p99_gate_ms, (
            f"autoscaled get p99 {auto_p99} ms >= SLO gate "
            f"{get_p99_gate_ms} ms"
        )
        print(
            f"# autoscale elastic leg: fleet 1 -> {peak_fleet} -> "
            f"{final_fleet}, {auto['volume_seconds']:.1f} vol-s "
            f"({ratio:.2f}x fixed), {auto['report']['ops_per_s']:.0f} "
            f"ops/s, get p99 {auto_p99:.2f} ms (gate "
            f"{get_p99_gate_ms:.0f} ms)",
            file=sys.stderr,
        )

        # ---- leg 3: cold restore from the blob manifest -----------------
        await ts.initialize(num_storage_volumes=1, store_name=cold_store)
        try:
            t0 = time.perf_counter()
            restore = await ts.blob_restore(store_name=cold_store)
            cold_restore_s = time.perf_counter() - t0
            assert restore["restored"] == ckpt["keys"], restore
            assert not restore["failed"], restore
            sample_key = f"{auto_store}/shared/0"
            got = np.asarray(await ts.get(sample_key, store_name=cold_store))
            assert got.nbytes > 0 and np.isfinite(got).all()
        finally:
            await ts.shutdown(cold_store)
        print(
            f"# autoscale cold restore: {restore['restored']} keys in "
            f"{cold_restore_s:.2f} s from the blob manifest",
            file=sys.stderr,
        )
    finally:
        for key, val in saved.items():
            if val is None:
                _os.environ.pop(key, None)
            else:
                _os.environ[key] = val
        _shutil.rmtree(blob_dir, ignore_errors=True)

    return {
        "drivers": n_drivers,
        "logical_clients": n_drivers * n_logical,
        "duration_s": duration_s,
        "period_s": period_s,
        "n_volumes_fixed": n_volumes_fixed,
        "autoscale_volume_seconds_ratio": round(ratio, 3),
        "autoscale_get_p99_ms": round(auto_p99, 3),
        "cold_restore_s": round(cold_restore_s, 3),
        "volume_seconds_fixed": round(fixed["volume_seconds"], 1),
        "volume_seconds_autoscaled": round(auto["volume_seconds"], 1),
        "peak_fleet": peak_fleet,
        "final_fleet": final_fleet,
        "fixed_get_p99_ms": round(fixed["get_p99_ms"] or 0.0, 3),
        "fixed_ops_per_s": fixed["report"]["ops_per_s"],
        "autoscaled_ops_per_s": auto["report"]["ops_per_s"],
        "restored_keys": restore["restored"],
        "get_p99_gate_ms": get_p99_gate_ms,
        "volume_seconds_gate": volume_seconds_gate,
    }


async def run(
    n_tensors: int = N_TENSORS,
    tensor_mb: float = TENSOR_MB,
    iters: int = ITERS,
    calib_mb: float = 256,
    lat_iters: int = 40,
    cold_steady_iters: int = 4,
    many_keys_n: int = 2048,
    many_keys_kb: float = 64,
    recovery_n_keys: int = 64,
    recovery_key_kb: float = 256,
    ledger_keys: int = 1024,
    ledger_reps: int = 16,
    streamed_layers: int = 16,
    streamed_layer_kb: float = 256,
    streamed_train_ms: float = 15.0,
    streamed_decode_ms: float = 15.0,
    streamed_iters: int = 3,
    fanout_fleets: int = 4,
    fanout_layers: int = 8,
    fanout_layer_kb: float = 128,
    fanout_train_ms: float = 10.0,
    capacity_versions: int = 8,
    capacity_keys: int = 16,
    capacity_key_kb: float = 256,
    delta_tensors: int = 8,
    delta_tensor_kb: float = 4096,
    delta_versions: int = 6,
    meta_shard_counts: tuple = (1, 4),
    meta_drivers: int = 16,
    meta_logical: int = 6,
    meta_duration_s: float = 3.0,
    fleet_drivers: int = 8,
    fleet_logical: int = 128,
    fleet_duration_s: float = 4.0,
    fleet_volumes: int = 4,
    fleet_gate_ms: float = 500.0,
    placement_drivers: int = 4,
    placement_logical: int = 64,
    placement_duration_s: float = 3.0,
    placement_volumes: int = 4,
) -> dict:
    """Host benchmark sections. Parameters exist so the tier-1 smoke test
    (tests/test_bench_smoke.py) can execute the REAL code path on KB-scale
    tensors — a bench.py regression then fails tests instead of silently
    zeroing a round's headline (VERDICT r5)."""
    import torchstore_tpu as ts

    # Host-weather calibration (ADVICE r5): measure THIS host's memcpy
    # ceiling and scale the 10 GB/s reference proxy down with it, so a
    # degraded shared host is visible in the JSON instead of silently
    # deflating vs_baseline.
    host_memcpy = calibrate_memcpy_gbps(size_mb=calib_mb)
    calib_ratio = min(1.0, host_memcpy / CALIB_MEMCPY_ANCHOR_GBPS)
    print(
        f"# host calibration: single-thread memcpy {host_memcpy:.2f} GB/s "
        f"(anchor {CALIB_MEMCPY_ANCHOR_GBPS:.1f}; proxy scale "
        f"{calib_ratio:.2f})",
        file=sys.stderr,
    )

    await ts.initialize(
        store_name="bench",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    n_elem = max(1, int(tensor_mb * 1024 * 1024 // 4))
    sd = {
        "layers": {
            str(i): np.random.rand(n_elem).astype(np.float32)
            for i in range(n_tensors)
        }
    }
    total_bytes = sum(v.nbytes for v in sd["layers"].values())
    user = {
        "layers": {str(i): np.zeros(n_elem, np.float32) for i in range(n_tensors)}
    }

    async def timed_loop(label: str, put_fn, get_fn, src=None, byte_factor=2) -> dict:
        """Time ITERS put+get round trips. Each iteration PERTURBS the source
        (so a silently dead data path cannot pass the final verification on
        stale bytes) and validates every tensor. ``byte_factor`` is how many
        times each byte crosses the data plane per iteration (2 for copy
        round trips, 1 when the publish direction is copy-free — that leg is
        reported in milliseconds, GB/s is reserved for legs that move bytes)."""
        import statistics

        src = src if src is not None else sd
        rates: list[float] = []
        for it in range(iters):
            stamp = float(it + 1)
            for arr in src["layers"].values():
                arr[0] = stamp
            t0 = time.perf_counter()
            await put_fn()
            t1 = time.perf_counter()
            out = await get_fn()
            t2 = time.perf_counter()
            if byte_factor == 1:
                # Copy-free publish: a GB/s figure here reads as 2000 GB/s
                # nonsense (VERDICT r4 weak #5) — the honest unit is time.
                put_leg = f"publish {(t1-t0)*1e3:.1f} ms (copy-free)"
                gbps = total_bytes / 1e9 / (t2 - t1)  # the pull moves the bytes
                kind = "pull physical"
            else:
                put_leg = f"put {total_bytes/1e9/(t1-t0):.2f} GB/s"
                gbps = byte_factor * total_bytes / 1e9 / (t2 - t0)
                kind = "delivered"
            rates.append(gbps)
            print(
                f"# {label} iter {it}: {put_leg}, "
                f"get {total_bytes/1e9/(t2-t1):.2f} GB/s, "
                f"{kind} {gbps:.2f} GB/s",
                file=sys.stderr,
            )
            for i in range(n_tensors):
                assert out["layers"][str(i)][0] == stamp, f"{label} stale data"
        for i in range(n_tensors):
            np.testing.assert_array_equal(
                out["layers"][str(i)], src["layers"][str(i)]
            )
        # Iter 0 is the cold start (first-touch faults, plan building);
        # iters 1+ are the warm steady state an RL loop actually lives in.
        # The headline is the warm MEDIAN — best-of-N would hide warm-path
        # collapses the consumer feels every step (VERDICT r2).
        warm = rates[1:] or rates
        best, median, worst = max(rates), statistics.median(warm), min(warm)
        mean = statistics.mean(warm)
        cv = (statistics.pstdev(warm) / mean) if mean > 0 else 0.0
        warn = worst < 0.5 * best
        print(
            f"# {label}: warm median {median:.2f}, best {best:.2f}, "
            f"warm min {worst:.2f} GB/s, warm CV {cv:.2f}"
            + ("  [WARN: warm min < 50% of best — warm-path collapse]" if warn else ""),
            file=sys.stderr,
        )
        return {
            "median": median,
            "best": best,
            "warm_min": worst,
            "warm_cv": cv,
            "warn": warn,
        }

    async def measured_section(label: str, put_fn, get_fn, **kw) -> dict:
        """Run a headline section with a BOUNDED rerun-on-WARN policy
        (VERDICT r4 task 1): a warm-collapse WARN means at least one warm
        iteration lost >50% to something — usually host weather on this
        shared 1-vCPU box — so the section gets up to RERUNS_ON_WARN fresh
        attempts. The best-median attempt is kept and the rerun count is
        carried into the JSON, so a clean number earned on a retry is
        distinguishable from a clean first run."""
        best_stats: dict | None = None
        for attempt in range(1 + RERUNS_ON_WARN):
            stats = await timed_loop(label, put_fn, get_fn, **kw)
            if best_stats is None or stats["median"] > best_stats["median"]:
                best_stats = stats
            if not stats["warn"]:
                break
            if attempt < RERUNS_ON_WARN:
                print(
                    f"# {label}: WARN fired — rerunning section "
                    f"({attempt + 1}/{RERUNS_ON_WARN} reruns used)",
                    file=sys.stderr,
                )
        best_stats["reruns"] = attempt
        return best_stats

    # Buffered consumer takes zero-copy snapshot views (the jax consumer
    # pattern: device_put straight from the returned views); `user`-dict
    # in-place landing is exercised by the direct path below.
    stats_buffered = await measured_section(
        "buffered",
        lambda: ts.put_state_dict("bench/sd", sd, store_name="bench"),
        lambda: ts.get_state_dict("bench/sd", store_name="bench"),
    )
    # Direct one-hop (the RL steady-state flow): first publish registers
    # staging buffers + builds the dest plan outside the timed loop; the
    # steady state (what a non-adopting trainer pays every step) is
    # refresh + pull with ops writing straight into destination memory.
    await ts.put_state_dict("bench/direct", sd, direct=True, store_name="bench")
    await ts.get_state_dict(
        "bench/direct", user_state_dict=user, direct=True, store_name="bench"
    )
    stats_direct = await measured_section(
        "direct",
        lambda: ts.put_state_dict("bench/direct", sd, direct=True, store_name="bench"),
        lambda: ts.get_state_dict(
            "bench/direct", user_state_dict=user, direct=True, store_name="bench"
        ),
    )
    # Registered-staging variant: the trainer ADOPTS the staging buffers as
    # its weight storage (ts.direct_staging_buffers — registered-memory
    # semantics, like the reference's RDMA-registered regions). Writing a
    # step's weights IS the staging, so a sync step moves each byte exactly
    # ONCE (publish + pull) — reported as one-way GB/s, not double-counted
    # as a round trip, and kept out of the headline for apples-to-apples
    # comparison with the reference metric.
    staging = ts.direct_staging_buffers("bench/direct", store_name="bench")
    assert staging is not None
    stats_registered = await measured_section(
        "direct+registered",
        lambda: ts.put_state_dict(
            "bench/direct", staging, direct=True, store_name="bench"
        ),
        lambda: ts.get_state_dict(
            "bench/direct", user_state_dict=user, direct=True, store_name="bench"
        ),
        src=staging,
        byte_factor=1,  # publish is copy-free; only the pull moves bytes
    )
    # p50 small-op latency (the BASELINE.json metric's latency half).
    lat_put, lat_get = [], []
    small = np.random.rand(256).astype(np.float32)
    for i in range(lat_iters):
        t0 = time.perf_counter()
        await ts.put(f"lat/{i % 4}", small, store_name="bench")
        lat_put.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        await ts.get(f"lat/{i % 4}", store_name="bench")
        lat_get.append(time.perf_counter() - t0)
    p50p = sorted(lat_put)[len(lat_put) // 2] * 1e3
    p50g = sorted(lat_get)[len(lat_get) // 2] * 1e3
    # WARM 1KB p50 get (ISSUE 7 / ROADMAP item 4 acceptance): repeat gets
    # of an unchanged key — after the first re-records the one-sided plan,
    # every get is a stamped read out of the pre-attached segment with
    # zero RPCs. The alternating loop above can never be warm (each put
    # moves the entry stamp), so this leg is measured separately.
    dest = np.zeros_like(small)
    await ts.get("lat/0", like=dest, store_name="bench")  # record the plan
    lat_warm = []
    for _ in range(max(lat_iters, 8)):
        t0 = time.perf_counter()
        await ts.get("lat/0", like=dest, store_name="bench")
        lat_warm.append(time.perf_counter() - t0)
    p50gw = sorted(lat_warm)[len(lat_warm) // 2] * 1e3
    print(
        f"# p50 latency (1KB): put {p50p:.2f} ms, get {p50g:.2f} ms, "
        f"warm one-sided get {p50gw:.3f} ms",
        file=sys.stderr,
    )

    # The observability registry IS the bench's emission path now: grab the
    # snapshot BEFORE shutdown (teardown resets volume gauges) so the
    # machine-readable record carries the per-transport byte counters and
    # op histograms of exactly this run. The fleet snapshot additionally
    # scrapes the controller's and every volume PROCESS's registry (merged,
    # process-labeled — PR 2), so the record shows both sides of every
    # transfer, not just the client's.
    metrics = ts.metrics_snapshot()
    fleet = await ts.fleet_snapshot(store_name="bench")
    await ts.shutdown("bench")
    # Cold-path section AFTER the bench fleet is down (it spawns two fresh
    # fleets of its own — first-sync numbers must not contend with the main
    # fleet's tmpfs footprint). Working set scales via
    # TORCHSTORE_TPU_BENCH_COLD_MB (default: the headline working set).
    import os as _os

    cold_mb = float(
        _os.environ.get("TORCHSTORE_TPU_BENCH_COLD_MB", n_tensors * tensor_mb)
    )
    cold = await cold_path_section(
        n_tensors=n_tensors,
        tensor_mb=cold_mb / n_tensors,
        steady_iters=cold_steady_iters,
    )
    # Many-small-keys section (its own fleet: thousands of tiny entries
    # must not pollute the headline fleet's pools or location caches).
    many_keys = await many_keys_section(
        n_keys=many_keys_n, key_kb=many_keys_kb
    )
    # Decision-telemetry overhead (ISSUE 10): the always-on traffic
    # ledger + flight recorder cost on the warm one-sided get leg.
    ledger_overhead = await ledger_overhead_section(
        n_keys=ledger_keys, reps=ledger_reps
    )
    # Time-series history overhead (ISSUE 17): the sampler + trend
    # detectors at 20x production sweep rate on the same warm get leg.
    history_overhead = await history_overhead_section(
        n_keys=ledger_keys, reps=ledger_reps
    )
    # Streamed-sync section (ISSUE 9): the simulated train→publish→decode
    # loop, barrier vs layer-streamed, on its own fleet.
    streamed = await streamed_sync_section(
        n_layers=streamed_layers,
        layer_kb=streamed_layer_kb,
        train_ms=streamed_train_ms,
        decode_ms=streamed_decode_ms,
        iters=streamed_iters,
    )
    # Recovery section (ISSUE 6): time-to-heal after a volume kill under
    # load, on its own replicated fleet.
    recovery = await recovery_section(
        n_keys=recovery_n_keys, key_kb=recovery_key_kb
    )
    # Fanout section (ISSUE 11): K generator fleets, point-to-point vs
    # relay tree, trainer-host egress measured by the traffic matrix.
    fanout = await fanout_section(
        k_fleets=fanout_fleets,
        n_layers=fanout_layers,
        layer_kb=fanout_layer_kb,
        train_ms=fanout_train_ms,
    )
    # Capacity section (ISSUE 12): working set 2x the tier budget, one
    # leased-hot version, spill + fault-in measured on its own fleet.
    capacity = await capacity_section(
        n_versions=capacity_versions,
        n_keys=capacity_keys,
        key_kb=capacity_key_kb,
    )

    # Delta-sync section (ISSUE 13): steady-state publish loop at
    # none / int8_block / int4_block+delta over the bulk/DCN path.
    delta_sync = await delta_sync_section(
        n_tensors=delta_tensors,
        tensor_kb=delta_tensor_kb,
        versions=delta_versions,
    )
    # Metadata-scale section (ISSUE 14): locate/notify/stream-poll RPC
    # throughput at 1 vs N controller shards, driven by multi-process
    # logical-client load on its own fleets.
    metadata_scale = await metadata_scale_section(
        shard_counts=meta_shard_counts,
        n_drivers=meta_drivers,
        n_logical=meta_logical,
        duration_s=meta_duration_s,
    )
    # Fleet-scale section (ISSUE 15): >= 1k logical clients over >= 8
    # driver processes against a multi-volume fleet — sustained ops/s
    # with p99 under the SLO gate, the telemetry budget re-verified under
    # load, and a deliberately induced violation whose dominant stage the
    # scoreboard must name. All asserted inside the section.
    fleet_scale = await fleet_scale_section(
        n_drivers=fleet_drivers,
        n_logical=fleet_logical,
        duration_s=fleet_duration_s,
        n_volumes=fleet_volumes,
        get_p99_gate_ms=fleet_gate_ms,
    )
    # Placement section (ISSUE 16): skewed loadgen with the control
    # engine idle vs acting — plan non-empty on skew, decisions applied,
    # zero failed gets while keys migrate under load. All asserted
    # inside the section.
    placement = await placement_section(
        n_drivers=placement_drivers,
        n_logical=placement_logical,
        duration_s=placement_duration_s,
        n_volumes=placement_volumes,
    )
    # ADVICE r5 fix: timed_loop/measured_section return stats DICTS — the
    # headline compares their median GB/s scalars, never the dicts.
    med_buffered = stats_buffered["median"]
    med_direct = stats_direct["median"]
    headline = max(med_buffered, med_direct)
    print(
        f"# headline (warm medians): buffered {med_buffered:.2f} GB/s, "
        f"direct steady-state {med_direct:.2f} GB/s",
        file=sys.stderr,
    )
    effective_proxy = REFERENCE_GBPS * calib_ratio
    return {
        "metric": "state_dict_weight_sync_round_trip",
        "value": round(headline, 3),
        "unit": "GB/s",
        "vs_baseline": round(headline / effective_proxy, 3),
        "host_memcpy_gbps": round(host_memcpy, 3),
        "calib_ratio": round(calib_ratio, 3),
        "sections": {
            "buffered": stats_buffered,
            "direct": stats_direct,
            "direct_registered": stats_registered,
        },
        "p50_put_ms": round(p50p, 3),
        "p50_get_ms": round(p50g, 3),
        # Warm one-sided 1KB get (zero RPCs): the ROADMAP item-4 number.
        "p50_get_1kb_ms": round(p50gw, 3),
        # ISSUE-3 acceptance ratios at top level; the full section under
        # "cold" (first-sync GB/s, prewarm report, working-set size).
        "cold_vs_steady": cold["cold_vs_steady"],
        "cold_prewarmed_vs_steady": cold["cold_prewarmed_vs_steady"],
        "cold": cold,
        # ISSUE-5 headline stats at top level; the full section under
        # "many_keys" (per-iteration medians, working-set shape).
        "many_keys_gbps": many_keys["many_keys_gbps"],
        "per_key_put_us": many_keys["per_key_put_us"],
        # ISSUE-7 one-sided get leg at top level: per-key get cost, the
        # delivered get rate, and its distance from the memcpy ceiling.
        "per_key_get_us": many_keys["per_key_get_us"],
        "many_keys_get_gbps": many_keys["get_gbps"],
        "get_memcpy_ratio": many_keys["get_memcpy_ratio"],
        "many_keys": many_keys,
        # ISSUE-10 acceptance: always-on recorder+ledger cost on the warm
        # many-keys leg (budget <= 2% at full scale); full section under
        # "ledger_overhead".
        "ledger_overhead_pct": ledger_overhead["overhead_pct"],
        "ledger_overhead": ledger_overhead,
        # ISSUE-17 acceptance: history sampler + detector cost on the same
        # warm get leg (budget <= 1% at full scale); full section under
        # "history_overhead".
        "history_overhead_pct": history_overhead["overhead_pct"],
        "history_overhead": history_overhead,
        # ISSUE-9 headline stats at top level: how much of the publish
        # window the streamed acquire overlapped (acceptance > 0) and the
        # first decoded layer relative to publish completion (negative =
        # decode beat the seal); the full section under "streamed_sync".
        "overlap_ratio": streamed["overlap_ratio"],
        "first_token_after_publish_ms": streamed[
            "first_token_after_publish_ms"
        ],
        "streamed_sync": streamed,
        # ISSUE-6 headline stats at top level; the full section under
        # "recovery" (detection / failover-get / re-replication timings).
        "heal_s": recovery["heal_s"],
        "failover_get_s": recovery["first_get_s"],
        "recovery": recovery,
        # ISSUE-11 headline stats at top level: tree/p2p trainer-host
        # egress ratio (acceptance <= 1.5/K, measured by the traffic
        # matrix) and the deepest fleet's publish-window overlap through
        # >= 2 relay hops; the full section under "fanout".
        "fanout_egress_ratio": fanout["fanout_egress_ratio"],
        "fanout_overlap_ratio": fanout["fanout_overlap_ratio"],
        "fanout": fanout,
        # ISSUE-12 headline stats at top level: warm leased-version get
        # cost after the spill writer ran (acceptance: unchanged within
        # bench_compare thresholds, zero warm get RPCs), cold-version
        # fault-in latency through the transport ladder, and how much of
        # the over-budget working set the policy demoted; full section
        # under "capacity".
        "warm_get_after_spill_us": capacity["warm_get_after_spill_us"],
        "fault_in_p50_ms": capacity["fault_in_p50_ms"],
        "spilled_bytes_ratio": capacity["spilled_bytes_ratio"],
        "capacity": capacity,
        # ISSUE-13 headline stats at top level: quantized/delta wire-tier
        # speedups over the unquantized bulk path, the delta leg's wire
        # compression, and the measured (bound-asserted) dequant error;
        # full section under "delta_sync".
        "delta_speedup_int8_block": delta_sync["delta_speedup_int8_block"],
        "delta_speedup_delta": delta_sync["delta_speedup_delta"],
        "delta_wire_compression_delta": delta_sync[
            "delta_wire_compression_int4_delta"
        ],
        "delta_max_abs_err": delta_sync["delta_max_abs_err"],
        "delta_sync": delta_sync,
        # ISSUE-14 headline stats at top level: metadata RPC throughput
        # scaling from 1 controller to the sharded plane (acceptance
        # >= 2.5x at 4 shards) and the sharded leg's absolute rate; full
        # section under "metadata_scale".
        "metadata_scale_x": metadata_scale["metadata_scale_x"],
        "metadata_ops_per_s_sharded": metadata_scale[
            "metadata_ops_per_s_sharded"
        ],
        "metadata_scale": metadata_scale,
        # ISSUE-15 headline stats at top level: sustained fleet ops/s at
        # >= 1k logical clients with get p99 under the SLO gate, and the
        # telemetry budget re-measured under that load; the full section
        # (scoreboard, induced-violation attribution) under "fleet_scale".
        "fleet_ops_per_s": fleet_scale["fleet_ops_per_s"],
        "fleet_get_p99_ms": fleet_scale["fleet_get_p99_ms"],
        "fleet_ledger_overhead_pct": fleet_scale[
            "fleet_ledger_overhead_pct"
        ],
        "fleet_scale": fleet_scale,
        # ISSUE-16 headline stats at top level: skewed-traffic throughput
        # recovery once the control engine rebalances, the quiet tenants'
        # get-p99 ratio under one bursting cohort, and the bytes the
        # engine's migrations moved; the full section (plan, decisions,
        # per-tenant scoreboard) under "placement".
        "rebalance_recovery_ratio": placement["rebalance_recovery_ratio"],
        "tenant_isolation_p99_ratio": placement[
            "tenant_isolation_p99_ratio"
        ],
        "migration_bytes": placement["migration_bytes"],
        "placement": placement,
        "metrics": metrics,
        "fleet": fleet,
    }


if __name__ == "__main__":
    if "--device-section" in sys.argv:
        sys.exit(asyncio.run(_device_section_child()))
    if "--cold-path" in sys.argv:
        # Standalone cold-path run (tpu_watch.sh device capture): one JSON
        # line with the cold/steady ratios, env-scaled working set.
        import os as _os

        _cold_mb = float(
            _os.environ.get(
                "TORCHSTORE_TPU_BENCH_COLD_MB", N_TENSORS * TENSOR_MB
            )
        )
        cold_result = asyncio.run(
            cold_path_section(
                n_tensors=N_TENSORS, tensor_mb=_cold_mb / N_TENSORS
            )
        )
        print(json.dumps(cold_result))
        sys.exit(0)
    if "--recovery" in sys.argv:
        # Standalone recovery run: one JSON line with time-to-heal timings.
        print(json.dumps(asyncio.run(recovery_section())))
        sys.exit(0)
    if "--streamed-sync" in sys.argv:
        # Standalone streamed-sync run: one JSON line with the barrier vs
        # streamed wall clocks and overlap metrics.
        print(json.dumps(asyncio.run(streamed_sync_section())))
        sys.exit(0)
    if "--fanout" in sys.argv:
        # Standalone fan-out run: one JSON line with the tree vs
        # point-to-point trainer-host egress and deep-hop overlap.
        print(json.dumps(asyncio.run(fanout_section())))
        sys.exit(0)
    if "--cross-host" in sys.argv:
        # Standalone cross-host run (gated: not part of the default
        # headline): one JSON line with the push vs doorbell first-layer
        # latencies, the metadata-relay egress ratio, and the warm
        # metadata-RPC audit over the emulated multi-host topology.
        print(json.dumps(asyncio.run(cross_host_section())))
        sys.exit(0)
    if "--capacity" in sys.argv:
        # Standalone tiered-capacity run: one JSON line with the
        # spill/fault-in/warm-leased-get numbers.
        print(json.dumps(asyncio.run(capacity_section())))
        sys.exit(0)
    if "--metadata-scale" in sys.argv:
        # Standalone metadata-plane run: one JSON line with per-shard-count
        # metadata ops/s and the 1 -> N scaling factor.
        print(json.dumps(asyncio.run(metadata_scale_section())))
        sys.exit(0)
    if "--fleet-scale" in sys.argv:
        # Standalone fleet-scale run: one JSON line with sustained ops/s,
        # the p99-vs-SLO gate, the under-load telemetry overhead, and the
        # induced-violation stage attribution.
        print(json.dumps(asyncio.run(fleet_scale_section())))
        sys.exit(0)
    if "--placement" in sys.argv:
        # Standalone placement run: one JSON line with the skewed-traffic
        # recovery ratio, tenant isolation, and migrated bytes.
        print(json.dumps(asyncio.run(placement_section())))
        sys.exit(0)
    if "--autoscale" in sys.argv:
        # Standalone elastic-fleet run (gated: not part of the default
        # headline): one JSON line with the diurnal fixed-vs-autoscaled
        # volume-seconds ratio, the autoscaled get p99, and the
        # scale-to-zero cold-restore wall clock.
        print(json.dumps(asyncio.run(autoscale_section())))
        sys.exit(0)
    if "--delta-sync" in sys.argv:
        # Standalone quantized/delta wire-tier run: one JSON line with the
        # per-mode effective GB/s, compression, and dequant error.
        print(json.dumps(asyncio.run(delta_sync_section())))
        sys.exit(0)
    result = asyncio.run(run())
    # The headline JSON lands BEFORE the device section: a wedged TPU
    # backend can cost up to two subprocess timeouts, and a driver killing
    # the bench mid-attempt must never lose the round's host numbers.
    print(json.dumps(result))
    sys.stdout.flush()
    device_section_subprocess()
