"""Headline benchmark: full state_dict weight-sync throughput.

Measures the BASELINE.md north-star flow — a trainer publishing a model-scale
state dict and a consumer pulling all of it back (put_state_dict +
get_state_dict round trip) through real storage-volume processes over the
same-host SHM transport. This is the store's data plane end to end: flatten,
commit-marker protocol, metadata RPCs, segment handshakes, and the hot
memcpys.

Host-resident arrays are used deliberately: on this image the TPU chip is
reached through a tunnel whose device->host path measures ~0.01 GB/s, which
would benchmark the tunnel, not the framework. The store's TPU coupling
(NamedSharding put/get) is exercised by the test suite and dryrun_multichip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is value / REFERENCE_GBPS where REFERENCE_GBPS approximates
the reference's CUDA+RDMA same-host weight-sync path (no number is published
by the reference — see BASELINE.md; 10 GB/s is the proxy the north star's
">=80% of the CUDA+RDMA path" is scored against).

Metric definition: DELIVERED bytes per second — each round trip hands N
logical bytes to the store and N to the consumer (2N per iteration),
independent of how many physical copies that took. Zero-copy snapshot gets
and copy-free registered publishes deliver without moving every byte; that
reduction is exactly the optimization under measurement (an RDMA one-sided
read is credited the same way). Physical per-direction rates are printed
on every iteration line so the copy count is never hidden.
"""

import asyncio
import json
import sys
import time

import numpy as np

REFERENCE_GBPS = 10.0

N_TENSORS = 32
TENSOR_MB = 32  # 32 x 32MB = 1 GiB per direction
ITERS = 6  # iter 0 is cold; iters 1+ are the warm set the headline reports


async def device_section() -> None:
    """Device-sourced sync with per-phase timing: separates the accelerator
    D2H cost (tunnel/PCIe — environment-attributable) from the framework's
    data-plane cost. Small payload: this image's TPU tunnel moves
    device->host at ~0.01 GB/s, which would otherwise dominate the bench.
    Best-effort: any device/runtime issue skips the section."""
    import os

    if os.environ.get("TORCHSTORE_TPU_BENCH_DEVICE", "1") in ("0", "false"):
        return
    try:
        import jax

        import torchstore_tpu as ts

        dev = jax.devices()[0]
        n_t, elems = 4, 512 * 1024  # 4 x 2 MB fp32 = 8 MB
        host = [np.random.rand(elems).astype(np.float32) for _ in range(n_t)]
        set_a = {str(i): jax.device_put(h, dev) for i, h in enumerate(host)}
        set_b = {str(i): jax.device_put(h, dev) for i, h in enumerate(host)}
        jax.block_until_ready(list(set_a.values()) + list(set_b.values()))
        total = sum(h.nbytes for h in host)

        # Phase 1: bare serial D2H (the environment's floor; jax caches the
        # host copy, so set_a is consumed by this measurement only).
        t0 = time.perf_counter()
        for a in set_a.values():
            np.asarray(a)
        d2h_s = time.perf_counter() - t0
        # Phase 2: store put of DEVICE arrays (includes overlapped D2H).
        t0 = time.perf_counter()
        await ts.put_state_dict("bench/dev", set_b, store_name="bench")
        put_s = time.perf_counter() - t0
        # Phase 3: host-side get (no device involvement).
        t0 = time.perf_counter()
        out = await ts.get_state_dict("bench/dev", store_name="bench")
        get_s = time.perf_counter() - t0
        np.testing.assert_array_equal(np.asarray(out["0"]), host[0])
        print(
            f"# device-sourced ({total/1e6:.0f} MB on {dev.platform}): "
            f"bare D2H {d2h_s*1e3:.0f} ms ({total/1e9/d2h_s:.3f} GB/s), "
            f"put incl overlapped D2H {put_s*1e3:.0f} ms, "
            f"framework share {max(put_s-d2h_s,0)*1e3:.0f} ms, "
            f"get {get_s*1e3:.0f} ms ({total/1e9/get_s:.2f} GB/s)",
            file=sys.stderr,
        )
    except Exception as exc:  # pragma: no cover - device-env dependent
        print(f"# device-sourced section skipped: {exc!r}", file=sys.stderr)


async def run() -> dict:
    import torchstore_tpu as ts

    await ts.initialize(
        store_name="bench",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    n_elem = TENSOR_MB * 1024 * 1024 // 4
    sd = {
        "layers": {
            str(i): np.random.rand(n_elem).astype(np.float32)
            for i in range(N_TENSORS)
        }
    }
    total_bytes = sum(v.nbytes for v in sd["layers"].values())
    user = {
        "layers": {str(i): np.zeros(n_elem, np.float32) for i in range(N_TENSORS)}
    }

    async def timed_loop(label: str, put_fn, get_fn, src=None, byte_factor=2) -> float:
        """Time ITERS put+get round trips. Each iteration PERTURBS the source
        (so a silently dead data path cannot pass the final verification on
        stale bytes) and validates every tensor. ``byte_factor`` is how many
        times each byte crosses the data plane per iteration (2 for copy
        round trips, 1 when the publish direction is copy-free)."""
        import statistics

        src = src if src is not None else sd
        rates: list[float] = []
        for it in range(ITERS):
            stamp = float(it + 1)
            for arr in src["layers"].values():
                arr[0] = stamp
            t0 = time.perf_counter()
            await put_fn()
            t1 = time.perf_counter()
            out = await get_fn()
            t2 = time.perf_counter()
            gbps = byte_factor * total_bytes / 1e9 / (t2 - t0)
            kind = "delivered" if byte_factor == 2 else "one-way physical"
            rates.append(gbps)
            print(
                f"# {label} iter {it}: put {total_bytes/1e9/(t1-t0):.2f} GB/s, "
                f"get {total_bytes/1e9/(t2-t1):.2f} GB/s, "
                f"{kind} {gbps:.2f} GB/s",
                file=sys.stderr,
            )
            for i in range(N_TENSORS):
                assert out["layers"][str(i)][0] == stamp, f"{label} stale data"
        for i in range(N_TENSORS):
            np.testing.assert_array_equal(
                out["layers"][str(i)], src["layers"][str(i)]
            )
        # Iter 0 is the cold start (first-touch faults, plan building);
        # iters 1+ are the warm steady state an RL loop actually lives in.
        # The headline is the warm MEDIAN — best-of-N would hide warm-path
        # collapses the consumer feels every step (VERDICT r2).
        warm = rates[1:] or rates
        best, median, worst = max(rates), statistics.median(warm), min(warm)
        print(
            f"# {label}: warm median {median:.2f}, best {best:.2f}, "
            f"warm min {worst:.2f} GB/s"
            + (
                "  [WARN: warm min < 50% of best — warm-path collapse]"
                if worst < 0.5 * best
                else ""
            ),
            file=sys.stderr,
        )
        return median

    # Buffered consumer takes zero-copy snapshot views (the jax consumer
    # pattern: device_put straight from the returned views); `user`-dict
    # in-place landing is exercised by the direct path below.
    med_buffered = await timed_loop(
        "buffered",
        lambda: ts.put_state_dict("bench/sd", sd, store_name="bench"),
        lambda: ts.get_state_dict("bench/sd", store_name="bench"),
    )
    # Direct one-hop (the RL steady-state flow): first publish registers
    # staging buffers + builds the dest plan outside the timed loop; the
    # steady state (what a non-adopting trainer pays every step) is
    # refresh + pull with ops writing straight into destination memory.
    await ts.put_state_dict("bench/direct", sd, direct=True, store_name="bench")
    await ts.get_state_dict(
        "bench/direct", user_state_dict=user, direct=True, store_name="bench"
    )
    med_direct = await timed_loop(
        "direct",
        lambda: ts.put_state_dict("bench/direct", sd, direct=True, store_name="bench"),
        lambda: ts.get_state_dict(
            "bench/direct", user_state_dict=user, direct=True, store_name="bench"
        ),
    )
    # Registered-staging variant: the trainer ADOPTS the staging buffers as
    # its weight storage (ts.direct_staging_buffers — registered-memory
    # semantics, like the reference's RDMA-registered regions). Writing a
    # step's weights IS the staging, so a sync step moves each byte exactly
    # ONCE (publish + pull) — reported as one-way GB/s, not double-counted
    # as a round trip, and kept out of the headline for apples-to-apples
    # comparison with the reference metric.
    staging = ts.direct_staging_buffers("bench/direct", store_name="bench")
    assert staging is not None
    await timed_loop(
        "direct+registered",
        lambda: ts.put_state_dict(
            "bench/direct", staging, direct=True, store_name="bench"
        ),
        lambda: ts.get_state_dict(
            "bench/direct", user_state_dict=user, direct=True, store_name="bench"
        ),
        src=staging,
        byte_factor=1,  # publish is copy-free; only the pull moves bytes
    )
    # p50 small-op latency (the BASELINE.json metric's latency half).
    lat_put, lat_get = [], []
    small = np.random.rand(256).astype(np.float32)
    for i in range(40):
        t0 = time.perf_counter()
        await ts.put(f"lat/{i % 4}", small, store_name="bench")
        lat_put.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        await ts.get(f"lat/{i % 4}", store_name="bench")
        lat_get.append(time.perf_counter() - t0)
    p50p = sorted(lat_put)[len(lat_put) // 2] * 1e3
    p50g = sorted(lat_get)[len(lat_get) // 2] * 1e3
    print(f"# p50 latency (1KB): put {p50p:.2f} ms, get {p50g:.2f} ms", file=sys.stderr)

    await device_section()

    await ts.shutdown("bench")
    headline = max(med_buffered, med_direct)
    print(
        f"# headline (warm medians): buffered {med_buffered:.2f} GB/s, "
        f"direct steady-state {med_direct:.2f} GB/s",
        file=sys.stderr,
    )
    return {
        "metric": "state_dict_weight_sync_round_trip",
        "value": round(headline, 3),
        "unit": "GB/s",
        "vs_baseline": round(headline / REFERENCE_GBPS, 3),
    }


if __name__ == "__main__":
    result = asyncio.run(run())
    print(json.dumps(result))
