"""Replication + elastic repair walkthrough.

A 3-volume store with 2-way replicated puts: a volume process is killed
mid-run, reads keep serving from the surviving replica, and ts.repair()
replaces the dead volume and re-replicates its keys. Run:

    python examples/fault_tolerance.py
"""

import asyncio

import numpy as np

import torchstore_tpu as ts

STORE = "ft_example"


async def main() -> None:
    await ts.initialize(
        num_storage_volumes=3,
        strategy=ts.LocalRankStrategy(replication=2),
        store_name=STORE,
    )
    try:
        weights = {f"layer{i}": np.random.rand(256).astype(np.float32) for i in range(4)}
        await ts.put_state_dict("model", weights, store_name=STORE)

        client = ts.client(STORE)
        located = await client.controller.locate_volumes.call_one(["model/layer0"])
        print(f"each key lives on {len(located['model/layer0'])} volumes")

        # Kill one replica's process out from under the store.
        victim = sorted(located["model/layer0"])[0]
        vmap = await client.controller.get_volume_map.call_one()
        target = vmap[victim]["ref"]
        from torchstore_tpu import api

        handle = api._stores[STORE]
        for ref, proc in zip(handle.volume_mesh.refs, handle.volume_mesh._processes):
            if (ref.host, ref.port, ref.name) == (target.host, target.port, target.name):
                proc.kill()
                proc.join(5)
        print(f"killed volume {victim!r}")

        # Reads fail over to the surviving replica.
        out = await ts.get_state_dict("model", store_name=STORE)
        np.testing.assert_array_equal(out["layer0"], weights["layer0"])
        print("reads keep serving from the surviving replica")

        # Heal the fleet: replacement volume + re-replication.
        report = await ts.repair(store_name=STORE)
        print(f"repair: {report}")
        assert report["replaced"] == [victim] and not report["lost"]

        statuses = await client.controller.check_volumes.call_one()
        assert all(s == "ok" for s in statuses.values())
        out = await ts.get_state_dict("model", store_name=STORE)
        np.testing.assert_array_equal(out["layer3"], weights["layer3"])
        print("fleet healthy; replication restored")
    finally:
        await ts.shutdown(STORE)
    print("fault-tolerance example OK")


if __name__ == "__main__":
    asyncio.run(main())
