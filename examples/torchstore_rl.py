"""RL weight sync: a learner actor trains a flax Llama and publishes weights;
generator actors pull them (resharded) and run inference.

Equivalent of the reference's example/torchstore_rl.py, TPU-first: the
learner trains fsdp-sharded on its mesh, generators pull tensor-parallel on
theirs — the store reshards automatically. Publishing rides the versioned
weight channel (WeightPublisher/WeightSubscriber): the learner publishes,
generators BLOCK until a newer version commits (no version bookkeeping, no
polling), and old versions are garbage-collected automatically. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/torchstore_rl.py
"""

import asyncio

import numpy as np

import torchstore_tpu as ts
from torchstore_tpu.runtime import Actor, endpoint, spawn_actors

STORE = "rl_example"
STEPS = 3


def _cpu_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


class Learner(Actor):
    def __init__(self):
        jax = _cpu_jax()
        import jax.numpy as jnp
        import optax

        from torchstore_tpu import parallel
        from torchstore_tpu.models.llama import Llama, LlamaConfig

        self.jax = jax
        cfg = LlamaConfig.tiny()
        self.model = Llama(cfg)
        self.mesh = parallel.make_mesh({"fsdp": 4})
        boxed = self.model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        self.params = parallel.unbox(parallel.shard_params(boxed, self.mesh))
        self.optimizer = optax.adamw(1e-3)
        self.opt_state = self.optimizer.init(self.params)
        self.step_fn = parallel.make_train_step(self.model, self.optimizer)
        self.vocab = cfg.vocab_size
        self.publisher = ts.WeightPublisher("policy", store_name=STORE)

    @endpoint
    async def train_and_publish(self, step: int) -> float:
        jax = self.jax
        tokens = jax.random.randint(
            jax.random.key(step), (4, 16), 0, self.vocab
        )
        self.params, self.opt_state, loss = self.step_fn(
            self.params, self.opt_state, tokens
        )
        await self.publisher.publish({"params": self.params})
        return float(loss)


class Generator(Actor):
    def __init__(self):
        jax = _cpu_jax()
        import jax.numpy as jnp

        from torchstore_tpu import parallel
        from torchstore_tpu.models.llama import Llama, LlamaConfig

        self.jax = jax
        cfg = LlamaConfig.tiny()
        self.model = Llama(cfg)
        self.mesh = parallel.make_mesh({"tp": 8})
        boxed = self.model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        self.template = parallel.unbox(parallel.shard_params(boxed, self.mesh))
        self.subscriber = ts.WeightSubscriber("policy", store_name=STORE)

    @endpoint
    async def sync_and_generate(self) -> list[int]:
        import jax.numpy as jnp

        # Blocks until a version NEWER than the last acquired one commits;
        # the fsdp-sharded push reshards into this mesh's tp layout on pull.
        synced, _version = await self.subscriber.acquire(
            user_state_dict={"params": self.template}, timeout=60.0
        )
        self.template = synced["params"]
        prompt = jnp.zeros((1, 4), jnp.int32)
        logits = self.model.apply(self.template, prompt)
        return [int(t) for t in jnp.argmax(logits[0, -2:], axis=-1)]


async def main():
    await ts.initialize(store_name=STORE)
    learner = await spawn_actors(1, Learner, "learner")
    generators = await spawn_actors(2, Generator, "generator")
    try:
        for step in range(STEPS):
            loss = await learner.train_and_publish.call_one(step)
            outs = await generators.sync_and_generate.call()
            print(f"step {step}: loss={loss:.4f} generator_tokens={outs}")
            assert outs[0] == outs[1], "generators must agree after sync"
    finally:
        await generators.stop()
        await learner.stop()
        await ts.shutdown(STORE)
    print("RL weight-sync example OK")


if __name__ == "__main__":
    asyncio.run(main())
