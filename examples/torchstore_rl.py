"""RL weight sync: a learner actor trains a flax Llama and publishes weights;
generator actors pull them (resharded) and run inference.

Equivalent of the reference's example/torchstore_rl.py, TPU-first: the
learner trains fsdp-sharded on its mesh, generators pull tensor-parallel on
theirs — the store reshards automatically. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/torchstore_rl.py
"""

import asyncio

import numpy as np

import torchstore_tpu as ts
from torchstore_tpu.runtime import Actor, endpoint, spawn_actors

STORE = "rl_example"
STEPS = 3


def _cpu_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


class Learner(Actor):
    def __init__(self):
        jax = _cpu_jax()
        import jax.numpy as jnp
        import optax

        from torchstore_tpu import parallel
        from torchstore_tpu.models.llama import Llama, LlamaConfig

        self.jax = jax
        cfg = LlamaConfig.tiny()
        self.model = Llama(cfg)
        self.mesh = parallel.make_mesh({"fsdp": 4})
        boxed = self.model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        self.params = parallel.unbox(parallel.shard_params(boxed, self.mesh))
        self.optimizer = optax.adamw(1e-3)
        self.opt_state = self.optimizer.init(self.params)
        self.step_fn = parallel.make_train_step(self.model, self.optimizer)
        self.vocab = cfg.vocab_size

    @endpoint
    async def train_and_publish(self, version: int) -> float:
        jax = self.jax
        tokens = jax.random.randint(
            jax.random.key(version), (4, 16), 0, self.vocab
        )
        self.params, self.opt_state, loss = self.step_fn(
            self.params, self.opt_state, tokens
        )
        await ts.put_state_dict(f"policy/v{version}", {"params": self.params},
                                store_name=STORE)
        return float(loss)


class Generator(Actor):
    def __init__(self):
        jax = _cpu_jax()
        import jax.numpy as jnp

        from torchstore_tpu import parallel
        from torchstore_tpu.models.llama import Llama, LlamaConfig

        self.jax = jax
        cfg = LlamaConfig.tiny()
        self.model = Llama(cfg)
        self.mesh = parallel.make_mesh({"tp": 8})
        boxed = self.model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        self.template = parallel.unbox(parallel.shard_params(boxed, self.mesh))

    @endpoint
    async def sync_and_generate(self, version: int) -> list[int]:
        import jax.numpy as jnp

        synced = await ts.get_state_dict(
            f"policy/v{version}", user_state_dict={"params": self.template},
            store_name=STORE,
        )
        self.template = synced["params"]
        prompt = jnp.zeros((1, 4), jnp.int32)
        logits = self.model.apply(self.template, prompt)
        return [int(t) for t in jnp.argmax(logits[0, -2:], axis=-1)]


async def main():
    await ts.initialize(store_name=STORE)
    learner = await spawn_actors(1, Learner, "learner")
    generators = await spawn_actors(2, Generator, "generator")
    try:
        for version in range(STEPS):
            loss = await learner.train_and_publish.call_one(version)
            outs = await generators.sync_and_generate.call(version)
            print(f"step {version}: loss={loss:.4f} generator_tokens={outs}")
            assert outs[0] == outs[1], "generators must agree after sync"
    finally:
        await generators.stop()
        await learner.stop()
        await ts.shutdown(STORE)
    print("RL weight-sync example OK")


if __name__ == "__main__":
    asyncio.run(main())
