"""Expert-parallel (MoE) weight exchange: per-expert keys + cross-layout
re-acquisition — the reference's fully-local DTensor use case
(/root/reference/torchstore/transport/types.py:58-85: expert weights are
Replicate/mesh-1 DTensors that demote to plain tensors, one key per
expert) expressed TPU-style.

An 8-way expert-parallel trainer publishes each expert's FFN matrices
under its own key plus 8-way shards of the shared attention weights; a
4-way inference fleet pulls TWO whole experts per rank and a 4-way
attention reshard (each dest slice spans two stored shards). Run:

    python examples/expert_parallel.py
"""

import asyncio

import numpy as np

import torchstore_tpu as ts

N_EXPERTS, EP_TRAIN, EP_INFER = 8, 8, 4
HIDDEN, FFN = 256, 512


async def main():
    await ts.initialize(store_name="ep")
    try:
        client = ts.client("ep")
        rng = np.random.default_rng(0)
        experts = [
            {
                "w1": rng.standard_normal((HIDDEN, FFN), np.float32),
                "w2": rng.standard_normal((FFN, HIDDEN), np.float32),
            }
            for _ in range(N_EXPERTS)
        ]
        attn_q = rng.standard_normal((HIDDEN, HIDDEN), np.float32)

        # --- trainer side: each of 8 EP ranks publishes ITS expert (plain
        # tensors under per-expert keys) + its attention shard.
        async def publish(rank: int):
            rows = HIDDEN // EP_TRAIN
            sl = ts.TensorSlice(
                offsets=(rank * rows, 0), local_shape=(rows, HIDDEN),
                global_shape=(HIDDEN, HIDDEN), coordinates=(rank,),
                mesh_shape=(EP_TRAIN,),
            )
            await client.put_batch({
                f"moe/e{rank}/w1": experts[rank]["w1"],
                f"moe/e{rank}/w2": experts[rank]["w2"],
                "moe/attn/q": ts.Shard(
                    np.ascontiguousarray(attn_q[rank * rows : (rank + 1) * rows]),
                    sl,
                ),
            })

        await asyncio.gather(*(publish(r) for r in range(EP_TRAIN)))
        print(f"published {N_EXPERTS} experts (ep={EP_TRAIN}) + attention shards")

        # --- inference side: 4 EP ranks, each acquiring TWO whole experts
        # and its 4-way attention reshard (spans two stored shards).
        async def acquire(rank: int):
            per = N_EXPERTS // EP_INFER
            rows = HIDDEN // EP_INFER
            sl = ts.TensorSlice(
                offsets=(rank * rows, 0), local_shape=(rows, HIDDEN),
                global_shape=(HIDDEN, HIDDEN), coordinates=(rank,),
                mesh_shape=(EP_INFER,),
            )
            wants = {"moe/attn/q": ts.Shard(None, sl)}
            for e in range(rank * per, (rank + 1) * per):
                wants[f"moe/e{e}/w1"] = None
                wants[f"moe/e{e}/w2"] = None
            return rank, await client.get_batch(wants)

        results = dict(await asyncio.gather(*(acquire(r) for r in range(EP_INFER))))
        for rank, got in sorted(results.items()):
            per = N_EXPERTS // EP_INFER
            for e in range(rank * per, (rank + 1) * per):
                np.testing.assert_array_equal(
                    got[f"moe/e{e}/w1"], experts[e]["w1"]
                )
            rows = HIDDEN // EP_INFER
            np.testing.assert_array_equal(
                got["moe/attn/q"], attn_q[rank * rows : (rank + 1) * rows]
            )
        print(
            f"{EP_INFER} inference ranks each acquired "
            f"{N_EXPERTS // EP_INFER} whole experts + a resharded "
            "attention slice — exact"
        )
    finally:
        await ts.shutdown("ep")


if __name__ == "__main__":
    asyncio.run(main())
    print("expert-parallel example OK")
