"""Resharding demo: put a jax.Array on one mesh layout, get it on another,
with PUT/GET wall-time printed (equivalent of the reference's
example/dtensor.py). Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/reshard.py
"""

import asyncio
import time

import numpy as np

import torchstore_tpu as ts


async def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    await ts.initialize(store_name="reshard")
    try:
        devs = np.array(jax.devices())
        mesh_src = Mesh(devs.reshape(2, 4), ("x", "y"))
        mesh_dst = Mesh(devs.reshape(4, 2), ("a", "b"))
        global_arr = np.arange(1024 * 768, dtype=np.float32).reshape(1024, 768)

        src = jax.device_put(global_arr, NamedSharding(mesh_src, P("x", "y")))
        t0 = time.perf_counter()
        await ts.put("weights", src, store_name="reshard")
        t1 = time.perf_counter()
        print(f"PUT 2x4 mesh ({global_arr.nbytes/1e6:.1f} MB): {t1-t0:.4f}s")

        like = jax.device_put(
            np.zeros_like(global_arr), NamedSharding(mesh_dst, P("b", "a"))
        )
        t0 = time.perf_counter()
        out = await ts.get("weights", like=like, store_name="reshard")
        t1 = time.perf_counter()
        print(f"GET as 4x2 mesh (transposed spec): {t1-t0:.4f}s")

        np.testing.assert_array_equal(np.asarray(out), global_arr)
        print("reshard example OK:", out.sharding)
    finally:
        await ts.shutdown("reshard")


if __name__ == "__main__":
    asyncio.run(main())
