"""SPMD demo: N ranks bootstrap one store collectively and exchange tensors
(equivalent of the reference's example/torchstore_spmd.py). This launcher
spawns the ranks itself; under a real multi-host launcher just run the
worker body on every rank. Run:

    python examples/spmd.py
"""

import asyncio
import multiprocessing as mp
import os

import numpy as np

WORLD = 4


def worker(rank: int, port: int) -> None:
    os.environ.update(
        {
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(WORLD),
            "LOCAL_WORLD_SIZE": str(WORLD),
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        }
    )
    asyncio.run(body(rank))


async def body(rank: int) -> None:
    import torchstore_tpu as ts

    await ts.initialize_spmd(store_name="spmd_demo")
    await ts.put(f"{rank}_tensor", np.full(4, float(rank)), store_name="spmd_demo")
    await ts.barrier("puts", store_name="spmd_demo")
    other = (rank + 1) % WORLD
    fetched = await ts.get(f"{other}_tensor", store_name="spmd_demo")
    print(f"Rank=[{rank}] fetched {fetched} from rank {other}")
    await ts.barrier("reads", store_name="spmd_demo")
    await ts.shutdown("spmd_demo")


def main() -> None:
    from torchstore_tpu.utils import get_free_port

    port = get_free_port()
    ctx = mp.get_context("spawn")
    procs = [ctx.Process(target=worker, args=(r, port)) for r in range(WORLD)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(120)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
    print("SPMD example OK")


if __name__ == "__main__":
    main()
