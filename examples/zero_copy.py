"""The two copy-free fast paths, end to end:

1. Zero-copy reads: a same-host consumer's ``get_state_dict`` returns
   immutable snapshot VIEWS of the store's shared-memory segments — no
   read copy at all, and later puts never mutate a held view (the volume
   rotates segments instead of overwriting leased ones).
2. Registered staging: the trainer ADOPTS the direct-sync staging buffers
   as its weight storage (``ts.direct_staging_buffers``) — every later
   direct put is a pure metadata publish, zero source-side copies (the
   host analog of RDMA registered memory).

Run:  python examples/zero_copy.py
"""

import asyncio
import time

import numpy as np

import torchstore_tpu as ts

MB = 1024 * 1024


async def main():
    await ts.initialize(store_name="zc_demo")
    try:
        sd = {"layers": {str(i): np.random.rand(4 * MB // 4).astype(np.float32)
                         for i in range(4)}}
        nbytes = sum(a.nbytes for a in sd["layers"].values())

        # --- 1. zero-copy reads ------------------------------------------
        await ts.put_state_dict("policy", sd, store_name="zc_demo")
        t0 = time.perf_counter()
        snap = await ts.get_state_dict("policy", store_name="zc_demo")
        dt = time.perf_counter() - t0
        view = snap["layers"]["0"]
        assert not view.flags.writeable  # immutable snapshot view
        print(f"zero-copy get of {nbytes / 1e6:.0f} MB in {dt * 1e3:.1f} ms "
              f"({nbytes / 1e9 / dt:.0f} GB/s nominal — no bytes moved)")

        # Snapshot isolation: a NEW push does not mutate the held view.
        before = float(view[0])
        sd["layers"]["0"][0] = -1.0
        await ts.put_state_dict("policy", sd, store_name="zc_demo")
        assert float(view[0]) == before  # old snapshot unchanged
        fresh = await ts.get_state_dict("policy", store_name="zc_demo")
        assert float(fresh["layers"]["0"][0]) == -1.0
        print("snapshot isolation holds: held view kept its value, "
              "fresh get sees the new push")

        # --- 2. registered staging (copy-free publishes) -----------------
        await ts.put_state_dict("policy_direct", sd, direct=True,
                                store_name="zc_demo")
        staging = ts.direct_staging_buffers("policy_direct",
                                            store_name="zc_demo")
        # Trainer writes a step's weights straight into the staging buffers
        # (in a real loop this IS the optimizer output buffer)...
        staging["layers"]["0"][0] = 42.0
        t0 = time.perf_counter()
        await ts.put_state_dict("policy_direct", staging, direct=True,
                                store_name="zc_demo")
        dt = time.perf_counter() - t0
        print(f"registered publish of {nbytes / 1e6:.0f} MB in "
              f"{dt * 1e3:.2f} ms (metadata only)")
        user = {"layers": {k: np.zeros_like(v)
                           for k, v in sd["layers"].items()}}
        out = await ts.get_state_dict("policy_direct", user_state_dict=user,
                                      direct=True, store_name="zc_demo")
        assert out["layers"]["0"][0] == 42.0
        print("zero-copy example OK")
    finally:
        await ts.shutdown("zc_demo")


if __name__ == "__main__":
    asyncio.run(main())
