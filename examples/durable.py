"""Durable store + crash recovery: put a checkpoint, hard-kill the volume
processes (no teardown), restart over the same directory, read everything
back. Run:

    python examples/durable.py
"""

import asyncio
import tempfile

import numpy as np

import torchstore_tpu as ts


async def main():
    storage = tempfile.mkdtemp(prefix="ts_durable_demo_")
    await ts.initialize(store_name="durable", storage_dir=storage)
    weights = np.random.rand(512, 256).astype(np.float32)
    await ts.put_state_dict(
        "ckpt/step100", {"weights": weights, "meta": {"step": 100}},
        store_name="durable",
    )
    print(f"wrote checkpoint to disk-backed store at {storage}")

    # --- simulate a crash: kill volumes, drop all local state -------------
    from torchstore_tpu import api
    from torchstore_tpu.runtime import stop_singleton

    handle = api._stores.pop("durable")
    for proc in handle.volume_mesh._processes:
        proc.terminate()
        proc.join(5)
    await stop_singleton("ts_durable_controller")
    print("volumes killed without teardown (simulated crash)")

    # --- recover ----------------------------------------------------------
    await ts.initialize(store_name="durable", storage_dir=storage, recover=True)
    restored = await ts.get_state_dict("ckpt/step100", store_name="durable")
    np.testing.assert_array_equal(restored["weights"], weights)
    assert restored["meta"]["step"] == 100
    print("recovered checkpoint after restart:", list(restored))
    await ts.shutdown("durable")
    print("durable example OK")


if __name__ == "__main__":
    asyncio.run(main())
