"""Direct one-hop weight sync: the store carries only metadata handles; the
consumer pulls straight from the trainer's staging buffers (SHM on the same
host). This is the steady-state RL weight-sync fast path. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/direct_sync.py
"""

import asyncio
import time

import numpy as np

import torchstore_tpu as ts


async def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    await ts.initialize(store_name="direct_demo")
    try:
        devs = np.array(jax.devices())
        w = np.random.rand(1024, 512).astype(np.float32)
        trainer_sd = {
            "w": jax.device_put(
                w, NamedSharding(Mesh(devs.reshape(8), ("fsdp",)), P("fsdp", None))
            )
        }
        consumer_sd = {"w": np.zeros_like(w)}

        # First publish registers staging buffers; first pull builds the plan.
        await ts.put_state_dict("policy", trainer_sd, direct=True,
                                store_name="direct_demo")
        await ts.get_state_dict("policy", user_state_dict=consumer_sd,
                                direct=True, store_name="direct_demo")

        # Steady state: refresh + pull, writing straight into consumer memory.
        for step in range(3):
            t0 = time.perf_counter()
            await ts.put_state_dict("policy", trainer_sd, direct=True,
                                    store_name="direct_demo")
            out = await ts.get_state_dict("policy", user_state_dict=consumer_sd,
                                          direct=True, store_name="direct_demo")
            dt = time.perf_counter() - t0
            np.testing.assert_array_equal(out["w"], w)
            print(f"step {step}: sync {2 * w.nbytes / 1e6:.1f} MB in {dt*1e3:.1f} ms")
    finally:
        await ts.shutdown("direct_demo")
    print("direct sync example OK")


if __name__ == "__main__":
    asyncio.run(main())
