"""torch.Tensor interop: reference users hold torch state dicts everywhere
(/root/reference/torchstore APIs take/return torch.Tensor); this build must
accept them transparently with zero-copy views and in-place get semantics.
Covers put/get round trips, bf16 reinterpretation, in-place targets
returning the caller's tensor objects, state-dict sync (buffered + direct),
transfer_dtype casting, and sharded Shard data."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import ml_dtypes  # noqa: E402

import torchstore_tpu as ts  # noqa: E402
from torchstore_tpu import torch_interop  # noqa: E402
from torchstore_tpu.client import Shard  # noqa: E402
from torchstore_tpu.transport.types import TensorSlice  # noqa: E402


class TestViews:
    def test_zero_copy_fp32(self):
        t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
        view = torch_interop.to_numpy_view(t)
        assert view.dtype == np.float32
        view[0, 0] = 42.0
        assert t[0, 0].item() == 42.0  # shared memory

    def test_bf16_reinterpret(self):
        t = torch.tensor([1.5, -2.25, 3.0], dtype=torch.bfloat16)
        view = torch_interop.to_numpy_view(t)
        assert view.dtype == ml_dtypes.bfloat16
        np.testing.assert_array_equal(
            view.astype(np.float32), np.array([1.5, -2.25, 3.0], np.float32)
        )
        # Shared memory: writes through the view surface in the tensor.
        view[1] = ml_dtypes.bfloat16(7.0)
        assert t[1].item() == 7.0

    def test_noncontiguous_strided_view_shares_memory(self):
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4).t()
        view = torch_interop.to_numpy_view(t)
        view[0, 0] = -1.0
        assert t[0, 0].item() == -1.0

    def test_noncontiguous_bf16_inplace_target_rejected(self):
        t = torch.zeros(3, 4, dtype=torch.bfloat16).t()
        with pytest.raises(TypeError, match="contiguous"):
            torch_interop.to_numpy_view(t, allow_copy=False)

    def test_requires_grad_detached(self):
        t = torch.ones(3, requires_grad=True)
        view = torch_interop.to_numpy_view(t)
        np.testing.assert_array_equal(view, np.ones(3, np.float32))

    def test_convert_tree_identity_without_torch_leaves(self):
        sd = {"a": np.ones(2), "b": [1, 2]}
        assert torch_interop.convert_tree(sd) is sd


@pytest.fixture
async def store():
    await ts.initialize(store_name="tint")
    yield "tint"
    await ts.shutdown("tint")


async def test_put_get_roundtrip(store):
    t = torch.randn(64, 32)
    await ts.put("w", t, store_name=store)
    out = await ts.get("w", store_name=store)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, t.numpy())


async def test_put_bf16_roundtrip(store):
    t = torch.randn(16, 8).to(torch.bfloat16)
    await ts.put("wb", t, store_name=store)
    out = await ts.get("wb", store_name=store)
    assert out.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        out.astype(np.float32),
        t.float().numpy(),
    )


async def test_inplace_get_returns_same_tensor(store):
    src = torch.randn(8, 8)
    await ts.put("x", src, store_name=store)
    dest = torch.zeros(8, 8)
    out = await ts.get("x", like=dest, store_name=store)
    assert out is dest  # caller's tensor object, filled in place
    torch.testing.assert_close(dest, src)


async def test_shard_put_and_sliced_get(store):
    full = torch.arange(16, dtype=torch.float32).reshape(4, 4)
    for row in range(2):
        sl = TensorSlice(
            offsets=(row * 2, 0),
            local_shape=(2, 4),
            global_shape=(4, 4),
            coordinates=(row,),
            mesh_shape=(2,),
        )
        await ts.put("sh", Shard(full[row * 2 : row * 2 + 2], sl), store_name=store)
    out = await ts.get("sh", store_name=store)
    np.testing.assert_array_equal(out, full.numpy())
    # In-place sliced get into a torch buffer.
    dest = torch.zeros(2, 4)
    want = TensorSlice(
        offsets=(1, 0),
        local_shape=(2, 4),
        global_shape=(4, 4),
        coordinates=(0,),
        mesh_shape=(1,),
    )
    got = await ts.get("sh", like=Shard(dest, want), store_name=store)
    assert got is dest
    torch.testing.assert_close(dest, full[1:3])


async def test_state_dict_roundtrip(store):
    sd = {
        "model": {"w": torch.randn(32, 16), "b": torch.zeros(16)},
        "step": 3,
    }
    await ts.put_state_dict("ckpt", sd, store_name=store)
    out = await ts.get_state_dict("ckpt", store_name=store)
    np.testing.assert_array_equal(out["model"]["w"], sd["model"]["w"].numpy())
    assert out["step"] == 3


async def test_state_dict_inplace_user_dict(store):
    sd = {"w": torch.randn(16, 16), "b": torch.randn(16)}
    await ts.put_state_dict("m", sd, store_name=store)
    user = {"w": torch.zeros(16, 16), "b": torch.zeros(16)}
    out = await ts.get_state_dict("m", user_state_dict=user, store_name=store)
    # The user's tensor objects come back, filled.
    assert out["w"] is user["w"] and out["b"] is user["b"]
    torch.testing.assert_close(user["w"], sd["w"])
    torch.testing.assert_close(user["b"], sd["b"])


async def test_state_dict_transfer_dtype(store):
    sd = {"w": torch.ones(8, dtype=torch.float32), "n": torch.arange(4)}
    await ts.put_state_dict(
        "cast", sd, transfer_dtype=ml_dtypes.bfloat16, store_name=store
    )
    out = await ts.get_state_dict("cast", store_name=store)
    assert out["w"].dtype == ml_dtypes.bfloat16
    assert out["n"].dtype == np.int64  # non-floating leaves uncast


async def test_direct_sync_torch_leaves(store):
    sd = {"w": torch.randn(64, 64), "b": torch.randn(64)}
    await ts.put_state_dict("dsync", sd, direct=True, store_name=store)
    user = {"w": torch.zeros(64, 64), "b": torch.zeros(64)}
    out = await ts.get_state_dict(
        "dsync", user_state_dict=user, direct=True, store_name=store
    )
    assert out["w"] is user["w"]
    torch.testing.assert_close(user["w"], sd["w"])
    torch.testing.assert_close(user["b"], sd["b"])
    # Refresh: trainer mutates weights in place, republish, re-pull.
    with torch.no_grad():
        sd["w"].add_(1.0)
    await ts.put_state_dict("dsync", sd, direct=True, store_name=store)
    out = await ts.get_state_dict(
        "dsync", user_state_dict=user, direct=True, store_name=store
    )
    torch.testing.assert_close(user["w"], sd["w"])


async def test_direct_get_noncontiguous_bf16_target_rejected(store):
    # A non-contiguous bf16 in-place target cannot be viewed zero-copy; the
    # direct path must refuse loudly rather than fill a silent copy.
    sd = {"w": torch.randn(8, 8).to(torch.bfloat16)}
    await ts.put_state_dict("ncbf", sd, direct=True, store_name=store)
    user = {"w": torch.zeros(8, 8, dtype=torch.bfloat16).t()}
    with pytest.raises(TypeError, match="contiguous"):
        await ts.get_state_dict(
            "ncbf", user_state_dict=user, direct=True, store_name=store
        )


async def test_direct_shard_torch_targets(store):
    # Shard(torch_tensor, slice) leaves must work on the direct path too
    # (MIGRATION.md promises Shard.data takes torch tensors everywhere).
    sd = {"w": torch.randn(8, 4)}
    await ts.put_state_dict("dshard", sd, direct=True, store_name=store)
    dest = torch.zeros(8, 4)
    sl = TensorSlice(
        offsets=(0, 0),
        local_shape=(8, 4),
        global_shape=(8, 4),
        coordinates=(0,),
        mesh_shape=(1,),
    )
    user = {"w": Shard(dest, sl)}
    out = await ts.get_state_dict(
        "dshard", user_state_dict=user, direct=True, store_name=store, strict=False
    )
    assert out["w"] is user["w"]  # the caller's Shard, its tensor filled
    torch.testing.assert_close(dest, sd["w"])


async def test_object_key_with_torch_target_returns_object(store):
    # A key stored as a plain object must come back as the object, never as
    # a silently unfilled tensor (parity with numpy like targets).
    await ts.put("obj", {"a": 1}, store_name=store)
    out = await ts.get("obj", like=torch.zeros(3), store_name=store)
    assert out == {"a": 1}


async def test_inplace_get_noncontiguous_fp32_target(store):
    # Non-bf16 strided tensors view zero-copy; in-place get works.
    src = torch.randn(4, 6)
    await ts.put("strided", src, store_name=store)
    dest = torch.zeros(6, 4).t()  # non-contiguous (4, 6) view
    out = await ts.get("strided", like=dest, store_name=store)
    assert out is dest
    torch.testing.assert_close(dest, src)


async def test_optimizer_style_nested_dict(store):
    # Mirrors reference test_state_dict model+optimizer round trips.
    sd = {
        "model": {"layers": [torch.randn(4, 4) for _ in range(3)]},
        "optim": {
            "state": {0: {"exp_avg": torch.randn(4, 4), "step": torch.tensor(9)}},
            "param_groups": [{"lr": 0.1}],
        },
    }
    await ts.put_state_dict("full", sd, store_name=store)
    out = await ts.get_state_dict("full", store_name=store)
    np.testing.assert_array_equal(
        out["model"]["layers"][1], sd["model"]["layers"][1].numpy()
    )
    np.testing.assert_array_equal(
        out["optim"]["state"][0]["exp_avg"], sd["optim"]["state"][0]["exp_avg"].numpy()
    )
    assert out["optim"]["param_groups"][0]["lr"] == 0.1
