"""k-way replication: puts land on the primary plus ring-successor
replicas, gets fail over transparently when a replica dies, deletes clean
every copy. Beyond the reference (which stores each key exactly once and
loses it with its volume)."""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.client import Shard
from torchstore_tpu.runtime import ActorDiedError
from torchstore_tpu.strategy import LocalRankStrategy
from torchstore_tpu.transport.types import TensorSlice


async def _kill_volume(store_name: str, volume_id: str) -> None:
    """Kill the process hosting ``volume_id`` (match refs by identity
    triple — pickled ActorRefs don't compare equal to the mesh's)."""
    from torchstore_tpu import api

    client = ts.client(store_name)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap[volume_id]["ref"]
    handle = api._stores[store_name]
    meshes = [handle.volume_mesh, *(handle.repair_meshes or [])]
    for mesh in meshes:
        for idx, ref in enumerate(mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = mesh._processes[idx]
                proc.kill()
                proc.join(5)
                return
    raise AssertionError(f"no process found for volume {volume_id!r}")


@pytest.fixture
async def store():
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="repl",
    )
    yield "repl"
    await ts.shutdown("repl")


async def test_put_indexes_on_two_volumes(store):
    await ts.put("k", np.arange(8.0, dtype=np.float32), store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["k"])
    assert len(located["k"]) == 2  # primary + 1 replica
    out = await ts.get("k", store_name=store)
    np.testing.assert_array_equal(out, np.arange(8.0, dtype=np.float32))


async def test_ring_selection_is_deterministic():
    s = LocalRankStrategy(replication=2)
    vols = ["0", "1", "2"]
    assert s.select_put_volume_ids("1", vols) == ["1", "2"]
    assert s.select_put_volume_ids("2", vols) == ["2", "0"]  # wraps
    with pytest.raises(ValueError, match="replication=4"):
        LocalRankStrategy(replication=4).select_put_volume_ids("0", vols)


async def test_replication_exceeding_volumes_rejected():
    with pytest.raises(ValueError, match="replication=3"):
        await ts.initialize(
            num_storage_volumes=2,
            strategy=LocalRankStrategy(replication=3),
            store_name="repl_bad",
        )


async def test_get_survives_volume_death(store):
    src = np.random.rand(64, 64).astype(np.float32)
    await ts.put("w", src, store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["w"])
    primary = sorted(located["w"])[0]
    await _kill_volume(store, primary)
    # First get may pay a diagnosis round trip; it must SUCCEED from the
    # surviving replica, not raise.
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)
    # And keep succeeding (dead volume now deprioritized).
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)


async def test_unreplicated_key_on_dead_volume_still_fails():
    # replication=1 control: a volume death LOSES its keys; the error must
    # surface rather than silently serving stale/empty data.
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=1),
        store_name="repl1",
    )
    try:
        await ts.put("only", np.ones(4), store_name="repl1")
        client = ts.client("repl1")
        located = await client.controller.locate_volumes.call_one(["only"])
        (vid,) = located["only"]
        await _kill_volume("repl1", vid)
        with pytest.raises((ActorDiedError, ConnectionError, OSError)):
            await ts.get("only", store_name="repl1")
    finally:
        await ts.shutdown("repl1")


async def test_sharded_replicated_roundtrip(store):
    # Each shard of a sharded key replicates; a resharded read assembles
    # from whichever replicas answer.
    full = np.arange(32.0, dtype=np.float32).reshape(4, 8)
    for row in range(4):
        sl = TensorSlice(
            offsets=(row, 0),
            local_shape=(1, 8),
            global_shape=(4, 8),
            coordinates=(row,),
            mesh_shape=(4,),
        )
        await ts.put("sh", Shard(full[row : row + 1], sl), store_name=store)
    out = await ts.get("sh", store_name=store)
    np.testing.assert_array_equal(out, full)


async def test_state_dict_replicated_with_failover(store):
    sd = {"a": np.random.rand(32).astype(np.float32), "b": np.arange(4)}
    await ts.put_state_dict("ck", sd, store_name=store)
    # Kill the primary (client id "0" -> volume "0" under LocalRank).
    await _kill_volume(store, "0")
    out = await ts.get_state_dict("ck", store_name=store)
    np.testing.assert_array_equal(out["a"], sd["a"])
    np.testing.assert_array_equal(out["b"], sd["b"])


async def test_bulk_transport_failover():
    # Volume death on the bulk transport surfaces as ConnectionError, not
    # ActorDiedError — failover must normalize and still serve from the
    # surviving replica.
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2, default_transport_type="bulk"),
        store_name="replb",
    )
    try:
        src = np.random.rand(1024).astype(np.float32)
        await ts.put("w", src, store_name="replb")
        client = ts.client("replb")
        located = await client.controller.locate_volumes.call_one(["w"])
        await _kill_volume("replb", sorted(located["w"])[0])
        out = await ts.get("w", store_name="replb")
        np.testing.assert_array_equal(out, src)
    finally:
        await ts.shutdown("replb")


async def test_degraded_overwrite_stays_consistent(store):
    """An overwrite that lands on only SOME replicas must not leave the
    failed replica serving the old value under committed metadata: the put
    succeeds at degraded redundancy and the stale copy is detached."""
    v1 = np.full(16, 1.0, np.float32)
    v2 = np.full(16, 2.0, np.float32)
    await ts.put("k", v1, store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["k"])
    replicas = sorted(located["k"])
    assert len(replicas) == 2
    await _kill_volume(store, replicas[1])
    # Overwrite: one replica is dead — the put succeeds (degraded) and the
    # dead replica's stale entry is detached from the index.
    await ts.put("k", v2, store_name=store)
    located = await client.controller.locate_volumes.call_one(["k"])
    assert replicas[1] not in located["k"]
    # Every read sees v2 — no divergence window.
    for _ in range(4):
        out = await ts.get("k", store_name=store)
        np.testing.assert_array_equal(out, v2)


async def test_delete_cleans_every_replica(store):
    await ts.put("gone", np.ones(4), store_name=store)
    await ts.delete("gone", store_name=store)
    assert not await ts.exists("gone", store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(
        ["gone"], missing_ok=True
    )
    assert located == {}


async def test_detached_stale_copy_reclaimed_and_not_served():
    """ADVICE r2 (medium): after a degraded replicated overwrite, the
    failed-but-ALIVE replica still holds the OLD bytes, and clients with
    warm location caches would read them. The controller must best-effort
    delete the stale copy once the replica recovers, so stale-cache reads
    fail over to the fresh value instead of silently serving v1."""
    import os
    import signal

    from torchstore_tpu.client import LocalClient
    from torchstore_tpu.config import StoreConfig

    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="reclaim",
        config=StoreConfig(rpc_timeout=2.0),
    )
    stopped = []
    try:
        v1 = np.full(8, 1.0, np.float32)
        v2 = np.full(8, 2.0, np.float32)
        await ts.put("k", v1, store_name="reclaim")
        client = ts.client("reclaim")
        # A second client with a WARM location cache for k.
        cli2 = LocalClient(client.controller, client._config)
        out = await cli2.get("k")
        np.testing.assert_array_equal(out, v1)
        assert "k" in cli2._loc_cache and len(cli2._loc_cache["k"]) == 2

        # Wedge volume "1" (alive but stuck) and overwrite at degraded
        # redundancy.
        from torchstore_tpu import api

        handle = api._stores["reclaim"]
        vmap = await client.controller.get_volume_map.call_one()
        target = vmap["1"]["ref"]
        proc = None
        for idx, ref in enumerate(handle.volume_mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host, target.port, target.name,
            ):
                proc = handle.volume_mesh._processes[idx]
        assert proc is not None
        os.kill(proc.pid, signal.SIGSTOP)
        stopped.append(proc.pid)
        await ts.put("k", v2, store_name="reclaim")
        located = await client.controller.locate_volumes.call_one(["k"])
        assert set(located["k"]) == {"0"}  # detached from the index

        # Recover the wedged replica; the controller's background reclaim
        # deletes its stale copy (first retry fires ~1s after the detach).
        os.kill(proc.pid, signal.SIGCONT)
        stopped.clear()
        deadline = asyncio.get_event_loop().time() + 30
        while True:
            stats = await target.stats.call_one()
            if stats["entries"] == 0:
                break
            assert asyncio.get_event_loop().time() < deadline, (
                f"stale copy never reclaimed: {stats}"
            )
            await asyncio.sleep(0.5)

        # The warm-cached client must now see v2, never v1: its cached
        # location for volume "1" finds no data and fails over.
        cli2._loc_cache["k"] = {
            "1": cli2._loc_cache["k"]["1"]
        }  # pin the cache to the stale replica
        out2 = await cli2.get("k")
        np.testing.assert_array_equal(out2, v2)
    finally:
        for pid in stopped:
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        await ts.shutdown("reclaim")
