"""k-way replication: puts land on the primary plus ring-successor
replicas, gets fail over transparently when a replica dies, deletes clean
every copy. Beyond the reference (which stores each key exactly once and
loses it with its volume)."""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.client import Shard
from torchstore_tpu.runtime import ActorDiedError
from torchstore_tpu.strategy import LocalRankStrategy
from torchstore_tpu.transport.types import TensorSlice


async def _kill_volume(store_name: str, volume_id: str) -> None:
    """Kill the process hosting ``volume_id`` (match refs by identity
    triple — pickled ActorRefs don't compare equal to the mesh's)."""
    from torchstore_tpu import api

    client = ts.client(store_name)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap[volume_id]["ref"]
    handle = api._stores[store_name]
    meshes = [handle.volume_mesh, *(handle.repair_meshes or [])]
    for mesh in meshes:
        for idx, ref in enumerate(mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = mesh._processes[idx]
                proc.kill()
                proc.join(5)
                return
    raise AssertionError(f"no process found for volume {volume_id!r}")


@pytest.fixture
async def store():
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="repl",
    )
    yield "repl"
    await ts.shutdown("repl")


async def test_put_indexes_on_two_volumes(store):
    await ts.put("k", np.arange(8.0, dtype=np.float32), store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["k"])
    assert len(located["k"]) == 2  # primary + 1 replica
    out = await ts.get("k", store_name=store)
    np.testing.assert_array_equal(out, np.arange(8.0, dtype=np.float32))


async def test_ring_selection_is_deterministic():
    s = LocalRankStrategy(replication=2)
    vols = ["0", "1", "2"]
    assert s.select_put_volume_ids("1", vols) == ["1", "2"]
    assert s.select_put_volume_ids("2", vols) == ["2", "0"]  # wraps
    with pytest.raises(ValueError, match="replication=4"):
        LocalRankStrategy(replication=4).select_put_volume_ids("0", vols)


async def test_replication_exceeding_volumes_rejected():
    with pytest.raises(ValueError, match="replication=3"):
        await ts.initialize(
            num_storage_volumes=2,
            strategy=LocalRankStrategy(replication=3),
            store_name="repl_bad",
        )


async def test_get_survives_volume_death(store):
    src = np.random.rand(64, 64).astype(np.float32)
    await ts.put("w", src, store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["w"])
    primary = sorted(located["w"])[0]
    await _kill_volume(store, primary)
    # First get may pay a diagnosis round trip; it must SUCCEED from the
    # surviving replica, not raise.
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)
    # And keep succeeding (dead volume now deprioritized).
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)


async def test_unreplicated_key_on_dead_volume_still_fails():
    # replication=1 control: a volume death LOSES its keys; the error must
    # surface rather than silently serving stale/empty data.
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=1),
        store_name="repl1",
    )
    try:
        await ts.put("only", np.ones(4), store_name="repl1")
        client = ts.client("repl1")
        located = await client.controller.locate_volumes.call_one(["only"])
        (vid,) = located["only"]
        await _kill_volume("repl1", vid)
        with pytest.raises((ActorDiedError, ConnectionError, OSError)):
            await ts.get("only", store_name="repl1")
    finally:
        await ts.shutdown("repl1")


async def test_sharded_replicated_roundtrip(store):
    # Each shard of a sharded key replicates; a resharded read assembles
    # from whichever replicas answer.
    full = np.arange(32.0, dtype=np.float32).reshape(4, 8)
    for row in range(4):
        sl = TensorSlice(
            offsets=(row, 0),
            local_shape=(1, 8),
            global_shape=(4, 8),
            coordinates=(row,),
            mesh_shape=(4,),
        )
        await ts.put("sh", Shard(full[row : row + 1], sl), store_name=store)
    out = await ts.get("sh", store_name=store)
    np.testing.assert_array_equal(out, full)


async def test_state_dict_replicated_with_failover(store):
    sd = {"a": np.random.rand(32).astype(np.float32), "b": np.arange(4)}
    await ts.put_state_dict("ck", sd, store_name=store)
    # Kill the primary (client id "0" -> volume "0" under LocalRank).
    await _kill_volume(store, "0")
    out = await ts.get_state_dict("ck", store_name=store)
    np.testing.assert_array_equal(out["a"], sd["a"])
    np.testing.assert_array_equal(out["b"], sd["b"])


async def test_bulk_transport_failover():
    # Volume death on the bulk transport surfaces as ConnectionError, not
    # ActorDiedError — failover must normalize and still serve from the
    # surviving replica.
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2, default_transport_type="bulk"),
        store_name="replb",
    )
    try:
        src = np.random.rand(1024).astype(np.float32)
        await ts.put("w", src, store_name="replb")
        client = ts.client("replb")
        located = await client.controller.locate_volumes.call_one(["w"])
        await _kill_volume("replb", sorted(located["w"])[0])
        out = await ts.get("w", store_name="replb")
        np.testing.assert_array_equal(out, src)
    finally:
        await ts.shutdown("replb")


async def test_degraded_overwrite_stays_consistent(store):
    """An overwrite that lands on only SOME replicas must not leave the
    failed replica serving the old value under committed metadata: the put
    succeeds at degraded redundancy and the stale copy is detached."""
    v1 = np.full(16, 1.0, np.float32)
    v2 = np.full(16, 2.0, np.float32)
    await ts.put("k", v1, store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["k"])
    replicas = sorted(located["k"])
    assert len(replicas) == 2
    await _kill_volume(store, replicas[1])
    # Overwrite: one replica is dead — the put succeeds (degraded) and the
    # dead replica's stale entry is detached from the index.
    await ts.put("k", v2, store_name=store)
    located = await client.controller.locate_volumes.call_one(["k"])
    assert replicas[1] not in located["k"]
    # Every read sees v2 — no divergence window.
    for _ in range(4):
        out = await ts.get("k", store_name=store)
        np.testing.assert_array_equal(out, v2)


async def test_delete_cleans_every_replica(store):
    await ts.put("gone", np.ones(4), store_name=store)
    await ts.delete("gone", store_name=store)
    assert not await ts.exists("gone", store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(
        ["gone"], missing_ok=True
    )
    assert located == {}


async def test_reclaim_never_deletes_a_put_that_raced_it():
    """ADVICE r3 (medium): a put landing on the volume while the reclaim's
    delete is in flight must keep its bytes. The reclaim delete is
    conditional on the stale write generation: a racing put bumps the
    volume's generation, so the volume reports the key fresh instead of
    deleting an acknowledged overwrite — even when this volume is the only
    replica (controller-level deterministic re-enactment of the race)."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()

    class FakeVolume:
        """Volume ref exposing only what the reclaim drainer touches, with
        a write-generation table mirroring StorageVolume's."""

        def __init__(self):
            self.kv = {}
            self.gens = {}
            self.deleted = []

        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            return self._Ep(getattr(self, f"_{name}"))

        async def _delete_batch_if(self, items):
            removed, kept, kept_gens = [], [], {}
            for key, stale_gen in items:
                cur = self.gens.get(key)
                if cur is not None and cur > stale_gen:
                    kept.append(key)
                    kept_gens[key] = cur
                    continue
                if self.kv.pop(key, None) is not None:
                    removed.append(key)
                    self.deleted.append(key)
                self.gens.pop(key, None)
            return {"removed": removed, "kept_fresh": kept, "kept_gens": kept_gens}

    vol = FakeVolume()
    c.volume_refs = {"v0": vol}

    def meta(key="k"):
        req = Request.from_tensor(key, np.ones(4, np.float32))
        req.tensor_meta = TensorMeta(shape=(4,), dtype="float32")
        return req.meta_only()

    # v1 lands on v0 at gen 100 and is indexed with that generation.
    vol.kv["k"] = "v1-bytes"
    vol.gens["k"] = 100
    await c.notify_put_batch([meta()], "v0", write_gens={"v0": {"k": 100}})
    # v2's data-plane write to v0 FAILS -> detach + reclaim scheduled with
    # stale_gen=100. (Indexed on another volume so the key survives.)
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 200}},
    )
    assert c._pending_reclaims["v0"] == {"k": 100}

    # THE RACE: before the reclaim drainer fires, a NEW put (v3) lands on
    # v0 (data plane, gen 300) but its controller notify has NOT arrived.
    vol.kv["k"] = "v3-bytes"
    vol.gens["k"] = 300

    # Drain the reclaim directly (skip the 1s backoff sleep).
    for task in list(c._reclaim_tasks):
        task.cancel()
    c._reclaim_running.discard("v0")
    pending = c._pending_reclaims["v0"]
    result = await vol._delete_batch_if(sorted(pending.items()))
    assert result == {
        "removed": [], "kept_fresh": ["k"], "kept_gens": {"k": 300},
    }
    assert vol.kv["k"] == "v3-bytes"  # the acknowledged put survived
    assert vol.deleted == []

    # Counter-case: with NO racing put the stale copy IS reclaimed.
    vol.kv["stale"] = "old-bytes"
    vol.gens["stale"] = 50
    result = await vol._delete_batch_if([("stale", 50)])
    assert result["removed"] == ["stale"] and "stale" not in vol.kv


async def test_reclaim_drainer_uses_conditional_delete():
    """End-to-end through the real drainer task: the controller's reclaim
    calls delete_batch_if with the captured stale generation; re-indexed
    keys are skipped outright; deleted keys drain pending."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()
    calls = []

    class FakeVolume:
        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            return self._Ep(getattr(self, f"_{name}"))

        async def _delete_batch_if(self, items):
            calls.append(items)
            return {
                "removed": [k for k, _ in items], "kept_fresh": [],
                "kept_gens": {},
            }

    c.volume_refs = {"v0": FakeVolume()}

    def meta():
        req = Request.from_tensor("k", np.ones(4, np.float32))
        req.tensor_meta = TensorMeta(shape=(4,), dtype="float32")
        return req.meta_only()

    await c.notify_put_batch([meta()], "v0", write_gens={"v0": {"k": 7}})
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 8}},
    )
    # Simulate the racing put's notify arriving before the drainer fires:
    # the key re-indexes on v0 and the drainer must skip it entirely.
    await c.notify_put_batch([meta()], "v0", write_gens={"v0": {"k": 9}})
    for task in list(c._reclaim_tasks):
        await task
    assert calls == []  # re-indexed -> no delete at all

    # And when the key stays detached, the conditional delete carries the
    # captured stale generation.
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 10}},
    )
    for task in list(c._reclaim_tasks):
        await task
    assert calls == [[("k", 9)]]
    assert c._pending_reclaims == {}


async def test_reclaim_requeues_kept_fresh_until_indexed_or_orphaned():
    """kept_fresh is NOT terminal: the drainer requeues the volume's
    reported generation, so (a) a put whose notify arrives is confirmed by
    the re-index check, and (b) an ORPHANED put (client died between
    data-plane ack and notify) is reclaimed on a later round instead of
    leaking unindexed bytes forever (code-review r4 finding)."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()
    calls = []
    state = {"gen": 300, "deleted": []}

    class FakeVolume:
        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            return self._Ep(getattr(self, f"_{name}"))

        async def _delete_batch_if(self, items):
            calls.append(items)
            removed, kept, kept_gens = [], [], {}
            for key, stale_gen in items:
                if state["gen"] > stale_gen:
                    kept.append(key)
                    kept_gens[key] = state["gen"]
                else:
                    removed.append(key)
                    state["deleted"].append(key)
            return {
                "removed": removed, "kept_fresh": kept,
                "kept_gens": kept_gens,
            }

    c.volume_refs = {"v0": FakeVolume()}

    def meta():
        req = Request.from_tensor("k", np.ones(4, np.float32))
        req.tensor_meta = TensorMeta(shape=(4,), dtype="float32")
        return req.meta_only()

    # Indexed at gen 100; detach schedules reclaim at stale_gen 100. The
    # volume holds ORPHANED gen-300 bytes whose notify never arrives.
    await c.notify_put_batch([meta()], "v0", write_gens={"v0": {"k": 100}})
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 200}},
    )
    for task in list(c._reclaim_tasks):
        await task
    # Round 1: kept (300 > 100) -> requeued at 300; round 2: 300 <= 300 ->
    # deleted. The orphan is reclaimed, not leaked.
    assert calls[0] == [("k", 100)]
    assert calls[1] == [("k", 300)]
    assert state["deleted"] == ["k"]
    assert c._pending_reclaims == {}


async def test_reclaim_collects_partial_landings_two_phase():
    """A detached volume with NO prior indexed copy may still hold bytes
    from a partial batch landing. The reclaim schedules it at generation
    -1 and resolves two-phase: read the volume's current generation, then
    conditionally delete exactly those bytes (code-review r4 finding)."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()
    state = {"gens": {"k": 77}, "kv": {"k": "partial-bytes"}, "calls": []}

    class FakeVolume:
        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            return self._Ep(getattr(self, f"_{name}"))

        async def _write_gens(self, keys):
            state["calls"].append(("write_gens", list(keys)))
            return {k: state["gens"][k] for k in keys if k in state["gens"]}

        async def _delete_batch_if(self, items):
            state["calls"].append(("delete_if", items))
            removed = []
            for key, stale_gen in items:
                cur = state["gens"].get(key)
                if cur is not None and cur > stale_gen:
                    continue
                if state["kv"].pop(key, None) is not None:
                    removed.append(key)
                state["gens"].pop(key, None)
            return {"removed": removed, "kept_fresh": [], "kept_gens": {}}

    c.volume_refs = {"v0": FakeVolume()}

    def meta():
        req = Request.from_tensor("k", np.ones(4, np.float32))
        req.tensor_meta = TensorMeta(shape=(4,), dtype="float32")
        return req.meta_only()

    # First-ever put of k: landed on v1 but FAILED on v0 after a partial
    # landing — v0 was never indexed, yet holds bytes at gen 77.
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 200}},
    )
    assert c._pending_reclaims["v0"] == {"k": -1}
    for task in list(c._reclaim_tasks):
        await task
    assert state["calls"] == [
        ("write_gens", ["k"]),
        ("delete_if", [("k", 77)]),
    ]
    assert state["kv"] == {}  # partial landing reclaimed, not leaked
    assert c._pending_reclaims == {}


async def test_reclaim_reconciles_clobbered_index_entries():
    """Safety net for the residual notify-in-flight race: if the index
    claims the volume holds a key the reclaim just deleted, the entry is
    detached loudly instead of routing readers at missing bytes."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()

    def meta():
        req = Request.from_tensor("k", np.ones(4, np.float32))
        req.tensor_meta = TensorMeta(shape=(4,), dtype="float32")
        return req.meta_only()

    class FakeVolume:
        class _Ep:
            def __init__(self, fn):
                self.call_one = fn

        def __getattr__(self, name):
            return self._Ep(getattr(self, f"_{name}"))

        async def _delete_batch_if(self, items):
            # The delete removes the bytes; meanwhile (before the drainer
            # processes the result) the racing put's notify indexes v0.
            await c.notify_put_batch(
                [meta()], "v0", write_gens={"v0": {"k": 500}}
            )
            return {
                "removed": [k for k, _ in items], "kept_fresh": [],
                "kept_gens": {},
            }

    c.volume_refs = {"v0": FakeVolume()}
    await c.notify_put_batch([meta()], "v0", write_gens={"v0": {"k": 7}})
    await c.notify_put_batch(
        [meta()], "v1", detach_volume_ids=["v0"],
        write_gens={"v1": {"k": 8}},
    )
    for task in list(c._reclaim_tasks):
        await task
    # The clobbered entry is detached: only v1 serves k now.
    located = await c.locate_volumes(["k"])
    assert set(located["k"]) == {"v1"}
    assert c._pending_reclaims == {}


async def test_detached_stale_copy_reclaimed_and_not_served():
    """ADVICE r2 (medium): after a degraded replicated overwrite, the
    failed-but-ALIVE replica still holds the OLD bytes, and clients with
    warm location caches would read them. The controller must best-effort
    delete the stale copy once the replica recovers, so stale-cache reads
    fail over to the fresh value instead of silently serving v1."""
    import os
    import signal

    from torchstore_tpu.client import LocalClient
    from torchstore_tpu.config import StoreConfig

    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="reclaim",
        config=StoreConfig(rpc_timeout=2.0),
    )
    stopped = []
    try:
        v1 = np.full(8, 1.0, np.float32)
        v2 = np.full(8, 2.0, np.float32)
        await ts.put("k", v1, store_name="reclaim")
        client = ts.client("reclaim")
        # A second client with a WARM location cache for k.
        cli2 = LocalClient(client.controller, client._config)
        out = await cli2.get("k")
        np.testing.assert_array_equal(out, v1)
        assert "k" in cli2._loc_cache and len(cli2._loc_cache["k"]) == 2

        # Wedge volume "1" (alive but stuck) and overwrite at degraded
        # redundancy.
        from torchstore_tpu import api

        handle = api._stores["reclaim"]
        vmap = await client.controller.get_volume_map.call_one()
        target = vmap["1"]["ref"]
        proc = None
        for idx, ref in enumerate(handle.volume_mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host, target.port, target.name,
            ):
                proc = handle.volume_mesh._processes[idx]
        assert proc is not None
        os.kill(proc.pid, signal.SIGSTOP)
        stopped.append(proc.pid)
        await ts.put("k", v2, store_name="reclaim")
        located = await client.controller.locate_volumes.call_one(["k"])
        assert set(located["k"]) == {"0"}  # detached from the index

        # Recover the wedged replica. Two safe outcomes converge on v2:
        # (a) the wedged put's buffered RPC lands late — the volume then
        #     holds v2 at a FRESH write generation and the conditional
        #     reclaim keeps it (deleting it would destroy good bytes);
        # (b) it never lands — the reclaim deletes the stale v1 copy and
        #     pinned reads fail over to volume "0".
        # Either way a warm-cached client pinned to "1" must converge to
        # v2 and never be left serving v1.
        os.kill(proc.pid, signal.SIGCONT)
        stopped.clear()
        stale_pin = cli2._loc_cache["k"]["1"]
        deadline = asyncio.get_event_loop().time() + 30
        while True:
            cli2._loc_cache["k"] = {"1": stale_pin}  # re-pin each probe
            out2 = await cli2.get("k")
            if (out2 == v2).all():
                break
            np.testing.assert_array_equal(out2, v1)  # only other legal value
            assert asyncio.get_event_loop().time() < deadline, (
                "pinned stale-cache read never converged to v2"
            )
            await asyncio.sleep(0.5)
        # And the reclaim machinery has fully drained (kept-fresh or
        # deleted, nothing pending).
        deadline = asyncio.get_event_loop().time() + 30
        while (await client.controller.stats.call_one()).get(
            "pending_reclaims"
        ):
            assert asyncio.get_event_loop().time() < deadline, (
                "reclaim never drained"
            )
            await asyncio.sleep(0.5)
    finally:
        for pid in stopped:
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        await ts.shutdown("reclaim")
