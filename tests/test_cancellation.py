"""Cancellation and fire-and-forget regression tests (ISSUE 4 satellites).

- cancellation must PROPAGATE through the transport-buffer put lifecycle
  (transport/buffers.py wraps the data-plane RPC in ``except BaseException``
  blocks that count errors — they must re-raise, never swallow, and drop()
  must still run);
- ``utils.spawn_logged`` is the repo's only sanctioned fire-and-forget
  spawn: it retains the task, and a failing task is logged + counted in
  ``ts_background_task_errors_total`` instead of vanishing (the
  orphan-task tslint rule points here).
"""

import asyncio
import logging
import pathlib
import sys

import numpy as np
import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from torchstore_tpu.observability import metrics as obs_metrics  # noqa: E402
from torchstore_tpu.strategy import StorageVolumeRef  # noqa: E402
from torchstore_tpu.transport.buffers import (  # noqa: E402
    TransportBuffer,
    TransportContext,
)
from torchstore_tpu.transport.types import Request  # noqa: E402
from torchstore_tpu.utils import spawn_logged  # noqa: E402


class _HangingEndpoint:
    """Stands in for ``volume.actor.put``: hangs until cancelled."""

    def __init__(self) -> None:
        self.started = asyncio.Event()

    def with_timeout(self, timeout):
        return self

    def _effective_timeout(self):
        return None

    async def call_one(self, *args, **kwargs):
        self.started.set()
        await asyncio.Event().wait()  # forever


class _FakeActor:
    def __init__(self) -> None:
        self.put = _HangingEndpoint()


class _NullBuffer(TransportBuffer):
    transport_name = "test_cancel"
    requires_handshake = False

    def __init__(self) -> None:
        self.dropped = 0

    def _handle_storage_volume_response(self, volume, remote, requests):
        return []

    def handle_put_request(self, ctx, metas, existing):
        return {}

    def handle_get_request(self, ctx, metas, entries):
        return None

    def drop(self) -> None:
        self.dropped += 1


def _errors(op: str) -> float:
    return obs_metrics.counter("ts_transport_errors_total").value(
        transport="test_cancel", op=op
    )


def test_cancellation_propagates_through_put_lifecycle():
    async def main():
        buf = _NullBuffer()
        actor = _FakeActor()
        volume = StorageVolumeRef(
            actor=actor, volume_id="v0", transport_context=TransportContext()
        )
        req = Request.from_tensor("k", np.zeros(16, dtype=np.float32))
        before = _errors("put")
        task = asyncio.create_task(buf.put_to_storage_volume(volume, [req]))
        await asyncio.wait_for(actor.put.started.wait(), 10)
        task.cancel()
        # The whole point: CancelledError comes back out — the lifecycle's
        # broad error accounting must re-raise, not swallow.
        with pytest.raises(asyncio.CancelledError):
            await task
        assert task.cancelled()
        # ... while the finally-guaranteed release still ran, and the error
        # counter recorded the aborted transfer.
        assert buf.dropped == 1
        assert _errors("put") == before + 1

    asyncio.run(main())


def test_spawn_logged_counts_and_logs_failures(caplog):
    async def main():
        tasks: set = set()

        async def boom():
            raise RuntimeError("kaboom")

        counter = obs_metrics.counter("ts_background_task_errors_total")
        before = counter.value(task="test.boom")
        with caplog.at_level(logging.ERROR, logger="torchstore_tpu.tasks"):
            t = spawn_logged(boom(), name="test.boom", tasks=tasks)
            assert t in tasks  # retained while in flight
            with pytest.raises(RuntimeError):
                await t
            await asyncio.sleep(0)  # let the done-callback run
        assert t not in tasks  # discarded once done
        assert counter.value(task="test.boom") == before + 1
        assert any("test.boom" in rec.getMessage() for rec in caplog.records)

    asyncio.run(main())


def test_spawn_logged_cancellation_is_not_an_error():
    async def main():
        tasks: set = set()

        async def forever():
            await asyncio.Event().wait()

        counter = obs_metrics.counter("ts_background_task_errors_total")
        before = counter.value(task="test.cancelled")
        t = spawn_logged(forever(), name="test.cancelled", tasks=tasks)
        await asyncio.sleep(0)
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        await asyncio.sleep(0)
        assert t not in tasks
        assert counter.value(task="test.cancelled") == before

    asyncio.run(main())


def test_spawn_logged_success_keeps_result():
    async def main():
        async def work():
            return 42

        t = spawn_logged(work(), name="test.ok")
        assert await t == 42

    asyncio.run(main())


def test_bulk_send_join_does_not_eat_outer_cancellation():
    """The bulk reader joins cancelled send tasks via gather(...,
    return_exceptions=True): cancelling the JOINING coroutine itself must
    still propagate (the old per-task ``except (CancelledError, Exception)``
    swallowed it)."""

    async def main():
        started = asyncio.Event()

        async def send():
            await asyncio.Event().wait()

        sends = [asyncio.ensure_future(send()) for _ in range(3)]

        async def reader_teardown():
            for s in sends:
                s.cancel()
            started.set()
            await asyncio.gather(*sends, return_exceptions=True)
            await asyncio.Event().wait()  # simulate further teardown work

        t = asyncio.create_task(reader_teardown())
        await started.wait()
        t.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t
        assert t.cancelled()

    asyncio.run(main())
