"""Model-family tests: forward shapes, sharded params, store round trip of a
sharded model + optimizer state — the e2e model flow the reference covers
with HF models (tests/test_models.py there)."""

import numpy as np
import pytest

import torchstore_tpu as ts

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from torchstore_tpu import parallel  # noqa: E402
from torchstore_tpu.models.llama import Llama, LlamaConfig  # noqa: E402


def test_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_moe_forward():
    cfg = LlamaConfig.tiny_moe()
    model = Llama(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 8, cfg.vocab_size)
    # Expert kernels carry a leading expert axis for ep sharding.
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    expert_leaves = [
        leaf for path, leaf in flat if "mlp" in str(path) and "router" not in str(path)
    ]
    from flax.core import meta

    assert any(
        (leaf.value if isinstance(leaf, meta.Partitioned) else leaf).shape[0]
        == cfg.num_experts
        for leaf in expert_leaves
    )


def test_shard_params_places_on_mesh():
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    boxed = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    params = parallel.unbox(parallel.shard_params(boxed, mesh))
    # An attention q kernel: ('embed','heads',None) -> P(None,'tp',None).
    q = params["params"]["layer_0"]["attn"]["q_proj"]["kernel"]
    assert q.sharding.spec == P(None, "tp", None)
    logits = jax.jit(model.apply)(params, jnp.zeros((2, 8), jnp.int32))
    assert logits.shape[-1] == cfg.vocab_size


def test_train_step_decreases_loss():
    cfg = LlamaConfig.tiny()
    model = Llama(cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    params = parallel.unbox(model.init(jax.random.key(0), tokens))
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = parallel.make_train_step(model, opt)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


async def test_sharded_model_store_roundtrip():
    await ts.initialize(store_name="mdl")
    try:
        mesh = parallel.make_mesh({"fsdp": 4, "tp": 2})
        cfg = LlamaConfig.tiny()
        model = Llama(cfg)
        boxed = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        params = parallel.unbox(parallel.shard_params(boxed, mesh))
        await ts.put_state_dict("model/v0", {"params": params}, store_name="mdl")
        # Pull onto a different mesh layout.
        mesh2 = parallel.make_mesh({"tp": 8})
        like = parallel.unbox(parallel.shard_params(boxed, mesh2))
        out = await ts.get_state_dict(
            "model/v0", user_state_dict={"params": like}, store_name="mdl"
        )
        ref = parallel.unbox(boxed)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(out["params"])[0],
            jax.tree_util.tree_flatten_with_path(ref)[0],
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    finally:
        await ts.shutdown("mdl")


@pytest.mark.parametrize("kv_heads", [8, 4], ids=["mha", "gqa"])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sequence_parallel_attention_in_model(impl, kv_heads):
    # Same params, dense vs sequence-parallel attention: logits must match
    # (incl. the GQA kv-repeat path and tp-sharded heads inside shard_map).
    import dataclasses

    mesh = parallel.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    base = dataclasses.replace(
        LlamaConfig.tiny(),
        num_kv_heads=kv_heads,
        dtype=jnp.float32,
        param_dtype=jnp.float32,
    )
    sp_cfg = dataclasses.replace(base, attn_impl=impl, mesh=mesh)
    tokens = jax.random.randint(jax.random.key(2), (2, 16), 0, base.vocab_size)
    params = parallel.unbox(
        Llama(base).init(jax.random.key(0), tokens)
    )
    dense = Llama(base).apply(params, tokens)
    sp = Llama(sp_cfg).apply(params, tokens)
    np.testing.assert_allclose(
        np.asarray(sp), np.asarray(dense), atol=5e-4, rtol=5e-4
    )


def test_ring_attention_model_trains():
    # Gradients flow through the sequence-parallel attention path.
    import dataclasses

    mesh = parallel.make_mesh({"sp": 2})
    cfg = dataclasses.replace(LlamaConfig.tiny(), attn_impl="ring", mesh=mesh)
    model = Llama(cfg)
    # 17 tokens: the train step feeds tokens[:, :-1] (16, divisible by sp=2).
    tokens = jax.random.randint(jax.random.key(1), (2, 17), 0, cfg.vocab_size)
    params = parallel.unbox(model.init(jax.random.key(0), tokens[:, :-1]))
    opt = optax.adamw(1e-2)
    step = parallel.make_train_step(model, opt)
    opt_state = opt.init(params)
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_graft_entry_single_chip():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 256
