"""int8 transfer quantization: put_state_dict(transfer_quant="int8") ships
symmetric per-tensor int8 (scales ride the commit marker), gets dequantize
toward the caller's targets — in place for numpy/torch, on-device after
resharding for jax. 4x fewer wire/store bytes than f32."""

import numpy as np
import pytest

import torchstore_tpu as ts

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import ml_dtypes  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


@pytest.fixture
async def store():
    await ts.initialize(store_name="q8")
    yield "q8"
    await ts.shutdown("q8")


def _tol(arr):
    # Symmetric int8: max error is scale/2 = max|x|/254.
    return float(np.max(np.abs(arr))) / 254.0 + 1e-7


async def test_roundtrip_accuracy(store):
    sd = {
        "w": np.random.randn(64, 32).astype(np.float32),
        "b": np.random.randn(32).astype(np.float32) * 0.01,
        "step": 7,  # non-floating leaves pass through untouched
    }
    await ts.put_state_dict("m", sd, transfer_quant="int8", store_name="q8")
    out = await ts.get_state_dict("m", store_name="q8")
    assert out["w"].dtype == np.float32
    np.testing.assert_allclose(out["w"], sd["w"], atol=_tol(sd["w"]))
    np.testing.assert_allclose(out["b"], sd["b"], atol=_tol(sd["b"]))
    assert out["step"] == 7


async def test_wire_bytes_are_int8(store):
    sd = {"w": np.random.randn(256, 256).astype(np.float32)}
    await ts.put_state_dict("m8", sd, transfer_quant="int8", store_name="q8")
    stats = await ts.client("q8").controller.stats.call_one(
        include_volumes=True
    )
    (vstats,) = stats["volumes"].values()
    # Stored bytes ~= N elements (int8), not 4N (f32).
    assert vstats["stored_bytes"] < sd["w"].size * 2


async def test_inplace_numpy_target(store):
    sd = {"w": np.random.randn(32, 32).astype(np.float32)}
    await ts.put_state_dict("mi", sd, transfer_quant="int8", store_name="q8")
    user = {"w": np.zeros((32, 32), np.float32)}
    out = await ts.get_state_dict("mi", user_state_dict=user, store_name="q8")
    assert out["w"] is user["w"]  # dequantized into the caller's memory
    np.testing.assert_allclose(user["w"], sd["w"], atol=_tol(sd["w"]))


async def test_inplace_torch_target(store):
    torch = pytest.importorskip("torch")
    sd = {"w": torch.randn(16, 16)}
    await ts.put_state_dict("mt", sd, transfer_quant="int8", store_name="q8")
    user = {"w": torch.zeros(16, 16)}
    out = await ts.get_state_dict("mt", user_state_dict=user, store_name="q8")
    assert out["w"] is user["w"]
    np.testing.assert_allclose(
        user["w"].numpy(), sd["w"].numpy(), atol=_tol(sd["w"].numpy())
    )


async def test_bf16_leaves(store):
    sd = {"w": np.random.randn(64).astype(ml_dtypes.bfloat16)}
    await ts.put_state_dict("mb", sd, transfer_quant="int8", store_name="q8")
    out = await ts.get_state_dict("mb", store_name="q8")
    assert out["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_allclose(
        out["w"].astype(np.float32),
        sd["w"].astype(np.float32),
        atol=_tol(sd["w"].astype(np.float32)) + 0.02,  # bf16 rounding
    )


async def test_sharded_jax_target_dequantizes_on_device(store):
    # The fetch reshards the INT8 bytes (4x cheaper than f32), then
    # dequantizes elementwise on device, preserving the target sharding.
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
    src = np.random.randn(8, 8).astype(np.float32)
    sharded = jax.device_put(
        jnp.asarray(src), NamedSharding(mesh, P("a", "b"))
    )
    await ts.put_state_dict(
        "mj", {"w": sharded}, transfer_quant="int8", store_name="q8"
    )
    target = jax.ShapeDtypeStruct(
        (8, 8), jnp.float32, sharding=NamedSharding(mesh, P("b", "a"))
    )
    out = await ts.get_state_dict(
        "mj", user_state_dict={"w": target}, store_name="q8"
    )
    assert out["w"].dtype == jnp.float32
    assert out["w"].sharding.spec == P("b", "a")
    np.testing.assert_allclose(np.asarray(out["w"]), src, atol=_tol(src))


async def test_quant_through_weight_channel(store):
    pub = ts.WeightPublisher("qp", store_name="q8")
    sub = ts.WeightSubscriber("qp", store_name="q8")
    src = {"w": np.random.randn(64).astype(np.float32)}
    await pub.publish(src, transfer_quant="int8")
    sd, v = await sub.acquire(timeout=10.0)
    np.testing.assert_allclose(sd["w"], src["w"], atol=_tol(src["w"]))


async def test_invalid_combinations(store):
    sd = {"w": np.ones(4, np.float32)}
    with pytest.raises(ValueError, match="mutually exclusive"):
        await ts.put_state_dict(
            "x", sd, transfer_quant="int8", transfer_dtype=np.float16,
            store_name="q8",
        )
    with pytest.raises(ValueError, match="buffered-path"):
        await ts.put_state_dict(
            "x", sd, transfer_quant="int8", direct=True, store_name="q8"
        )
    with pytest.raises(ValueError, match="unsupported"):
        await ts.put_state_dict(
            "x", sd, transfer_quant="int4", store_name="q8"
        )


async def test_jax_target_dtype_honored(store):
    # bf16-sourced push, f32 jax target: the dequantized array must carry
    # the TARGET dtype (orbax restore idiom), like every other branch.
    src = np.random.randn(16).astype(ml_dtypes.bfloat16)
    await ts.put_state_dict(
        "md", {"w": jnp.asarray(src)}, transfer_quant="int8", store_name="q8"
    )
    target = jax.ShapeDtypeStruct(
        (16,),
        jnp.float32,
        sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]),
    )
    out = await ts.get_state_dict(
        "md", user_state_dict={"w": target}, store_name="q8"
    )
    assert out["w"].dtype == jnp.float32


async def test_empty_and_nonaddressable_leaves(store):
    # Empty leaves quantize without crashing (both array families).
    sd = {"e_np": np.zeros((0, 8), np.float32), "e_jx": jnp.zeros((0, 8))}
    await ts.put_state_dict("me", sd, transfer_quant="int8", store_name="q8")
    out = await ts.get_state_dict("me", store_name="q8")
    assert out["e_np"].shape == (0, 8) and np.asarray(out["e_jx"]).shape == (0, 8)


async def test_nonfinite_weights_rejected(store):
    # NaN would silently zero sub-unit weights (scale falls back to 1);
    # Inf would dequantize to all-NaN. Both must fail loudly.
    bad = np.random.randn(8).astype(np.float32)
    bad[3] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        await ts.put_state_dict(
            "nf", {"w": bad}, transfer_quant="int8", store_name="q8"
        )
    bad[3] = np.inf
    with pytest.raises(ValueError, match="non-finite"):
        await ts.put_state_dict(
            "nf", {"w": bad}, transfer_quant="int8", store_name="q8"
        )


async def test_zero_tensor_quantizes(store):
    sd = {"w": np.zeros(16, np.float32)}
    await ts.put_state_dict("mz", sd, transfer_quant="int8", store_name="q8")
    out = await ts.get_state_dict("mz", store_name="q8")
    np.testing.assert_array_equal(out["w"], sd["w"])
