"""Colocated (in-process) volume mode: local endpoint calls dispatch
directly (no RPC, no serialization), remote processes still reach the
volume over its real actor server, and value semantics survive the
by-reference dispatch (VERDICT r1 item 3's same-process fast path)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.runtime import Actor, endpoint, spawn_actors


@pytest.fixture(params=[None, "rpc"])
async def colo(request):
    strategy = ts.SingletonStrategy(default_transport_type=request.param)
    await ts.initialize(store_name="colo", strategy=strategy, colocated=True)
    yield "colo"
    await ts.shutdown("colo")


async def test_roundtrip_and_inproc_dispatch(colo):
    client = ts.client(colo)
    await client._ensure_setup()
    volume = next(iter(client._volume_refs.values()))
    assert volume.is_inproc()  # direct dispatch, not RPC
    x = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    await ts.put("k", x, store_name=colo)
    np.testing.assert_array_equal(await ts.get("k", store_name=colo), x)
    await ts.put("obj", {"step": 7}, store_name=colo)
    assert await ts.get("obj", store_name=colo) == {"step": 7}


async def test_value_semantics_despite_reference_dispatch(colo):
    """Direct dispatch passes arrays by reference; the store must still
    behave as if values were serialized: later mutations of the caller's
    array must not change the stored entry, and mutating a fetched copy
    must not corrupt the store."""
    x = np.ones(32, np.float32)
    await ts.put("k", x, store_name=colo)
    x[:] = -5.0  # trainer reuses its buffer
    out = await ts.get("k", store_name=colo)
    np.testing.assert_array_equal(np.asarray(out), np.ones(32))
    if out.flags.writeable:  # rpc path returns plain arrays
        out[:] = 99.0
        again = await ts.get("k", store_name=colo)
        np.testing.assert_array_equal(np.asarray(again), np.ones(32))


async def test_object_value_semantics(colo):
    """Object payloads must be copied on store AND serve despite the
    by-reference in-process dispatch."""
    cfg = {"lr": 0.1, "betas": [0.9, 0.95]}
    await ts.put("cfg", cfg, store_name=colo)
    cfg["lr"] = 0.0  # caller mutates after put
    out = await ts.get("cfg", store_name=colo)
    assert out["lr"] == 0.1
    out["betas"].append(123)  # consumer mutates the fetched object
    again = await ts.get("cfg", store_name=colo)
    assert again == {"lr": 0.1, "betas": [0.9, 0.95]}


async def test_shutdown_releases_segments():
    """A colocated volume's /dev/shm segments must be released at shutdown
    (the orphan reaper can't help — the creator pid stays alive)."""
    import os as _os

    def n_segments():
        return len(
            [n for n in _os.listdir("/dev/shm") if n.startswith("ts_shm_")]
        )

    before = n_segments()
    await ts.initialize(store_name="colo3", colocated=True)
    await ts.put("big", np.random.rand(1 << 18), store_name="colo3")
    await ts.get("big", store_name="colo3")
    await ts.shutdown("colo3")
    assert n_segments() <= before


class _Reader(Actor):
    @endpoint
    async def read(self):
        out = await ts.get("shared", store_name="colo")
        return float(np.asarray(out)[0])


async def test_remote_process_reaches_colocated_volume(colo):
    """A spawned actor (separate process) fetches from the colocated volume
    over its real server while this process's loop keeps serving."""
    await ts.put("shared", np.full(4, 8.25, np.float32), store_name=colo)
    readers = await spawn_actors(1, _Reader, "reader")
    try:
        assert await readers.read.call() == [8.25]
    finally:
        await readers.stop()


async def test_state_dict_roundtrip_colocated(colo):
    sd = {"layer": {"w": np.random.rand(256).astype(np.float32)}}
    await ts.put_state_dict("m", sd, store_name=colo)
    out = await ts.get_state_dict("m", store_name=colo)
    np.testing.assert_array_equal(out["layer"]["w"], sd["layer"]["w"])


async def test_colocated_rejects_multiple_volumes():
    with pytest.raises(ValueError, match="exactly one volume"):
        await ts.initialize(
            num_storage_volumes=2, store_name="colo2", colocated=True
        )
    from torchstore_tpu import api

    assert "colo2" not in api._stores
