"""Ring attention differential tests: exactness vs dense attention on the
8-device CPU mesh (sequence-parallel over an sp ring)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from torchstore_tpu.ops.ring_attention import ring_attention_sharded  # noqa: E402
from torchstore_tpu import parallel  # noqa: E402


def dense_reference(q, k, v, causal):
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def make_qkv(b=2, s=64, h=4, d=16, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(key, shape, jnp.float32) for key in keys)


@pytest.mark.parametrize("impl", ["fused", "einsum"])
@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("ring", [2, 4, 8])
def test_matches_dense(causal, ring, impl):
    """Both block bodies — the pallas fused kernel (per-hop
    flash_attention_stats + online-softmax merge) and the einsum fallback —
    are exact vs dense attention (VERDICT r3 item 5)."""
    q, k, v = make_qkv()
    mesh = parallel.make_mesh({"sp": ring})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal, impl=impl)
    ref = dense_reference(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    assert out.sharding.spec == P(None, "sp", None, None)


@pytest.mark.parametrize("impl", ["fused", "einsum"])
@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_gqa_matches_dense(causal, impl):
    """GQA through the ring: kv heads stay unrepeated on the wire in both
    bodies (grouped einsum / in-kernel kv index map)."""
    keys = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(keys[0], (2, 64, 8, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 64, 2, 16), jnp.float32)
    mesh = parallel.make_mesh({"sp": 4})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal, impl=impl)
    ref = dense_reference(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("impl", ["fused", "einsum"])
@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
def test_gradients_match_dense(causal, impl):
    """Training differentiates through ring attention; the fused body's
    custom VJP (pallas forward, dense recompute backward) must produce the
    same q/k/v gradients as differentiating dense attention."""
    q, k, v = make_qkv(b=1, s=32, h=2, d=16, seed=11)
    mesh = parallel.make_mesh({"sp": 4})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))

    def ring_loss(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh, "sp", causal=causal, impl=impl)
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)))

    def dense_loss(q, k, v):
        out = dense_reference(q, k, v, causal)
        return jnp.sum(out * jnp.cos(jnp.arange(out.size).reshape(out.shape)))

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(qs, ks, vs)
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=3e-5, rtol=3e-5
        )


def test_auto_picks_fused_for_tileable_shapes():
    from torchstore_tpu.ops.flash_attention import flash_stats_eligible

    assert flash_stats_eligible((2, 8, 4, 16), (2, 8, 4, 16))
    assert not flash_stats_eligible((2, 9, 4, 16), (2, 9, 4, 16))  # 9 untileable
    assert not flash_stats_eligible((2, 8, 4, 10), (2, 8, 4, 10))  # d % 8


def test_flash_stats_merge_property():
    """Property sweep of the merge invariant over GQA ratios, head dims,
    asymmetric kv splits, and both mask modes: blocks merged with the
    flash rescale equal whole-sequence attention (the exact algebra the
    ring's hop merge relies on)."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    from torchstore_tpu.ops.flash_attention import flash_attention_stats

    @settings(max_examples=15, deadline=None)
    @given(
        hk=st.sampled_from([1, 2, 4]),
        g=st.sampled_from([1, 2, 4]),
        d=st.sampled_from([8, 16, 24]),
        sq=st.sampled_from([16, 32, 40]),
        split=st.sampled_from([8, 16, 24]),
        seed=st.integers(0, 2**16),
    )
    def check(hk, g, d, sq, split, seed):
        h = hk * g
        sk = 48
        keys = jax.random.split(jax.random.key(seed), 3)
        q = jax.random.normal(keys[0], (1, sq, h, d), jnp.float32)
        k = jax.random.normal(keys[1], (1, sk, hk, d), jnp.float32)
        v = jax.random.normal(keys[2], (1, sk, hk, d), jnp.float32)
        a1, m1, l1 = flash_attention_stats(q, k[:, :split], v[:, :split])
        a2, m2, l2 = flash_attention_stats(q, k[:, split:], v[:, split:])
        m = jnp.maximum(m1, m2)
        c1, c2 = jnp.exp(m1 - m), jnp.exp(m2 - m)
        o = (a1 * c1[..., None] + a2 * c2[..., None]) / (
            l1 * c1 + l2 * c2
        )[..., None]
        out = jnp.transpose(o, (0, 2, 1, 3))
        ref = dense_reference(q, k, v, False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )

    check()


def test_flash_stats_merge_identity():
    """flash_attention_stats blocks merged with the flash rescale equal
    whole-sequence dense attention — the invariant the ring's hop merge
    relies on."""
    from torchstore_tpu.ops.flash_attention import flash_attention_stats

    q, k, v = make_qkv(b=1, s=64, h=2, d=16, seed=5)
    k1, k2 = k[:, :32], k[:, 32:]
    v1, v2 = v[:, :32], v[:, 32:]
    a1, m1, l1 = flash_attention_stats(q, k1, v1)
    a2, m2, l2 = flash_attention_stats(q, k2, v2)
    m = jnp.maximum(m1, m2)
    c1, c2 = jnp.exp(m1 - m), jnp.exp(m2 - m)
    o = (a1 * c1[..., None] + a2 * c2[..., None]) / (
        l1 * c1 + l2 * c2
    )[..., None]
    out = jnp.transpose(o, (0, 2, 1, 3))
    ref = dense_reference(q, k, v, False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_single_device_ring_degenerates_to_dense():
    q, k, v = make_qkv(s=32)
    mesh = parallel.make_mesh({"sp": 1})
    out = ring_attention_sharded(q, k, v, mesh, "sp", causal=True)
    ref = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_long_sequence_memory_shape():
    # 8-way ring over a longer sequence: each device only ever holds
    # seq/8-sized k/v blocks; output stays sequence-sharded.
    q, k, v = make_qkv(b=1, s=512, h=2, d=8)
    mesh = parallel.make_mesh({"sp": 8})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
    assert out.shape == (1, 512, 2, 8)
    for shard in out.addressable_shards:
        assert shard.data.shape[1] == 512 // 8
    ref = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = make_qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = parallel.make_mesh({"sp": 4})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = ring_attention_sharded(
        *(jax.device_put(x, spec) for x in (qb, kb, vb)), mesh, "sp", causal=False
    )
    assert out.dtype == jnp.bfloat16
    ref = dense_reference(qb, kb, vb, False)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    @pytest.mark.parametrize("ring", [2, 4])
    def test_matches_dense(self, causal, ring):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv()
        mesh = parallel.make_mesh({"sp": ring})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        out = ulysses_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )
        assert out.sharding.spec == P(None, "sp", None, None)

    def test_indivisible_heads_rejected(self):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv(h=3)
        mesh = parallel.make_mesh({"sp": 2})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(qs, ks, vs, mesh, "sp")

    def test_agrees_with_ring(self):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv(s=128)
        mesh = parallel.make_mesh({"sp": 4})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        ring = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
        uly = ulysses_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(uly), atol=3e-5, rtol=3e-5
        )

    def test_hypothesis_sweep_gqa_heads_causal(self):
        """Property sweep of the Ulysses envelope (VERDICT r5 #4): GQA
        ratio x head count x causal mode against the dense oracle. Head
        counts are drawn divisible by the sp axis (the op's contract); the
        all-to-all re-partition must be exact for every combination."""
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        from torchstore_tpu.ops import ulysses_attention_sharded

        sp = 4
        mesh = parallel.make_mesh({"sp": sp})
        spec = NamedSharding(mesh, P(None, "sp", None, None))

        @settings(max_examples=12, deadline=None)
        @given(
            kv_heads=st.sampled_from([4, 8]),  # divisible by sp
            gqa=st.sampled_from([1, 2, 3]),  # q heads = kv * gqa
            d=st.sampled_from([8, 16]),
            causal=st.booleans(),
            seed=st.integers(0, 2**16),
        )
        def check(kv_heads, gqa, d, causal, seed):
            h = kv_heads * gqa
            keys = jax.random.split(jax.random.key(seed), 3)
            q = jax.random.normal(keys[0], (1, 32, h, d), jnp.float32)
            k = jax.random.normal(keys[1], (1, 32, kv_heads, d), jnp.float32)
            v = jax.random.normal(keys[2], (1, 32, kv_heads, d), jnp.float32)
            qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
            out = ulysses_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal)
            ref = dense_reference(q, k, v, causal)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
            )

        check()

    def test_model_head_divisibility_fallback_to_ring(self, monkeypatch):
        """Boundary of the head-divisibility envelope: a model configured
        with attn_impl='ulysses' whose per-shard head counts do NOT divide
        the sp axis must fall back to ring attention — logits still match
        dense, and the ulysses body is never entered (stubbed to fail)."""
        import dataclasses
        import importlib

        # The package re-exports the function under the submodule's name, so
        # ``import ... as`` would bind the function; fetch the module itself.
        ua = importlib.import_module("torchstore_tpu.ops.ulysses_attention")
        from torchstore_tpu.models.llama import Llama, LlamaConfig
        from torchstore_tpu.ops._sharded import make_sharded_attention

        def boom(*args, **kwargs):
            raise AssertionError(
                "ulysses body must not run for indivisible heads"
            )

        monkeypatch.setattr(ua, "ulysses_attention", boom)
        make_sharded_attention.cache_clear()  # a cached fn could mask the stub
        mesh = parallel.make_mesh({"sp": 4})
        base = dataclasses.replace(
            LlamaConfig.tiny(),
            num_heads=6,  # 6 % 4 != 0: outside the ulysses envelope
            num_kv_heads=6,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
        )
        sp_cfg = dataclasses.replace(base, attn_impl="ulysses", mesh=mesh)
        tokens = jax.random.randint(
            jax.random.key(3), (2, 16), 0, base.vocab_size
        )
        params = parallel.unbox(Llama(base).init(jax.random.key(0), tokens))
        dense = Llama(base).apply(params, tokens)
        out = Llama(sp_cfg).apply(params, tokens)  # fell back to ring
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dense), atol=5e-4, rtol=5e-4
        )


class TestPallasFlash:
    """Pallas flash kernel (interpret mode on CPU; compiles and runs on the
    real v5e chip at ~120 TFLOP/s — see BASELINE.md for the jitted-XLA
    comparison)."""

    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    def test_matches_dense(self, causal):
        from torchstore_tpu.ops import flash_attention

        q, k, v = make_qkv(b=1, s=256, h=2, d=32)
        out = flash_attention(q, k, v, causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )

    def test_gqa(self):
        from torchstore_tpu.ops import flash_attention

        keys = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(keys[0], (1, 256, 8, 32), jnp.float32)
        k = jax.random.normal(keys[1], (1, 256, 2, 32), jnp.float32)
        v = jax.random.normal(keys[2], (1, 256, 2, 32), jnp.float32)
        out = flash_attention(q, k, v, causal=True)
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )

    def test_untileable_falls_back(self):
        from torchstore_tpu.ops import flash_attention

        q, k, v = make_qkv(b=1, s=100, h=2, d=32)  # 100 % 128 != 0
        out = flash_attention(q, k, v, causal=True)
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )
