"""Ring attention differential tests: exactness vs dense attention on the
8-device CPU mesh (sequence-parallel over an sp ring)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from torchstore_tpu.ops.ring_attention import ring_attention_sharded  # noqa: E402
from torchstore_tpu import parallel  # noqa: E402


def dense_reference(q, k, v, causal):
    return jax.nn.dot_product_attention(q, k, v, is_causal=causal)


def make_qkv(b=2, s=64, h=4, d=16, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(key, shape, jnp.float32) for key in keys)


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("ring", [2, 4, 8])
def test_matches_dense(causal, ring):
    q, k, v = make_qkv()
    mesh = parallel.make_mesh({"sp": ring})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal)
    ref = dense_reference(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    assert out.sharding.spec == P(None, "sp", None, None)


def test_single_device_ring_degenerates_to_dense():
    q, k, v = make_qkv(s=32)
    mesh = parallel.make_mesh({"sp": 1})
    out = ring_attention_sharded(q, k, v, mesh, "sp", causal=True)
    ref = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_long_sequence_memory_shape():
    # 8-way ring over a longer sequence: each device only ever holds
    # seq/8-sized k/v blocks; output stays sequence-sharded.
    q, k, v = make_qkv(b=1, s=512, h=2, d=8)
    mesh = parallel.make_mesh({"sp": 8})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
    assert out.shape == (1, 512, 2, 8)
    for shard in out.addressable_shards:
        assert shard.data.shape[1] == 512 // 8
    ref = dense_reference(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = make_qkv()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    mesh = parallel.make_mesh({"sp": 4})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    out = ring_attention_sharded(
        *(jax.device_put(x, spec) for x in (qb, kb, vb)), mesh, "sp", causal=False
    )
    assert out.dtype == jnp.bfloat16
    ref = dense_reference(qb, kb, vb, False)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    @pytest.mark.parametrize("ring", [2, 4])
    def test_matches_dense(self, causal, ring):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv()
        mesh = parallel.make_mesh({"sp": ring})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        out = ulysses_attention_sharded(qs, ks, vs, mesh, "sp", causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )
        assert out.sharding.spec == P(None, "sp", None, None)

    def test_indivisible_heads_rejected(self):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv(h=3)
        mesh = parallel.make_mesh({"sp": 2})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention_sharded(qs, ks, vs, mesh, "sp")

    def test_agrees_with_ring(self):
        from torchstore_tpu.ops import ulysses_attention_sharded

        q, k, v = make_qkv(s=128)
        mesh = parallel.make_mesh({"sp": 4})
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        ring = ring_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
        uly = ulysses_attention_sharded(qs, ks, vs, mesh, "sp", causal=True)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(uly), atol=3e-5, rtol=3e-5
        )


class TestPallasFlash:
    """Pallas flash kernel (interpret mode on CPU; compiles and runs on the
    real v5e chip at ~120 TFLOP/s — see BASELINE.md for the jitted-XLA
    comparison)."""

    @pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
    def test_matches_dense(self, causal):
        from torchstore_tpu.ops import flash_attention

        q, k, v = make_qkv(b=1, s=256, h=2, d=32)
        out = flash_attention(q, k, v, causal=causal)
        ref = dense_reference(q, k, v, causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )

    def test_gqa(self):
        from torchstore_tpu.ops import flash_attention

        keys = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(keys[0], (1, 256, 8, 32), jnp.float32)
        k = jax.random.normal(keys[1], (1, 256, 2, 32), jnp.float32)
        v = jax.random.normal(keys[2], (1, 256, 2, 32), jnp.float32)
        out = flash_attention(q, k, v, causal=True)
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )

    def test_untileable_falls_back(self):
        from torchstore_tpu.ops import flash_attention

        q, k, v = make_qkv(b=1, s=100, h=2, d=32)  # 100 % 128 != 0
        out = flash_attention(q, k, v, causal=True)
        ref = dense_reference(q, k, v, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
        )
