"""Pure-unit tests for the reshard math (mirrors the reference's
tests/test_utils.py coverage: intersection, destination views, assembly with
gaps/overlap/size-mismatch, byte views)."""

import numpy as np
import pytest

from torchstore_tpu.utils import (
    Box,
    assemble_tensor,
    bounding_box,
    get_destination_view,
    intersect_boxes,
    tensors_overlap_in_memory,
    to_byte_view,
)


class TestBox:
    def test_contains(self):
        outer = Box((0, 0), (10, 10))
        assert outer.contains(Box((2, 3), (4, 5)))
        assert outer.contains(outer)
        assert not outer.contains(Box((8, 8), (4, 4)))

    def test_rank_mismatch(self):
        with pytest.raises(ValueError):
            Box((0,), (1, 2))

    def test_index(self):
        x = np.arange(100).reshape(10, 10)
        box = Box((2, 3), (4, 5))
        assert x[box.to_index()].shape == (4, 5)


class TestIntersection:
    def test_overlap_1d(self):
        r = intersect_boxes(Box((0,), (10,)), Box((5,), (10,)))
        assert r == Box((5,), (5,))

    def test_disjoint(self):
        assert intersect_boxes(Box((0,), (5,)), Box((5,), (5,))) is None
        assert intersect_boxes(Box((0, 0), (2, 2)), Box((0, 2), (2, 2))) is None

    def test_2d_partial(self):
        r = intersect_boxes(Box((0, 0), (4, 4)), Box((2, 2), (4, 4)))
        assert r == Box((2, 2), (2, 2))

    def test_contained(self):
        r = intersect_boxes(Box((0, 0), (8, 8)), Box((1, 2), (3, 4)))
        assert r == Box((1, 2), (3, 4))

    def test_rank_mismatch(self):
        with pytest.raises(ValueError):
            intersect_boxes(Box((0,), (1,)), Box((0, 0), (1, 1)))


class TestDestinationView:
    def test_full(self):
        dest = np.zeros((4, 4))
        v = get_destination_view(dest, Box((0, 0), (4, 4)), Box((0, 0), (4, 4)))
        assert v is dest or v.base is dest

    def test_row_block_contiguous(self):
        dest = np.zeros((8, 4))
        v = get_destination_view(dest, Box((0, 0), (8, 4)), Box((2, 0), (3, 4)))
        assert v is not None and v.shape == (3, 4) and v.flags["C_CONTIGUOUS"]
        v[:] = 1.0
        assert dest[2:5].sum() == 12.0

    def test_column_block_not_contiguous(self):
        dest = np.zeros((8, 4))
        v = get_destination_view(dest, Box((0, 0), (8, 4)), Box((0, 1), (8, 2)))
        assert v is None

    def test_column_block_allowed_when_not_required(self):
        dest = np.zeros((8, 4))
        v = get_destination_view(
            dest, Box((0, 0), (8, 4)), Box((0, 1), (8, 2)), require_contiguous=False
        )
        assert v is not None and v.shape == (8, 2)

    def test_outside(self):
        dest = np.zeros((4,))
        assert get_destination_view(dest, Box((4,), (4,)), Box((0,), (2,))) is None

    def test_offset_dest(self):
        dest = np.zeros((4, 4))
        v = get_destination_view(dest, Box((4, 0), (4, 4)), Box((5, 0), (2, 4)))
        assert v is not None and v.shape == (2, 4)
        v[:] = 7
        assert dest[1:3].sum() == 7 * 8

    def test_single_element_always_ok(self):
        dest = np.zeros((4, 4))
        v = get_destination_view(dest, Box((0, 0), (4, 4)), Box((1, 1), (1, 1)))
        assert v is not None


class TestAssemble:
    def test_1d_tiles(self):
        parts = [(np.arange(5.0), (0,)), (np.arange(5.0, 10.0), (5,))]
        out, off = assemble_tensor(parts)
        assert off == (0,)
        np.testing.assert_array_equal(out, np.arange(10.0))

    def test_2d_quadrants(self):
        full = np.arange(16.0).reshape(4, 4)
        parts = [
            (full[:2, :2].copy(), (0, 0)),
            (full[:2, 2:].copy(), (0, 2)),
            (full[2:, :2].copy(), (2, 0)),
            (full[2:, 2:].copy(), (2, 2)),
        ]
        out, off = assemble_tensor(parts)
        assert off == (0, 0)
        np.testing.assert_array_equal(out, full)

    def test_offset_region(self):
        parts = [(np.ones((2, 2)), (2, 2)), (np.ones((2, 2)) * 2, (2, 4))]
        out, off = assemble_tensor(parts)
        assert off == (2, 2)
        assert out.shape == (2, 4)

    def test_single_part_no_copy(self):
        p = np.arange(6.0).reshape(2, 3)
        out, off = assemble_tensor([(p, (4, 0))])
        assert out is p and off == (4, 0)

    def test_gap_raises(self):
        parts = [(np.ones((2,)), (0,)), (np.ones((2,)), (4,))]
        with pytest.raises(ValueError, match="do not tile"):
            assemble_tensor(parts)

    def test_dtype_mismatch(self):
        parts = [
            (np.ones((2,), np.float32), (0,)),
            (np.ones((2,), np.float64), (2,)),
        ]
        with pytest.raises(ValueError, match="dtype"):
            assemble_tensor(parts)

    def test_overlapping_replicas_allowed(self):
        # Replicated shards produce overlapping parts; last-writer wins and
        # coverage accounting still >= bbox size.
        parts = [(np.ones((4,)), (0,)), (np.ones((4,)) * 2, (0,))]
        out, _ = assemble_tensor(parts)
        np.testing.assert_array_equal(out, np.full((4,), 2.0))

    def test_bounding_box(self):
        bb = bounding_box([Box((1, 1), (2, 2)), Box((3, 0), (1, 4))])
        assert bb == Box((1, 0), (3, 4))


class TestMemoryOverlap:
    def test_views_overlap(self):
        dest = np.zeros((10,))
        assert tensors_overlap_in_memory(dest, [dest[0:5], dest[5:10]])

    def test_copy_does_not(self):
        dest = np.zeros((10,))
        assert not tensors_overlap_in_memory(dest, [dest[0:5].copy()])

    def test_other_array(self):
        dest = np.zeros((10,))
        other = np.zeros((10,))
        assert not tensors_overlap_in_memory(dest, [other[0:5]])


class TestByteView:
    def test_roundtrip(self):
        x = np.arange(10, dtype=np.float32)
        b = to_byte_view(x)
        assert b.dtype == np.uint8 and b.nbytes == 40
        b[0:4] = np.frombuffer(np.float32(99.0).tobytes(), dtype=np.uint8)
        assert x[0] == 99.0

    def test_non_contiguous_rejected(self):
        x = np.arange(16, dtype=np.float32).reshape(4, 4)
        with pytest.raises(ValueError):
            to_byte_view(x[:, 1:3])


class TestTraceExport:
    def test_chrome_trace_events_written(self, tmp_path):
        import json

        from torchstore_tpu import logging as tslog

        trace_path = str(tmp_path / "trace.json")
        old = tslog._trace.path
        tslog._trace.path = trace_path
        try:
            tracker = tslog.LatencyTracker("unit_op")
            tracker.track_step("phase_a", nbytes=1000)
            tracker.track_step("phase_b")
            tslog._trace.flush()
        finally:
            tslog._trace.path = old
        with open(trace_path) as f:
            content = f.read()
        # JSON *array* trace format: the closing bracket is optional (the
        # file remains loadable after a crash mid-run).
        data = json.loads(
            content if content.rstrip().endswith("]") else content + "\n]"
        )
        names = [e["name"] for e in data]
        assert "unit_op/phase_a" in names and "unit_op/phase_b" in names
        ev = next(e for e in data if e["name"] == "unit_op/phase_a")
        assert ev["ph"] == "X" and ev["dur"] >= 0 and ev["args"]["bytes"] == 1000

    def test_disabled_is_noop(self):
        from torchstore_tpu import logging as tslog

        tracker = tslog.LatencyTracker("noop")
        tracker.track_step("s")  # no env -> no events collected


class TestBoxSubtraction:
    """subtract_box / boxes_cover: the exact-coverage primitives the direct
    device pull uses to reject publications with holes (overlap-safe)."""

    def test_subtract_disjoint(self):
        from torchstore_tpu.utils import Box, subtract_box

        base = Box((0, 0), (4, 4))
        assert subtract_box(base, Box((10, 10), (2, 2))) == [base]

    def test_subtract_full_cover(self):
        from torchstore_tpu.utils import Box, subtract_box

        assert subtract_box(Box((1, 1), (2, 2)), Box((0, 0), (8, 8))) == []

    def test_subtract_partial_preserves_elements(self):
        import numpy as np

        from torchstore_tpu.utils import Box, subtract_box

        base = Box((0, 0), (6, 6))
        cut = Box((2, 2), (2, 3))
        pieces = subtract_box(base, cut)
        # Pieces are disjoint and tile base minus cut exactly.
        grid = np.zeros((6, 6), int)
        for p in pieces:
            region = tuple(slice(o, o + s) for o, s in zip(p.offsets, p.shape))
            grid[region] += 1
        cut_region = tuple(slice(o, o + s) for o, s in zip(cut.offsets, cut.shape))
        assert grid[cut_region].sum() == 0
        grid[cut_region] += 1
        np.testing.assert_array_equal(grid, np.ones((6, 6), int))

    def test_boxes_cover_with_overlaps(self):
        from torchstore_tpu.utils import Box, boxes_cover

        region = Box((0,), (10,))
        assert boxes_cover(region, [Box((0,), (6,)), Box((4,), (6,))])
        # Duplicated cover of one half must NOT mask the missing half.
        assert not boxes_cover(
            region, [Box((0,), (5,)), Box((0,), (5,)), Box((0,), (5,))]
        )

    def test_boxes_cover_exact_tiling(self):
        from torchstore_tpu.utils import Box, boxes_cover

        region = Box((0, 0), (4, 4))
        tiles = [Box((i, j), (2, 2)) for i in (0, 2) for j in (0, 2)]
        assert boxes_cover(region, tiles)
        assert not boxes_cover(region, tiles[:3])
