"""Fleet time-series history, trend detection, and ts-top (ISSUE 17).

Covers the retention layer (observability/history.py: ring math, the
downsample min/max/last discipline, counter-rate derivation across a
process restart, the series cap), the detectors (observability/detect.py:
sustained / drift / ramp with injected clocks), the fleet surfaces
(``ts.history()`` with a dead volume, ``/history.json`` on the HTTP
exporter, flight-recorder dumps embedding vitals), the ISSUE-17 acceptance
leg (an induced ``shm.landing_stamp`` delay ramp makes the
sustained-overload detector fire in ``slo_report()["trends"]`` AND in
``ts.control_plan()``'s snapshot BEFORE any instantaneous SLO gate trips),
and the ts-top console (pure renderers plus one live frame per attach
mode).
"""

import asyncio
import importlib.util
import json
import os
import pathlib
import sys
import time
import urllib.request

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.observability import detect as obs_detect
from torchstore_tpu.observability import history as obs_history
from torchstore_tpu.observability import http_exporter
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO_ROOT / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------
# ring math (pure units)
# --------------------------------------------------------------------------


class TestRing:
    def test_same_bucket_merges_min_max_last_sum_count(self):
        ring = obs_history._Ring(1.0, 8)
        ring.add(100.2, 5.0)
        ring.add(100.7, 1.0)
        ring.add(100.9, 3.0)
        rows = ring.points(0.0)
        assert rows == [[100.0, 1.0, 5.0, 3.0, 9.0, 3]]

    def test_stale_slot_is_overwritten_not_merged(self):
        ring = obs_history._Ring(1.0, 4)
        ring.add(10.0, 1.0)  # bucket 10 -> slot 2
        ring.add(14.0, 2.0)  # bucket 14 -> slot 2 again: retention wrap
        rows = ring.points(0.0)
        assert rows == [[14.0, 2.0, 2.0, 2.0, 2.0, 1]]

    def test_points_filter_and_order(self):
        ring = obs_history._Ring(1.0, 16)
        for t in (5.5, 3.2, 7.9):
            ring.add(t, t)
        rows = ring.points(4.0)
        assert [r[0] for r in rows] == [5.0, 7.0]

    def test_spike_survives_downsample_to_60s_via_max(self):
        """One 1-second spike inside a quiet minute: the 60s ring's mean
        barely moves, but its max column still shows the spike and last
        shows the closing value — the downsample contract."""
        series = obs_history.Series("s", "gauge", obs_history.LEVELS)
        t0 = 6000.0  # 60s-aligned: the whole minute lands in one bucket
        for i in range(60):
            series.add(t0 + i, 250.0 if i == 17 else 1.0)
        coarse = series.rings[2].points(0.0)
        assert len(coarse) == 1
        _ts, vmin, vmax, vlast, vsum, count = coarse[0]
        assert vmax == 250.0 and vmin == 1.0 and vlast == 1.0
        assert count == 60 and vsum == 59 * 1.0 + 250.0
        # The 1s ring still holds the spike bucket exactly.
        fine = series.rings[0].points(t0 + 17)
        assert fine[0][:4] == [t0 + 17, 250.0, 250.0, 250.0]


class _FakeRegistry:
    """A registry stand-in: ``sample_values()`` rows are scripted per
    sweep so restart semantics are testable without forking."""

    def __init__(self):
        self.rows = []

    def sample_values(self):
        return list(self.rows)


class TestSeriesStore:
    def test_query_picks_finest_covering_level(self):
        store = obs_history.SeriesStore()
        now = 10_000.0
        for dt in range(6):
            store.observe("g", "gauge", float(dt), now=now - dt)
        assert store.query(series="g", since=60, now=now)["step_s"] == 1.0
        assert store.query(series="g", since=2000, now=now)["step_s"] == 10.0
        assert store.query(series="g", since=20000, now=now)["step_s"] == 60.0
        assert store.query(series="g", level=60.0, now=now)["step_s"] == 60.0
        with pytest.raises(ValueError, match="unknown history level"):
            store.query(series="g", level=5, now=now)

    def test_absolute_since_timestamp(self):
        store = obs_history.SeriesStore()
        t0 = 2_000_000_000.0
        store.observe("g", "gauge", 1.0, now=t0)
        store.observe("g", "gauge", 2.0, now=t0 + 100)
        doc = store.query(series="g", since=t0 + 50, now=t0 + 101)
        assert [r[0] for r in doc["series"]["g"]["points"]] == [t0 + 100]

    def test_counter_rate_derivation_survives_restart(self):
        """A counter dropping below its predecessor is a process restart:
        the new value IS the delta, the rate never goes negative."""
        store = obs_history.SeriesStore()
        fake = _FakeRegistry()
        t0 = 5_000.0
        for dt, value in ((0, 10.0), (1, 16.0), (2, 4.0)):
            fake.rows = [("ts_fake_total", "counter", (), value)]
            store.sample(registry=fake, now=t0 + dt)
        doc = store.query(series="ts_fake_total:rate", level=0, now=t0 + 3)
        points = doc["series"]["ts_fake_total:rate"]["points"]
        assert [(r[0], r[3]) for r in points] == [(t0 + 1, 6.0), (t0 + 2, 4.0)]
        assert all(r[3] >= 0 for r in points)
        # The raw cumulative series is retained alongside.
        raw = store.query(series="ts_fake_total", level=0, now=t0 + 3)
        assert len(raw["series"]["ts_fake_total"]["points"]) == 3

    def test_max_series_cap_drops_never_allocates(self):
        store = obs_history.SeriesStore(max_series=4)
        for i in range(6):
            store.observe(f"g{i}", "gauge", 1.0, now=1000.0)
        assert len(store) == 4
        assert store._dropped == {"g4", "g5"}

    def test_disabled_store_samples_nothing(self):
        store = obs_history.SeriesStore()
        store.set_enabled(False)
        fake = _FakeRegistry()
        fake.rows = [("ts_fake_total", "counter", (), 1.0)]
        assert store.sample(registry=fake, now=1.0) == 0.0
        assert len(store) == 0


class TestMergeHelpers:
    def test_series_matches_bare_name_covers_labeled_variants(self):
        assert obs_history.series_matches("ts_x", ("ts_x",))
        assert obs_history.series_matches('ts_x{v="1"}', ("ts_x",))
        assert not obs_history.series_matches("ts_xy", ("ts_x",))
        assert obs_history.series_matches("ts_xy", ("ts_x*",))
        assert obs_history.series_matches('ts_x{v="1"}', ('ts_x{v="1"}',))
        assert not obs_history.series_matches('ts_x{v="2"}', ('ts_x{v="1"}',))

    def test_merge_points_sum_and_max(self):
        a = [[0.0, 1.0, 2.0, 1.5, 3.0, 2]]
        b = [[0.0, 0.5, 4.0, 1.0, 5.0, 4], [1.0, 9.0, 9.0, 9.0, 9.0, 1]]
        summed = obs_history.merge_points([a, b], how="sum")
        assert summed == [
            [0.0, 1.5, 6.0, 2.5, 8.0, 6],
            [1.0, 9.0, 9.0, 9.0, 9.0, 1],
        ]
        worst = obs_history.merge_points([a, b], how="max")
        assert worst[0] == [0.0, 0.5, 4.0, 1.5, 5.0, 4]
        with pytest.raises(ValueError, match="merge_points"):
            obs_history.merge_points([a], how="avg")

    def test_counter_rate_points_skip_first_and_restart(self):
        rows = [
            [0.0, 10.0, 10.0, 10.0, 10.0, 1],
            [1.0, 16.0, 16.0, 16.0, 16.0, 1],
            [3.0, 4.0, 4.0, 4.0, 4.0, 1],  # restart: 4 < 16, gap of 2s
        ]
        assert obs_history.counter_rate_points(rows) == [
            [1.0, 6.0],
            [3.0, 2.0],
        ]


# --------------------------------------------------------------------------
# detectors (pure functions, injected clocks)
# --------------------------------------------------------------------------


def _rows(vals, t0=0.0, step=1.0):
    return [
        [t0 + i * step, v, v, v, v, 1] for i, v in enumerate(vals)
    ]


class TestDetectors:
    def test_sustained_counts_trailing_run_only(self):
        result = obs_detect.sustained(
            _rows([5, 5, 0, 5, 5]), threshold=1.0, min_samples=3
        )
        assert not result["active"] and result["samples"] == 2
        result = obs_detect.sustained(
            _rows([5, 5, 0, 5, 5]), threshold=1.0, min_samples=2
        )
        assert result["active"]
        assert result["since_ts"] == 3.0 and result["duration_s"] == 1.0
        # Latest bucket under threshold: run resets to zero.
        result = obs_detect.sustained(
            _rows([5, 5, 0]), threshold=1.0, min_samples=1
        )
        assert not result["active"] and result["samples"] == 0

    def test_ewma_drift_fires_on_jump_and_clamps_flat_baseline(self):
        quiet = _rows([1.0] * 20)
        assert not obs_detect.ewma_drift(quiet, z=3.0)["active"]
        jump = _rows([1.0] * 20 + [100.0])
        result = obs_detect.ewma_drift(jump, z=3.0)
        # Zero-variance baseline: clamped to MAX_Z, never Infinity.
        assert result["active"] and result["z"] == obs_detect.MAX_Z
        short = obs_detect.ewma_drift(_rows([1.0, 99.0]), min_samples=8)
        assert not short["active"] and short["samples"] == 2

    def test_ramp_least_squares_slope(self):
        rising = _rows([2.0 * i for i in range(10)])
        result = obs_detect.ramp(rising, min_slope=1.0)
        assert result["active"] and result["slope"] == pytest.approx(2.0)
        assert not obs_detect.ramp(rising, min_slope=0.0)["active"]
        flat = obs_detect.ramp(_rows([7.0] * 10), min_slope=1.0)
        assert not flat["active"] and flat["slope"] == pytest.approx(0.0)

    def test_evaluate_detector_rejects_unknown_kind(self):
        det = obs_detect.Detector(name="x", series="ts_landing_inflight", kind="wat")
        with pytest.raises(ValueError, match="unknown detector kind"):
            obs_detect.evaluate_detector(det, [])

    def test_evaluate_trends_worst_labeled_series_wins(self):
        store = obs_history.SeriesStore()
        now = 50_000.0
        for dt in range(10):
            store.observe(
                'ts_landing_inflight{volume="v0"}', "gauge", 40.0, now=now - dt
            )
            store.observe(
                'ts_landing_inflight{volume="v1"}', "gauge", 0.0, now=now - dt
            )
        dets = (
            obs_detect.Detector(
                name="landing_inflight_sustained",
                series="ts_landing_inflight",
                kind="sustained",
                threshold=16.0,
                min_samples=5,
            ),
        )
        trends = obs_detect.evaluate_trends(store=store, detectors=dets, now=now)
        result = trends["landing_inflight_sustained"]
        assert result["active"] and result["kind"] == "sustained"
        assert result["series"] == 'ts_landing_inflight{volume="v0"}'
        assert obs_detect.active_sustained(trends) == {
            "landing_inflight_sustained": result
        }
        # An inactive result never makes the control-plane subset.
        assert obs_detect.active_sustained(
            {"a": {"active": False, "kind": "sustained"}}
        ) == {}


# --------------------------------------------------------------------------
# ts-top pure renderers
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ts_top():
    return _load_script("ts_top")


class TestTsTopRender:
    def test_spark_scales_and_survives_empty(self, ts_top):
        assert ts_top.spark([]) == "(no data)"
        line = ts_top.spark([0.0, 5.0, 10.0])
        assert len(line) == 3 and line[0] != line[2]
        assert len(set(ts_top.spark([3.0, 3.0, 3.0]))) == 1

    def test_trend_arrow_marks(self, ts_top):
        assert ts_top.trend_arrow({}) == "="
        arrow = ts_top.trend_arrow(
            {
                "a": {"kind": "sustained", "active": True},
                "b": {"kind": "ramp", "active": True},
                "c": {"kind": "drift", "active": False},
            }
        )
        assert arrow == "".join(sorted("!^"))

    def test_fleet_rate_and_gauge_series_fold_processes(self, ts_top):
        doc = {
            "processes": {
                "client": {
                    "series": {
                        'ts_client_ops_total{op="put"}': {
                            "kind": "counter",
                            "points": _rows([0.0, 10.0, 30.0]),
                        },
                        'ts_op_p99_seconds{op="get"}': {
                            "kind": "gauge",
                            "points": _rows([0.010, 0.020, 0.015]),
                        },
                    }
                },
                "volume:v0": {
                    "series": {
                        'ts_client_ops_total{op="put"}': {
                            "kind": "counter",
                            "points": _rows([0.0, 5.0, 5.0]),
                        },
                        'ts_op_p99_seconds{op="get"}': {
                            "kind": "gauge",
                            "points": _rows([0.040, 0.001, 0.001]),
                        },
                    }
                },
            }
        }
        ops = ts_top.fleet_rate_series(doc, "ts_client_ops_total")
        assert ops == [[1.0, 15.0], [2.0, 20.0]]
        p99 = ts_top.fleet_gauge_series(doc, 'ts_op_p99_seconds{op="get"}')
        assert p99 == [[0.0, 0.040], [1.0, 0.020], [2.0, 0.015]]

    def test_fleet_gauge_sum_series_totals_volumes(self, ts_top):
        doc = {
            "processes": {
                "volume:v0": {
                    "series": {
                        "ts_blob_bytes": {
                            "kind": "gauge",
                            "points": _rows([100.0, 200.0]),
                        }
                    }
                },
                "volume:v1": {
                    "series": {
                        "ts_blob_bytes": {
                            "kind": "gauge",
                            "points": _rows([50.0, 25.0]),
                        }
                    }
                },
            }
        }
        total = ts_top.fleet_gauge_sum_series(doc, "ts_blob_bytes")
        assert total == [[0.0, 150.0], [1.0, 225.0]]
        assert ts_top.fleet_gauge_sum_series(doc, "ts_absent") == []

    def test_fmt_bytes_scales(self, ts_top):
        assert ts_top.fmt_bytes(512) == "512"
        assert ts_top.fmt_bytes(2048) == "2.0K"
        assert ts_top.fmt_bytes(3 * 1024 * 1024) == "3.0M"

    def test_render_frame_full_and_empty(self, ts_top):
        data = {
            "source": "store:unit",
            "generated_ts": 1_700_000_000.0,
            "history": {
                "processes": {
                    "client": {
                        "series": {
                            "ts_client_ops_total": {
                                "kind": "counter",
                                "points": _rows([0.0, 4.0, 12.0]),
                            }
                        }
                    }
                },
                "errors": {"volume:v1": "ActorDiedError"},
            },
            "slo": {
                "slos": {
                    "get_p99_ms": {
                        "threshold": 50.0,
                        "current": 75.0,
                        "violated": True,
                        "violations": 3,
                    }
                },
                "trends": {
                    "landing_inflight_sustained": {
                        "kind": "sustained",
                        "active": True,
                        "series": 'ts_landing_inflight{volume="v0"}',
                        "duration_s": 12.0,
                    }
                },
            },
            "overload": {
                "volumes": {
                    "v0": {
                        "landing_inflight": 9,
                        "doorbell_plans": 2,
                        "window_ops": 100,
                        "trends": {
                            "landing_inflight_sustained": {
                                "kind": "sustained",
                                "active": True,
                            }
                        },
                    }
                }
            },
            "plan": {
                "actions": [
                    {"kind": "migrate", "subject": "k", "reason": "hot"}
                ],
                "snapshot": {
                    "sustained_overload": {
                        "v0": ["landing_inflight_sustained"]
                    }
                },
            },
            "events": [{"ts": 1.0, "kind": "fault", "name": "shm.landing"}],
            "autoscale": {
                "actions": [
                    {
                        "kind": "scale_out",
                        "subject": "fleet",
                        "reason": "landing brackets saturated on v0",
                    }
                ],
                "fleet": {
                    "volumes": 3,
                    "draining": ["v2"],
                    "idle_rounds": 0,
                    "spilled_keys": {"v0": 5},
                },
            },
        }
        data["history"]["processes"]["client"]["series"][
            "ts_fleet_volumes"
        ] = {"kind": "gauge", "points": _rows([1.0, 2.0, 3.0])}
        data["history"]["processes"]["client"]["series"][
            "ts_blob_bytes"
        ] = {"kind": "gauge", "points": _rows([0.0, 4096.0])}
        frame = ts_top.render_frame(data)
        assert "ts-top — store:unit" in frame
        assert "ops/s" in frame and "get p99" in frame
        assert "VIOLATED" in frame
        assert "trend ! landing_inflight_sustained" in frame
        assert "v0" in frame and "[!]" in frame
        assert "sustained_overload v0: landing_inflight_sustained" in frame
        assert "plan migrate k" in frame
        assert "[fault] shm.landing" in frame
        assert "unreachable: volume:v1" in frame
        assert "3 vol (1 draining" in frame
        assert "blob 4.0K" in frame
        assert "5 key(s) blob-eligible" in frame
        assert "plan scale_out fleet: landing brackets saturated" in frame
        # Every section optional: an empty frame still renders.
        assert ts_top.render_frame({}).startswith("ts-top")


# --------------------------------------------------------------------------
# fleet surfaces
# --------------------------------------------------------------------------


class TestLocalSurfaces:
    def test_history_json_http_roundtrip(self, monkeypatch):
        """/history.json serves the same rings SeriesStore.query does,
        with series/since/level query params honored."""
        # A long pytest session can fill the process-global store to its
        # series cap; this test's series must not be the one dropped.
        monkeypatch.setenv(obs_history.ENV_HISTORY_MAX_SERIES, "100000")
        store = obs_history.series_store()
        sid = "ts_hist_rt_gauge"
        store.observe(sid, "gauge", 7.0)
        exp = http_exporter.start_http_exporter(0, host="127.0.0.1")
        try:
            base = f"http://127.0.0.1:{exp.port}"
            doc = json.loads(
                urllib.request.urlopen(
                    f"{base}/history.json?series={sid},ts_none*&since=300"
                    "&level=0",
                    timeout=10,
                ).read()
            )
            assert doc["step_s"] == 1.0
            local = store.query(series=sid, since=300, level=0)
            assert doc["series"][sid]["points"] == local["series"][sid]["points"]
            assert doc["series"][sid]["points"][-1][3] == 7.0
        finally:
            exp.close()

    def test_flight_dump_embeds_history_vitals(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv(obs_history.ENV_HISTORY_MAX_SERIES, "100000")
        sid = 'ts_landing_inflight{volume="hist_fr"}'
        obs_history.series_store().observe(sid, "gauge", 11.0)
        rec = obs_recorder.FlightRecorder(maxlen=8)
        rec.record("fault", "unit.history")
        path = rec.dump("unit:history")
        assert path and os.path.exists(path)
        doc = json.loads(open(path).read())
        # The curated DEFAULT_DUMP_SERIES vitals ride every post-mortem.
        assert sid in doc["history"]["series"]
        assert doc["history"]["series"][sid]["points"][-1][3] == 11.0


@pytest.mark.anyio
async def test_fleet_history_merges_and_tolerates_dead_volume():
    """ts.history() collects client + controller + every volume's rings;
    a dead volume lands in errors, never fails the scrape."""
    from torchstore_tpu.runtime import ActorDiedError

    await ts.initialize(store_name="hist_dead", num_storage_volumes=2)
    try:
        await ts.put(
            "hist/k", np.ones(64, np.float32), store_name="hist_dead"
        )
        # Give every process at least one sampler sweep.
        await asyncio.sleep(1.5)
        doc = await ts.history(store_name="hist_dead")
        assert "client" in doc["processes"]
        assert "controller" in doc["processes"]
        volumes = [k for k in doc["processes"] if k.startswith("volume:")]
        assert len(volumes) == 2, doc["processes"].keys()
        client_doc = doc["processes"]["client"]
        assert client_doc["levels"] == [list(lv) for lv in obs_history.LEVELS]
        handle = ts.api._stores["hist_dead"]
        victim = handle.volume_mesh._processes[0]
        victim.terminate()
        victim.join(10.0)
        doc = await ts.history(store_name="hist_dead")
        assert len(doc["errors"]) == 1, doc["errors"]
        assert "client" in doc["processes"]
    finally:
        try:
            await ts.shutdown("hist_dead")
        except (ActorDiedError, Exception):
            pass


@pytest.mark.anyio
async def test_sustained_overload_fires_before_slo_gate(monkeypatch):
    """ISSUE 17 acceptance: under an induced ``shm.landing_stamp`` delay
    ramp the sustained-overload detector fires in
    ``slo_report()["trends"]`` AND reaches ``ts.control_plan()``'s
    snapshot while every instantaneous SLO gate is still green — the
    burst-vs-regime-change distinction the detectors exist for."""
    monkeypatch.setenv("TORCHSTORE_TPU_HISTORY_INTERVAL_S", "0.1")
    monkeypatch.setenv("TORCHSTORE_TPU_TREND_INFLIGHT", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_TREND_SUSTAIN_SAMPLES", "2")
    # Instantaneous gates parked far away: nothing may trip them.
    monkeypatch.setenv("TORCHSTORE_TPU_SLO_PUT_P99_MS", "60000")
    monkeypatch.setenv("TORCHSTORE_TPU_SLO_GET_P99_MS", "60000")
    await ts.initialize(
        store_name="hist_sus",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    stop = asyncio.Event()

    async def hammer(key, arr):
        while not stop.is_set():
            await ts.put(key, arr, store_name="hist_sus")

    tasks = []
    try:
        arrs = {
            f"sus/{i}": np.random.rand(4096).astype(np.float32)
            for i in range(3)
        }
        for key, arr in arrs.items():
            await ts.put(key, arr, store_name="hist_sus")
        # Every put holds its landing bracket an extra 250ms: inflight
        # stays pinned >= 1 — a held regime, not a burst.
        await ts.inject_fault(
            "shm.landing_stamp", "delay", delay_ms=250, store_name="hist_sus"
        )
        tasks = [
            asyncio.create_task(hammer(k, a)) for k, a in arrs.items()
        ]
        fired = None
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            report = await ts.slo_report(store_name="hist_sus")
            active = {
                name: result
                for name, result in (report.get("trends") or {}).items()
                if "landing_inflight_sustained" in name
                and result.get("active")
            }
            if active:
                fired = (report, active)
                break
            await asyncio.sleep(0.3)
        assert fired is not None, "sustained detector never fired"
        report, active = fired
        # The detector beat the instantaneous gates: both parked SLOs are
        # green at the moment the trend is already active.
        for name in ("put_p99_ms", "get_p99_ms"):
            assert not report["slos"][name]["violated"], report["slos"][name]
        # Volume-side detections surface with their process key.
        assert any(name.startswith("volume:") for name in active), active
        # ... and the SAME signal reaches the control plane's snapshot.
        plan = await ts.control_plan(store_name="hist_sus")
        sustained = plan["snapshot"]["sustained_overload"]
        assert sustained, plan["snapshot"]
        assert any(
            "landing_inflight_sustained" in dets
            for dets in sustained.values()
        ), sustained
    finally:
        stop.set()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        try:
            await ts.clear_faults(store_name="hist_sus")
        finally:
            await ts.shutdown("hist_sus")


@pytest.mark.anyio
async def test_ts_top_renders_live_frames_both_attach_modes(ts_top):
    """One real frame per attach mode: --store (fleet view) and --url
    (single-process exporter view)."""
    await ts.initialize(
        store_name="hist_top",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        arr = np.random.rand(1024).astype(np.float32)
        await ts.put("top/k", arr, store_name="hist_top")
        out = await ts.get("top/k", store_name="hist_top")
        np.testing.assert_array_equal(out, arr)
        await asyncio.sleep(1.2)  # one sampler sweep so sparklines have data
        data = await ts_top.collect_store("hist_top")
        frame = ts_top.render_frame(data)
        assert "ts-top — store:hist_top" in frame
        assert "ops/s" in frame and "SLOs" in frame
        exp = http_exporter.start_http_exporter(0, host="127.0.0.1")
        try:
            data = ts_top.collect_url(f"http://127.0.0.1:{exp.port}")
            frame = ts_top.render_frame(data)
            assert f"127.0.0.1:{exp.port}" in frame
            assert "ops/s" in frame
        finally:
            exp.close()
    finally:
        await ts.shutdown("hist_top")


@pytest.mark.anyio
async def test_capture_telemetry_doc_includes_history():
    """The capture_telemetry doc (what --watch appends per line) carries
    the history plane next to traffic/slo/control_plan."""
    mod = _load_script("capture_telemetry")
    await ts.initialize(
        store_name="telemetry_capture",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        await ts.put(
            "cap/k", np.ones(256, np.float32), store_name="telemetry_capture"
        )
        doc = await mod._capture(ts, include_record=False)
        assert set(doc) >= {"captured_ts", "traffic", "slo", "control_plan", "history"}
        assert "client" in doc["history"]["processes"]
        json.dumps(doc)  # the JSONL line must serialize
    finally:
        await ts.shutdown("telemetry_capture")
