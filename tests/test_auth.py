"""Connection-auth tests: HMAC challenge-response on actor / rendezvous /
bulk listeners (ADVICE r1: unauthenticated pickle-over-TCP), plus the
end-to-end store path with a secret configured."""

import asyncio
import os

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import config as config_mod
from torchstore_tpu.config import StoreConfig
from torchstore_tpu.runtime import auth


@pytest.fixture
def secret_env():
    """Set a process-wide auth secret for the test and restore after."""
    old = os.environ.get("TORCHSTORE_TPU_AUTH_SECRET")
    os.environ["TORCHSTORE_TPU_AUTH_SECRET"] = "test-secret-123"
    config_mod._default_config = None
    yield "test-secret-123"
    if old is None:
        os.environ.pop("TORCHSTORE_TPU_AUTH_SECRET", None)
    else:
        os.environ["TORCHSTORE_TPU_AUTH_SECRET"] = old
    config_mod._default_config = None


class TestChallengeResponse:
    async def _serve_once(self, secret):
        accepted = asyncio.get_running_loop().create_future()

        async def handle(reader, writer):
            ok = await auth.server_authenticate(reader, writer, secret)
            if not accepted.done():
                accepted.set_result(ok)
            if ok:
                writer.write(b"WELCOME!")
                await writer.drain()
            writer.close()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        return server, port, accepted

    async def test_right_secret_accepted(self):
        server, port, accepted = await self._serve_once("s3cret")
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await auth.client_authenticate(reader, writer, "s3cret")
        assert await reader.readexactly(8) == b"WELCOME!"
        assert await accepted is True
        writer.close()
        server.close()

    async def test_wrong_secret_rejected(self):
        server, port, accepted = await self._serve_once("s3cret")
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await auth.client_authenticate(reader, writer, "WRONG")
        assert await accepted is False
        # Server closes without serving anything.
        assert await reader.read(8) == b""
        writer.close()
        server.close()

    async def test_no_auth_client_rejected(self):
        server, port, accepted = await self._serve_once("s3cret")
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        # A client unaware of auth writes its normal first frame; the server
        # reads it as a (wrong) MAC and closes without parsing anything.
        writer.write(b"\x00" * 64)
        await writer.drain()
        assert await accepted is False
        writer.close()
        server.close()

    async def test_secret_client_plain_server_fails_loudly(self):
        async def handle(reader, writer):
            await asyncio.sleep(0.2)
            writer.write(b"\x01" * 20)  # some non-challenge response
            await writer.drain()

        server = await asyncio.start_server(handle, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        with pytest.raises(ConnectionError, match="did not issue a challenge"):
            await auth.client_authenticate(reader, writer, "s3cret")
        writer.close()
        server.close()

    async def test_disabled_is_zero_overhead(self):
        server, port, accepted = await self._serve_once(None)
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await auth.client_authenticate(reader, writer, None)
        assert await reader.readexactly(8) == b"WELCOME!"
        writer.close()
        server.close()


@pytest.mark.parametrize("transport", ["shm", "bulk", "rpc"])
async def test_store_roundtrip_with_auth(secret_env, transport):
    """Full store path (actor RPC + data transport) with auth enabled."""
    await ts.initialize(
        store_name="auth",
        strategy=ts.SingletonStrategy(default_transport_type=transport),
        config=StoreConfig(auth_secret=secret_env),
    )
    try:
        x = np.random.rand(4096).astype(np.float32)
        await ts.put("k", x, store_name="auth")
        np.testing.assert_array_equal(await ts.get("k", store_name="auth"), x)
    finally:
        await ts.shutdown("auth")


async def test_rogue_connection_to_actor_server_rejected(secret_env):
    await ts.initialize(
        store_name="auth2", config=StoreConfig(auth_secret=secret_env)
    )
    try:
        from torchstore_tpu import api

        ref = api._stores["auth2"].controller
        reader, writer = await asyncio.open_connection(ref.host, ref.port)
        # Rogue peer with the WRONG secret: completes the challenge with a
        # bad MAC; the server must close without processing any frame.
        await auth.client_authenticate(reader, writer, "wrong-secret")
        assert await reader.read(16) == b""  # connection dropped
        writer.close()
        # The store itself still works for authenticated clients.
        await ts.put("ok", np.ones(8), store_name="auth2")
        np.testing.assert_array_equal(
            await ts.get("ok", store_name="auth2"), np.ones(8)
        )
    finally:
        await ts.shutdown("auth2")
