"""Blob cold tier (torchstore_tpu/tiering/blob.py, ISSUE 18).

Bottom-up: the emulated object store's contract (crash-safe puts, torn
writers invisible to list, the latency/rate service envelope, the
``blob.io`` faultpoint), the per-volume ``BlobTier`` bookkeeping
(archive/load/restore/discard, restart resume, reset-vs-purge
durability), the fleet manifest, and finally the live fleet paths:
disk→blob demotion via ``blob_sweep``, byte-identical fault-in through
plain gets, and ``ts.blob_checkpoint()`` → scale-to-zero →
``ts.blob_restore()`` onto a brand-new fleet.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import faults
from torchstore_tpu.tiering import blob as blob_mod
from torchstore_tpu.tiering.blob import (
    MANIFEST_OBJECT,
    BlobStore,
    BlobTier,
    read_fleet_manifest,
    write_fleet_manifest,
)
from torchstore_tpu.transport.types import Request, TensorMeta


@pytest.fixture
def store(tmp_path):
    return BlobStore(root=str(tmp_path / "blob"))


def _tensor_entry(key, arr):
    return [Request(key=key, tensor_meta=TensorMeta.of(arr))], {0: arr}


# ---------------------------------------------------------------------------
# BlobStore: the emulated object service
# ---------------------------------------------------------------------------


class TestBlobStore:
    def test_put_get_head_list_delete(self, store):
        assert store.put("a/b/k0", b"hello") == 5
        store.put("a/b/k1", b"world!")
        store.put("other", b"x")
        assert store.get("a/b/k0") == b"hello"
        size, mtime = store.head("a/b/k1")
        assert size == 6 and mtime > 0
        assert store.list("a/b/") == ["a/b/k0", "a/b/k1"]
        assert store.list() == ["a/b/k0", "a/b/k1", "other"]
        assert store.delete("a/b/k0") is True
        assert store.delete("a/b/k0") is False  # idempotent
        assert store.list("a/b/") == ["a/b/k1"]

    def test_missing_object_raises(self, store):
        with pytest.raises(KeyError):
            store.get("nope")
        with pytest.raises(KeyError):
            store.head("nope")

    def test_overwrite_replaces(self, store):
        store.put("k", b"v1")
        store.put("k", b"v2-longer")
        assert store.get("k") == b"v2-longer"
        assert store.list() == ["k"]

    def test_torn_put_invisible_to_list(self, store):
        """A writer killed between write-temp and rename leaves only a
        ``*.tmp.<pid>`` file — never a trusted object."""
        store.put("good", b"data")
        torn = store._path("torn") + ".tmp.12345"
        with open(torn, "wb") as f:
            f.write(b"partial")
        assert store.list() == ["good"]
        with pytest.raises(KeyError):
            store.get("torn")

    def test_foreign_files_skipped(self, store, tmp_path):
        store.put("k", b"v")
        # Not urlsafe-b64 of anything: must not break list().
        with open(os.path.join(store.root, "README~"), "w") as f:
            f.write("not an object")
        assert store.list() == ["k"]

    def test_latency_and_rate_envelope(self, tmp_path):
        fast = BlobStore(root=str(tmp_path / "f"), latency_ms=0, rate_mbps=0)
        slow = BlobStore(root=str(tmp_path / "s"), latency_ms=40, rate_mbps=1)
        payload = b"x" * 100_000  # 0.1 s at 1 MB/s
        t0 = time.monotonic()
        fast.put("k", payload)
        fast_s = time.monotonic() - t0
        t0 = time.monotonic()
        slow.put("k", payload)
        slow_s = time.monotonic() - t0
        # 40 ms latency + ~100 ms rate stall, minus scheduler slack.
        assert slow_s >= 0.1
        assert slow_s > fast_s

    def test_blob_io_faultpoint(self, store):
        faults.arm("blob.io", "raise", count=1)
        try:
            with pytest.raises(faults.FaultInjectedError):
                store.put("k", b"v")
            store.put("k", b"v")  # budget spent: next op serves
            assert store.get("k") == b"v"
        finally:
            faults.disarm("blob.io")


# ---------------------------------------------------------------------------
# BlobTier: per-volume bookkeeping
# ---------------------------------------------------------------------------


class TestBlobTier:
    def test_archive_load_round_trip(self, store):
        tier = BlobTier("v0", store=store)
        arr = np.arange(256, dtype=np.float32)
        metas, values = _tensor_entry("t", arr)
        nbytes = tier.archive("t", metas, values)
        assert nbytes > 0 and tier.archived == {"t": nbytes}
        assert tier.archived_bytes == nbytes
        got_metas, got_values = tier.load("t")
        assert got_metas[0].key == "t"
        assert np.array_equal(got_values[0], arr)
        # Objects ride the same envelope.
        obj = {"step": 7, "tags": ["a", "b"]}
        tier.archive("o", [Request(key="o", is_object=True)], {0: obj})
        ometas, ovalues = tier.load("o")
        assert ometas[0].is_object and ovalues[0] == obj
        with pytest.raises(KeyError):
            tier.load("missing")

    def test_restored_drops_object(self, store):
        tier = BlobTier("v0", store=store)
        tier.archive("t", *_tensor_entry("t", np.zeros(8)))
        tier.restored("t", reason="get")
        assert tier.archived == {}
        assert store.list(tier.prefix) == []

    def test_pinned_restore_keeps_checkpoint_object(self, store):
        """A fault-in promotion of a checkpoint-pinned key must KEEP the
        blob object — the fleet manifest references it, and dropping it
        would destroy the durable copy a cold restore replays."""
        tier = BlobTier("v0", store=store)
        n = tier.archive("t", *_tensor_entry("t", np.zeros(8)))
        tier.pin(["t"])
        tier.restored("t", reason="get")
        assert tier.archived == {"t": n}
        assert store.list(tier.prefix) == [tier.object_name("t")]
        # An overwrite ABOVE the tier still supersedes the checkpoint.
        assert tier.discard("t") is True
        assert store.list(tier.prefix) == []

    def test_pins_seed_from_manifest(self, store):
        """A restarted volume keeps honoring the last committed manifest:
        its keys come back pinned, other volumes' keys do not."""
        t1 = BlobTier("v0", store=store)
        n = t1.archive("t", *_tensor_entry("t", np.zeros(8)))
        write_fleet_manifest(
            store,
            {"t": {"object": t1.object_name("t"), "nbytes": n, "write_gen": 1}},
        )
        assert BlobTier("v0", store=store).pinned == {"t"}
        assert BlobTier("v1", store=store).pinned == set()

    def test_discard_idempotent(self, store):
        tier = BlobTier("v0", store=store)
        tier.archive("t", *_tensor_entry("t", np.zeros(8)))
        assert tier.discard("t") is True
        assert tier.discard("t") is False
        assert store.list(tier.prefix) == []

    def test_restart_resumes_archive(self, store):
        """A restarted volume process seeds ``archived`` from the store:
        the blob tier survives the process, not just the object bytes."""
        t1 = BlobTier("v0", store=store)
        arr = np.arange(64, dtype=np.int32)
        n = t1.archive("t", *_tensor_entry("t", arr))
        t2 = BlobTier("v0", store=store)
        assert t2.archived == {"t": n}
        _m, values = t2.load("t")
        assert np.array_equal(values[0], arr)
        # Volumes do not see each other's namespaces.
        assert BlobTier("v1", store=store).archived == {}

    def test_manifest_excludes_warmer_tiers(self, store):
        tier = BlobTier("v0", store=store)
        tier.archive("a", *_tensor_entry("a", np.zeros(4)))
        tier.archive("b", *_tensor_entry("b", np.ones(4)))
        items = tier.manifest(exclude={"a"})
        assert [item["meta"].key for item in items] == ["b"]
        assert all(item["mtime"] > 0 for item in items)

    def test_reset_keeps_objects_purge_deletes(self, store):
        tier = BlobTier("v0", store=store)
        tier.archive("t", *_tensor_entry("t", np.zeros(8)))
        tier.reset()
        assert tier.archived == {}
        # The objects are the durable tier: a fresh view resumes them.
        assert "t" in BlobTier("v0", store=store).archived
        tier2 = BlobTier("v0", store=store)
        tier2.purge()
        assert BlobTier("v0", store=store).archived == {}
        assert store.list() == []


class TestFleetManifest:
    def test_round_trip_and_absent(self, store):
        assert read_fleet_manifest(store) is None
        keys = {
            "k0": {"object": "vol/v0/k0", "nbytes": 10, "write_gen": 2},
            "k1": {"object": "vol/v1/k1", "nbytes": 20, "write_gen": 1},
        }
        write_fleet_manifest(store, keys, extra={"volumes": 2})
        doc = read_fleet_manifest(store)
        assert doc["keys"] == keys
        assert doc["volumes"] == 2
        # Crash-safe like any put: the manifest object is valid JSON on
        # disk, no temp debris beside it.
        raw = store.get(MANIFEST_OBJECT)
        assert json.loads(raw.decode())["keys"]["k1"]["nbytes"] == 20


# ---------------------------------------------------------------------------
# fleet: demote / fault-in / checkpoint / cold restore
# ---------------------------------------------------------------------------


@pytest.fixture
def blob_env(monkeypatch, tmp_path):
    monkeypatch.setenv("TORCHSTORE_TPU_BLOB_ENABLED", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_BLOB_DIR", str(tmp_path / "blobfleet"))
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_ENABLED", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_BUDGET_BYTES", str(1 << 20))
    return str(tmp_path / "blobfleet")


async def _demote_all(c, keys):
    """Force disk spill then blob demotion for ``keys`` on every volume."""
    swept = []
    for vid, ref in c._volume_refs.items():
        await ref.actor.tier_sweep.call_one(demote_keys=list(keys))
        rep = await ref.actor.blob_sweep.call_one(32)
        swept.extend(rep["archived"])
    return swept


async def test_blob_demote_and_fault_in(blob_env):
    await ts.initialize(num_storage_volumes=2, store_name="blobf")
    try:
        arrs = {
            f"k{i}": np.arange(500, dtype=np.float32) * (i + 1)
            for i in range(5)
        }
        arrs["obj"] = {"step": 3, "lr": 0.1}
        for k, v in arrs.items():
            await ts.put(k, v, store_name="blobf")
        c = ts.client("blobf")
        await c._ensure_setup()
        swept = await _demote_all(c, arrs)
        assert sorted(swept) == sorted(arrs)
        # Residency is visible in stats while the bytes live in blob only.
        blob_keys = 0
        for ref in c._volume_refs.values():
            st = await ref.actor.stats.call_one()
            blob_keys += st.get("tier", {}).get("blob_keys", 0)
        assert blob_keys == len(arrs)
        # Plain gets fault the entries back in, byte-identical.
        for k, v in arrs.items():
            got = await ts.get(k, store_name="blobf")
            if isinstance(v, dict):
                assert got == v
            else:
                assert np.array_equal(got, v), k
        # Fault-in consumed the blob copies (promotion, not a cache).
        blob_keys = 0
        for ref in c._volume_refs.values():
            st = await ref.actor.stats.call_one()
            blob_keys += st.get("tier", {}).get("blob_keys", 0)
        assert blob_keys == 0
    finally:
        await ts.shutdown("blobf")


async def test_overwrite_discards_stale_blob_copy(blob_env):
    await ts.initialize(store_name="blobow")
    try:
        await ts.put("k", np.zeros(100, dtype=np.float32), store_name="blobow")
        c = ts.client("blobow")
        await c._ensure_setup()
        await _demote_all(c, ["k"])
        fresh = np.ones(100, dtype=np.float32)
        await ts.put("k", fresh, store_name="blobow")
        got = await ts.get("k", store_name="blobow")
        assert np.array_equal(got, fresh)
        for ref in c._volume_refs.values():
            st = await ref.actor.stats.call_one()
            assert st.get("tier", {}).get("blob_keys", 0) == 0
    finally:
        await ts.shutdown("blobow")


async def test_checkpoint_scale_to_zero_cold_restore(blob_env):
    """The headline: checkpoint the fleet to blob, kill EVERYTHING, start
    a brand-new fleet, ``ts.blob_restore()`` — every committed key comes
    back byte-identical with zero client errors."""
    arrs = {
        f"w{i}": np.arange(800, dtype=np.float32) + i * 1000 for i in range(4)
    }
    arrs["meta"] = {"epoch": 12}
    await ts.initialize(num_storage_volumes=2, store_name="blobckpt")
    try:
        for k, v in arrs.items():
            await ts.put(k, v, store_name="blobckpt")
        rep = await ts.blob_checkpoint(store_name="blobckpt")
        assert rep["keys"] == len(arrs) and not rep["errors"], rep
    finally:
        await ts.shutdown("blobckpt")
        ts.reset_client()

    # Scale-to-zero happened above: no volume survives. Fresh fleet.
    await ts.initialize(num_storage_volumes=1, store_name="blobcold")
    try:
        rep = await ts.blob_restore(store_name="blobcold")
        assert rep["restored"] == len(arrs), rep
        assert not rep["failed"], rep
        for k, v in arrs.items():
            got = await ts.get(k, store_name="blobcold")
            if isinstance(v, dict):
                assert got == v
            else:
                assert np.array_equal(got, v), k
    finally:
        await ts.shutdown("blobcold")


async def test_reads_after_checkpoint_preserve_cold_copies(blob_env):
    """Ordinary traffic AFTER a checkpoint must not destroy it: resident
    keys never re-fault from blob (no wasted round trip, no deleted
    object), and a blob-only key's fault-in keeps its pinned checkpoint
    object — so a later kill-all + ``ts.blob_restore()`` still recovers
    every committed key byte-identical."""
    arrs = {
        f"c{i}": np.arange(300, dtype=np.float32) * (i + 1) for i in range(3)
    }
    arrs["obj"] = {"step": 9}
    await ts.initialize(num_storage_volumes=2, store_name="blobrd")
    try:
        for k, v in arrs.items():
            await ts.put(k, v, store_name="blobrd")
        c = ts.client("blobrd")
        await c._ensure_setup()
        # One key lives blob-ONLY before the checkpoint (demoted): its
        # post-checkpoint read exercises the pinned fault-in path.
        assert await _demote_all(c, ["c0"]) == ["c0"]
        rep = await ts.blob_checkpoint(store_name="blobrd")
        assert rep["keys"] == len(arrs) and not rep["errors"], rep
        for k, v in arrs.items():
            got = await ts.get(k, store_name="blobrd")
            if isinstance(v, dict):
                assert got == v
            else:
                assert np.array_equal(got, v), k
        # Every checkpointed object survived the reads.
        blob_keys = 0
        for ref in c._volume_refs.values():
            st = await ref.actor.stats.call_one()
            blob_keys += st.get("tier", {}).get("blob_keys", 0)
        assert blob_keys == len(arrs)
    finally:
        await ts.shutdown("blobrd")
        ts.reset_client()

    await ts.initialize(num_storage_volumes=1, store_name="blobrd2")
    try:
        rep = await ts.blob_restore(store_name="blobrd2")
        assert rep["restored"] == len(arrs), rep
        assert not rep["failed"], rep
        for k, v in arrs.items():
            got = await ts.get(k, store_name="blobrd2")
            if isinstance(v, dict):
                assert got == v
            else:
                assert np.array_equal(got, v), k
    finally:
        await ts.shutdown("blobrd2")


async def test_blob_restore_requires_manifest(blob_env):
    await ts.initialize(store_name="blobnomf")
    try:
        with pytest.raises(RuntimeError):
            await ts.blob_restore(store_name="blobnomf")
    finally:
        await ts.shutdown("blobnomf")
