"""Scale-out metadata plane (ISSUE 14): sharded controller index +
one-sided stamped metadata reads.

Covers the whole stack: the stable key->shard hash and the router's
partition/merge vocabulary (pure units), a sharded fleet end-to-end
(puts/gets/keys/exists/delete/waits and a streamed publish whose
watermarks route through the coordinator AFTER the owning shards index
the batch), the zero-RPC warm-path acceptance (plan validation, same-host
locate, stream polling all measured at ZERO controller RPCs in
``ts.traffic_matrix()["metadata"]``), the stamped seqlock machinery
(torn-write fallback, tombstones), the deterministic chaos leg (one
controller shard killed mid-put-storm via the ``controller.shard_dispatch``
faultpoint: clients fail loudly, coordinator-scoped state survives, no
committed key on a surviving shard is lost), and the regression tests for
the single-controller-ref assumptions in ``_raise_with_diagnosis`` and
the health supervisor (both route through the coordinator now).
"""

import asyncio
import pickle

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.metadata import INDEX_OPS, shard_of
from torchstore_tpu.metadata import stamped as stamped_mod
from torchstore_tpu.metadata.shards import (
    partition_keys,
    partition_metas,
    slice_write_gens,
)
from torchstore_tpu.runtime import ActorDiedError
from torchstore_tpu.transport.types import Request

pytestmark = pytest.mark.anyio


# --------------------------------------------------------------------------
# units: hashing + partitioning
# --------------------------------------------------------------------------


def test_shard_of_is_stable_and_total():
    """crc32 sharding: deterministic across processes/runs (clients,
    coordinator, and shards must all agree), total over any string, and
    identity at 1 shard."""
    keys = [f"ns/k{i}" for i in range(500)] + ["", "a/b/c", "é"]
    for key in keys:
        assert shard_of(key, 1) == 0
        s = shard_of(key, 4)
        assert 0 <= s < 4
        assert shard_of(key, 4) == s  # stable on repeat
    # All shards actually used at this scale (hash spreads).
    assert len({shard_of(k, 4) for k in keys}) == 4


def test_partition_vocabulary():
    keys = [f"k{i}" for i in range(64)]
    parts = partition_keys(keys, 4)
    assert sorted(k for ks in parts.values() for k in ks) == sorted(keys)
    for i, ks in parts.items():
        assert all(shard_of(k, 4) == i for k in ks)
    metas = [Request.from_tensor(k, np.zeros(2, np.float32)).meta_only()
             for k in keys]
    mparts = partition_metas(metas, 4)
    assert sum(len(ms) for ms in mparts.values()) == len(metas)
    gens = {"v0": {k: i for i, k in enumerate(keys)}}
    sliced = slice_write_gens(gens, set(parts[0]))
    assert set(sliced["v0"]) == set(parts[0])
    assert slice_write_gens(None, {"x"}) is None


# --------------------------------------------------------------------------
# unit: the stamped seqlock segment
# --------------------------------------------------------------------------


def test_stamped_writer_reader_roundtrip_and_tombstone():
    payload = {"hello": 1}
    writer = stamped_mod.MetaStampWriter(lambda: payload, size=64 << 10)
    try:
        writer.publish_now()
        reader = stamped_mod.MetaStampReader(
            writer.seg.name, writer.size
        )
        gen1, obj, epoch = reader.read()
        assert obj == {"hello": 1} and epoch == 0
        # Unchanged generation: header-only re-read serves the cache.
        gen2, obj2, _ = reader.read()
        assert gen2 == gen1 and obj2 is obj
        payload["hello"] = 2
        writer.publish_now()
        gen3, obj3, _ = reader.read()
        assert gen3 > gen1 and obj3 == {"hello": 2}
        # A payload outgrowing the segment tombstones it: readers get a
        # PERMANENT MetaUnavailable (they stand down to the RPC path).
        payload["big"] = b"x" * (128 << 10)
        writer.publish_now()
        with pytest.raises(stamped_mod.MetaUnavailable) as exc:
            reader.read()
        assert exc.value.reason == "tombstone"
    finally:
        writer.close()


def test_stamped_reader_never_published():
    writer = stamped_mod.MetaStampWriter(lambda: {}, size=64 << 10)
    try:
        reader = stamped_mod.MetaStampReader(writer.seg.name, writer.size)
        with pytest.raises(stamped_mod.MetaUnavailable):
            reader.read()
        assert reader.generation() is None
    finally:
        writer.close()


def test_stamped_torn_write_detected():
    """A write-in-flight (odd seqlock) or a publish racing the payload
    copy is detected and surfaces as a torn fallback, never bad bytes."""
    writer = stamped_mod.MetaStampWriter(lambda: {"v": 1}, size=64 << 10)
    try:
        writer.publish_now()
        reader = stamped_mod.MetaStampReader(writer.seg.name, writer.size)
        # Force the seqlock odd (writer mid-publish from the reader's view).
        writer.words[0] = int(writer.words[0]) + 1
        with pytest.raises(stamped_mod.MetaUnavailable) as exc:
            reader.read()
        assert exc.value.reason == "torn"
        writer.words[0] = int(writer.words[0]) + 1  # settle even again
        _, obj, _ = reader.read()
        assert obj == {"v": 1}
    finally:
        writer.close()


# --------------------------------------------------------------------------
# fleet: sharded metadata plane end-to-end
# --------------------------------------------------------------------------


async def test_sharded_store_end_to_end():
    """A 3-shard fleet serves the full core-op surface with classic
    semantics: batched puts/gets across shards, prefix keys, exists,
    deletes (through the coordinator's lease guard + stream retire),
    wait_for, and per-shard ownership actually spread."""
    await ts.initialize(
        num_storage_volumes=2, store_name="mp3", controller_shards=3
    )
    try:
        c = ts.client("mp3")
        items = {
            f"mp3k/{i}": np.full((16,), i, np.float32) for i in range(48)
        }
        await ts.put_batch(items, store_name="mp3")
        out = await ts.get_batch(list(items), store_name="mp3")
        for k, v in items.items():
            assert np.array_equal(out[k], v), k
        assert await ts.keys("mp3k", store_name="mp3") == sorted(items)
        assert await ts.exists("mp3k/3", store_name="mp3")
        assert not await ts.exists("mp3k/nope", store_name="mp3")
        await c.wait_for(list(items)[:5], timeout=10)
        # Ownership is spread: every shard holds a nonempty slice.
        router = c.controller
        assert len(router.shard_refs) == 3
        per_shard = await asyncio.gather(
            *(ref.summary.call_one() for ref in router.shard_refs)
        )
        assert all(s["num_keys"] > 0 for s in per_shard), per_shard
        assert sum(s["num_keys"] for s in per_shard) >= len(items)
        # Coordinator stats merge the shard rollups.
        stats = await router.stats.call_one()
        assert stats["num_keys"] >= len(items)
        assert stats["metadata_shards"] == 3
        assert stats["puts"] >= len(items)
        # Deletes: guard -> shard drop -> stream retire; idempotent.
        await ts.delete_batch(["mp3k/0", "mp3k/1"], store_name="mp3")
        assert not await ts.exists("mp3k/0", store_name="mp3")
        with pytest.raises(KeyError):
            await ts.get("mp3k/0", store_name="mp3")
        # wait_for_change routes to the owning shard.
        res = await c.wait_for_change("mp3k/2", 0, timeout=5)
        assert res["state"] == "committed"
    finally:
        await ts.shutdown("mp3")


async def test_sharded_streamed_publish_acquire():
    """Streamed publish under sharding: layer watermarks are recorded on
    the coordinator strictly AFTER the owning shards indexed each batch,
    and a streaming reader serves a consistent single-generation dict."""
    await ts.initialize(
        num_storage_volumes=1, store_name="mpst", controller_shards=2
    )
    try:
        served = []
        stream = ts.state_dict_stream("sd", store_name="mpst")
        await stream.put({"a": np.ones((64,), np.float32)})
        await stream.put({"b": np.full((64,), 2.0, np.float32)})
        await stream.seal()
        got = await ts.get_state_dict(
            "sd",
            stream=True,
            on_layer=lambda k, v: served.append(k),
            store_name="mpst",
        )
        assert np.array_equal(got["a"], np.ones((64,), np.float32))
        assert np.array_equal(got["b"], np.full((64,), 2.0, np.float32))
        assert sorted(served) == ["a", "b"]
    finally:
        await ts.shutdown("mpst")


# --------------------------------------------------------------------------
# acceptance: warm-path metadata is ZERO controller RPCs
# --------------------------------------------------------------------------


async def _metadata_counts():
    tm = await ts.traffic_matrix("mpz")
    return tm["metadata"]


async def test_warm_path_zero_metadata_rpcs():
    """The ISSUE-14 acceptance, measured: after warmup, same-host locate
    (fresh client, cold caches), cached-plan validation, and streamed
    wait_for_stream polling all run with ZERO controller RPCs — every
    one served from the stamped segments and counted as such in
    ``ts.traffic_matrix()["metadata"]``."""
    await ts.initialize(num_storage_volumes=1, store_name="mpz")
    try:
        c = ts.client("mpz")
        items = {
            f"wz/{i}": np.full((256,), i, np.float32) for i in range(8)
        }
        await ts.put_batch(items, store_name="mpz")
        # Let the debounced stamped publishes land.
        await asyncio.sleep(4 * stamped_mod.publish_interval_s() + 0.05)

        # --- same-host locate on a COLD client: zero RPCs ---------------
        ts.reset_client("mpz")
        c = ts.client("mpz")
        await c._ensure_setup()
        before = await _metadata_counts()
        out = await ts.get_batch(list(items), store_name="mpz")
        for k, v in items.items():
            assert np.array_equal(out[k], v)
        after = await _metadata_counts()
        assert after["rpcs"].get("locate_volumes", 0) == before["rpcs"].get(
            "locate_volumes", 0
        ), (before, after)
        assert after["stamped"].get("locate_volumes", 0) > before[
            "stamped"
        ].get("locate_volumes", 0)

        # --- warm plan validation: zero RPCs ----------------------------
        # Two identical batched gets: the second validates its cached plan
        # against the STAMPED epoch (confirmation fast path).
        await ts.get_batch(list(items), store_name="mpz")
        await c.placement_epoch()  # adopt the current epoch once (RPC ok)
        before = await _metadata_counts()
        epoch = await c.placement_epoch()
        after = await _metadata_counts()
        assert epoch > 0
        assert after["rpcs"].get("placement_epoch", 0) == before["rpcs"].get(
            "placement_epoch", 0
        ), (before, after)
        assert after["stamped"].get("placement_epoch", 0) > before[
            "stamped"
        ].get("placement_epoch", 0)

        # --- streamed wait_for_stream polling: zero RPCs ----------------
        stream = ts.state_dict_stream("zs", store_name="mpz")
        await stream.put({"l0": np.ones((64,), np.float32)})
        await stream.put({"l1": np.ones((64,), np.float32)})
        await stream.seal()
        await asyncio.sleep(4 * stamped_mod.publish_interval_s() + 0.05)
        before = await _metadata_counts()
        got = await ts.get_state_dict("zs", stream=True, store_name="mpz")
        assert set(got) == {"l0", "l1"}
        after = await _metadata_counts()
        assert after["rpcs"].get("wait_for_stream", 0) == before["rpcs"].get(
            "wait_for_stream", 0
        ), (before, after)
        assert after["stamped"].get("wait_for_stream", 0) > before[
            "stamped"
        ].get("wait_for_stream", 0)
    finally:
        await ts.shutdown("mpz")


async def test_stamped_disabled_falls_back_to_rpc(monkeypatch):
    """TORCHSTORE_TPU_META_STAMPED=0: no segments are attached, every
    metadata op is a counted RPC — the knob and the fallback ladder both
    work (and the RPC path is what the sharded bench measures)."""
    monkeypatch.setenv("TORCHSTORE_TPU_META_STAMPED", "0")
    from torchstore_tpu import config as config_mod

    config_mod._default_config = None
    try:
        await ts.initialize(num_storage_volumes=1, store_name="mpoff")
        try:
            c = ts.client("mpoff")
            await ts.put("offk", np.ones((32,), np.float32),
                         store_name="mpoff")
            ts.reset_client("mpoff")
            # The ledger is process-cumulative (earlier tests' stamped
            # reads persist): assert on DELTAS across this get only.
            before = (await ts.traffic_matrix("mpoff"))["metadata"]
            await ts.get("offk", store_name="mpoff")
            md = (await ts.traffic_matrix("mpoff"))["metadata"]
            assert md["rpcs"].get("locate_volumes", 0) > before["rpcs"].get(
                "locate_volumes", 0
            ), (before, md)
            assert md["stamped"] == before["stamped"], (before, md)
        finally:
            await ts.shutdown("mpoff")
    finally:
        config_mod._default_config = None


# --------------------------------------------------------------------------
# chaos: one controller shard dies mid-put-storm
# --------------------------------------------------------------------------


async def test_shard_kill_mid_put_storm_fails_loud_coordinator_survives():
    """Deterministic kill of one controller shard under load (the
    ``controller.shard_dispatch`` faultpoint, die action): puts whose keys
    hash to the dead shard fail LOUDLY (ActorDiedError — never silent
    loss, never wrong data), keys owned by surviving shards stay fully
    readable with correct bytes, and every coordinator-scoped subsystem
    (streams, leases, health, epoch) keeps answering."""
    await ts.initialize(
        num_storage_volumes=2, store_name="mpck", controller_shards=2
    )
    try:
        c = ts.client("mpck")
        router = c.controller
        n = 2
        keys = [f"ck/{i}" for i in range(40)]
        committed = {}
        for k in keys[:20]:
            v = np.full((64,), hash(k) % 97, np.float32)
            await ts.put(k, v, store_name="mpck")
            committed[k] = v
        # Arm the kill on shard 0 only: its NEXT dispatch dies.
        await ts.inject_fault(
            "controller.shard_dispatch", "die", scope="shard:0",
            store_name="mpck",
        )
        survivors = [k for k in committed if shard_of(k, n) == 1]
        dead_keys = [k for k in committed if shard_of(k, n) == 0]
        assert survivors and dead_keys  # both shards own committed keys
        # Put storm over fresh keys: everything routed to shard 0 fails
        # loudly once it dies; shard-1 keys keep landing.
        storm_ok, storm_dead = 0, 0
        for k in keys[20:]:
            try:
                await ts.put(
                    k, np.zeros((64,), np.float32), store_name="mpck"
                )
                storm_ok += 1
            except (ActorDiedError, ConnectionError, OSError):
                storm_dead += 1
        assert storm_dead >= 1, "the armed shard never died"
        assert storm_ok >= 1, "surviving shard stopped serving puts"
        # Committed keys on the SURVIVING shard: bytes intact, readable.
        got = await ts.get_batch(
            {k: None for k in survivors}, store_name="mpck"
        )
        for k in survivors:
            assert np.array_equal(got[k], committed[k]), k
        # Dead-shard keys fail loudly at locate — not wrong data. (The
        # stamped index may serve a pre-kill snapshot — also CORRECT data
        # — so force the RPC path via a fresh locate.)
        with pytest.raises((ActorDiedError, ConnectionError, OSError)):
            await router.locate_volumes.call_one([dead_keys[0]])
        # Coordinator-scoped state survives: health, epoch, streams,
        # leases all answer.
        health = await ts.volume_health("mpck")
        assert set(health)  # supervisor still tracking volumes
        assert await router.placement_epoch.call_one() > 0
        assert await router.lease_list.call_one() == {}
        assert await router.stream_state.call_one("never-streamed") is None
    finally:
        await ts.shutdown("mpck")


# --------------------------------------------------------------------------
# fix: diagnosis + health supervisor under sharding
# --------------------------------------------------------------------------


async def test_diagnosis_routes_through_coordinator_when_sharded():
    """``_raise_with_diagnosis`` fans the health check out through the
    COORDINATOR (never a shard): killing a volume under a sharded store
    still yields the controller-diagnosed error string, and the client's
    dead-volume memory comes from the coordinator's verdict."""
    await ts.initialize(
        num_storage_volumes=2, store_name="mpdx", controller_shards=2
    )
    try:
        c = ts.client("mpdx")
        await ts.put("dxk", np.ones((32,), np.float32), store_name="mpdx")
        located = await c.controller.locate_volumes.call_one(["dxk"])
        vid = next(iter(located["dxk"]))
        # Kill the volume process holding the key.
        await ts.inject_fault(
            "volume.get", "die", scope=vid, store_name="mpdx"
        )
        with pytest.raises(ActorDiedError) as exc:
            # Bypass caches/one-sided so the fetch really dials the dead
            # volume (stamped/warm paths would serve the local copy).
            c._loc_cache.clear()
            await c.get("dxk")
        assert "controller diagnosis" in str(exc.value)
    finally:
        await ts.shutdown("mpdx")


async def test_quarantine_pushes_to_shards(monkeypatch):
    """The health supervisor's quarantine verdict reaches every shard
    (set_quarantined push): a sharded locate filters the quarantined
    replica exactly like the classic controller did."""
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTO_REPAIR", "0")
    from torchstore_tpu.strategy import LocalRankStrategy

    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="mpq",
        controller_shards=2,
    )
    try:
        c = ts.client("mpq")
        await ts.put("qk", np.ones((32,), np.float32), store_name="mpq")
        located = await c.controller.locate_volumes.call_one(["qk"])
        assert len(located["qk"]) == 2  # replicated on both volumes
        victim = sorted(located["qk"])[0]
        await ts.inject_fault(
            "actor.ping", "wedge", scope=victim, store_name="mpq"
        )
        deadline = asyncio.get_event_loop().time() + 20
        while True:
            health = await ts.volume_health("mpq")
            if health.get(victim, {}).get("state") == "quarantined":
                break
            assert asyncio.get_event_loop().time() < deadline, health
            await asyncio.sleep(0.2)
        # Give the best-effort shard push a beat, then locate via the
        # owning SHARD: the quarantined replica is filtered.
        deadline = asyncio.get_event_loop().time() + 5
        while True:
            located = await c.controller.locate_volumes.call_one(["qk"])
            if victim not in located["qk"]:
                break
            assert asyncio.get_event_loop().time() < deadline, located
            await asyncio.sleep(0.1)
        assert len(located["qk"]) == 1
    finally:
        await ts.shutdown("mpq")


# --------------------------------------------------------------------------
# router plumbing
# --------------------------------------------------------------------------


async def test_router_counts_every_metadata_rpc():
    """Every controller RPC a client issues lands in the ledger's metadata
    cells per (op, shard) — the measurement the zero-RPC assertions and
    the metadata_scale bench both read."""
    await ts.initialize(
        num_storage_volumes=1, store_name="mprc", controller_shards=2
    )
    try:
        c = ts.client("mprc")
        await ts.put("rck", np.ones((16,), np.float32), store_name="mprc")
        await c.controller.locate_volumes.call_one(["rck"])
        await c.controller.keys.call_one(None)
        tm = await ts.traffic_matrix("mprc")
        md = tm["metadata"]
        assert md["rpcs"].get("notify_put_batch", 0) >= 1, md
        assert md["rpcs"].get("locate_volumes", 0) >= 1, md
        assert md["rpcs"].get("keys", 0) >= 2, md  # fanned to both shards
        shards = set(md["rpcs_by_shard"])
        assert {"s0", "s1"} <= shards or "coord" in shards, md
        # INDEX_OPS is the router's routing table: a new index op must be
        # added there deliberately (this keeps the set honest).
        assert "locate_volumes" in INDEX_OPS
        assert pickle.loads(pickle.dumps(shard_of))("x", 2) == shard_of(
            "x", 2
        )
    finally:
        await ts.shutdown("mprc")
