"""TensorSlice protocol tests: explicit slice reads, multi-volume sharded
puts from rank actors, partial-commit rejection, fully-replicated demotion
(reference tests/test_tensor_slice.py)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import LocalRankStrategy, Shard, TensorSlice
from torchstore_tpu.runtime import Actor, endpoint, spawn_actors

GLOBAL = np.arange(64.0, dtype=np.float32).reshape(8, 8)


def row_slice(rank, world, mesh_shape=None):
    rows = GLOBAL.shape[0] // world
    return TensorSlice(
        offsets=(rank * rows, 0),
        local_shape=(rows, GLOBAL.shape[1]),
        global_shape=GLOBAL.shape,
        coordinates=(rank,),
        mesh_shape=mesh_shape or (world,),
    )


class RankPutActor(Actor):
    def __init__(self):
        import os

        self.rank = int(os.environ["RANK"])
        self.world = int(os.environ["WORLD_SIZE"])

    @endpoint
    async def put_shard(self, key: str):
        sl = row_slice(self.rank, self.world)
        data = GLOBAL[sl.box.to_index()]
        await ts.put(key, Shard(data, sl), store_name="tsl")

    @endpoint
    async def get_shard(self, key: str, other_rank: int):
        sl = row_slice(other_rank, self.world)
        out = await ts.get(key, like=sl, store_name="tsl")
        return np.asarray(out)


@pytest.fixture
async def store():
    await ts.initialize(
        num_storage_volumes=4, strategy=LocalRankStrategy(), store_name="tsl"
    )
    yield "tsl"
    await ts.shutdown("tsl")


async def test_multi_volume_sharded_put_and_slice_get(store):
    actors = await spawn_actors(4, RankPutActor, "rankput")
    try:
        await actors.put_shard.call("w")
        # Each rank reads its neighbor's shard — crosses volumes.
        outs = await actors.get_shard.call("w", 0)
        for out in outs:
            np.testing.assert_array_equal(out, GLOBAL[0:2])
    finally:
        await actors.stop()
    # Full fetch from the parent client assembles across all 4 volumes.
    full = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(full, GLOBAL)


async def test_partial_commit_rejected(store):
    actors = await spawn_actors(4, RankPutActor, "rankput2")
    try:
        # Only ranks 0 and 1 put (mesh_shape says 4 coords are expected).
        await actors[0].put_shard.call_one("p")
        await actors[1].put_shard.call_one("p")
        assert await ts.exists("p", store_name=store)  # present but partial
        with pytest.raises(KeyError, match="partially committed"):
            await ts.get("p", store_name=store)
        # Completing the commit unlocks reads.
        await actors[2].put_shard.call_one("p")
        await actors[3].put_shard.call_one("p")
        np.testing.assert_array_equal(
            await ts.get("p", store_name=store), GLOBAL
        )
    finally:
        await actors.stop()


async def test_explicit_slice_read_of_full_tensor(store):
    await ts.put("full", GLOBAL, store_name=store)
    want = TensorSlice(
        offsets=(2, 4), local_shape=(3, 2), global_shape=(8, 8),
        coordinates=(), mesh_shape=(),
    )
    out = await ts.get("full", like=want, store_name=store)
    np.testing.assert_array_equal(out, GLOBAL[2:5, 4:6])


async def test_slice_read_spanning_shards(store):
    actors = await spawn_actors(4, RankPutActor, "rankput3")
    try:
        await actors.put_shard.call("w2")
    finally:
        await actors.stop()
    # Rows 1..6 span three stored shards (each shard holds 2 rows).
    want = TensorSlice(
        offsets=(1, 0), local_shape=(6, 8), global_shape=(8, 8),
        coordinates=(), mesh_shape=(),
    )
    out = await ts.get("w2", like=want, store_name=store)
    np.testing.assert_array_equal(out, GLOBAL[1:7])


async def test_inplace_shard_get(store):
    await ts.put("full", GLOBAL, store_name=store)
    sl = row_slice(1, 4)
    dest = np.zeros(sl.local_shape, dtype=np.float32)
    out = await ts.get("full", like=Shard(dest, sl), store_name=store)
    assert out is dest
    np.testing.assert_array_equal(dest, GLOBAL[2:4])


async def test_fully_replicated_jax_demotion(store):
    jax = pytest.importorskip("jax")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
    x = jax.device_put(GLOBAL, NamedSharding(mesh, P()))
    await ts.put("rep", x, store_name=store)
    # Demoted to a plain TENSOR: immediately fully committed, readable whole.
    out = await ts.get("rep", store_name=store)
    np.testing.assert_array_equal(out, GLOBAL)


async def test_expert_parallel_distinct_keys(store):
    # EP pattern: each "expert" is a separate key, fully local to its rank
    # (reference MoE demotion use case).
    actors = await spawn_actors(4, _ExpertActor, "experts")
    try:
        await actors.put_expert.call()
    finally:
        await actors.stop()
    for e in range(4):
        out = await ts.get(f"expert/{e}", store_name=store)
        np.testing.assert_array_equal(out, np.full((4, 4), float(e)))


class _ExpertActor(Actor):
    def __init__(self):
        import os

        self.rank = int(os.environ["RANK"])

    @endpoint
    async def put_expert(self):
        await ts.put(
            f"expert/{self.rank}",
            np.full((4, 4), float(self.rank)),
            store_name="tsl",
        )
