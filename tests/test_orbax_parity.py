"""Differential harness: orbax as the resharding oracle.

The reference validates its resharding against torch DCP — save with DCP,
reshard-load through both DCP and torchstore, assert equality
(/root/reference/tests/test_state_dict.py:82-265). Here orbax plays DCP's
role: the same sharded state dict goes through (a) an orbax checkpoint
save/restore with a different target sharding and (b) a store put/get with
that target sharding; both must produce identical arrays.
"""

import numpy as np
import pytest

import torchstore_tpu as ts

jax = pytest.importorskip("jax")
ocp = pytest.importorskip("orbax.checkpoint")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def sharded(arr, shape, names, spec):
    mesh = Mesh(np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape), names)
    return jax.device_put(arr, NamedSharding(mesh, spec))


@pytest.mark.parametrize(
    "src,dst",
    [
        (((8,), ("x",), P("x")), ((4, 2), ("a", "b"), P("a", "b"))),
        (((2, 4), ("x", "y"), P("y", "x")), ((8,), ("z",), P(None, "z"))),
        (((4,), ("f",), P("f")), ((2, 2), ("d", "t"), P(None, "t"))),
    ],
)
async def test_reshard_matches_orbax(tmp_path, src, dst):
    g = np.arange(32 * 16, dtype=np.float32).reshape(32, 16)
    b = np.random.rand(16).astype(np.float32)
    sd = {
        "w": sharded(g, *src),
        "b": sharded(b, (2,), ("r",), P()),
    }

    # --- oracle: orbax save + restore under the target sharding ------------
    ckpt_dir = tmp_path / "ckpt"
    checkpointer = ocp.StandardCheckpointer()
    checkpointer.save(ckpt_dir / "st", sd)
    checkpointer.wait_until_finished()
    target_w = sharded(np.zeros_like(g), *dst)
    target_b = sharded(np.zeros_like(b), (2,), ("r",), P())
    restored = checkpointer.restore(
        ckpt_dir / "st",
        target={
            "w": jax.ShapeDtypeStruct(g.shape, g.dtype, sharding=target_w.sharding),
            "b": jax.ShapeDtypeStruct(b.shape, b.dtype, sharding=target_b.sharding),
        },
    )

    # --- store: put sharded, get under the same target sharding ------------
    await ts.initialize(store_name="orbax")
    try:
        await ts.put_state_dict("sd", sd, store_name="orbax")
        out = await ts.get_state_dict(
            "sd",
            user_state_dict={"w": target_w, "b": target_b},
            store_name="orbax",
        )
    finally:
        await ts.shutdown("orbax")

    for key in ("w", "b"):
        np.testing.assert_array_equal(
            np.asarray(out[key]), np.asarray(restored[key])
        )
        assert out[key].sharding == restored[key].sharding
