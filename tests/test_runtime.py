"""Actor runtime tests: spawn, endpoints, fan-out, zero-copy tensor frames,
error propagation, rank env, singleton registry, shutdown."""

import asyncio
import os

import numpy as np
import pytest

from torchstore_tpu.runtime import (
    Actor,
    ActorMeshRef,
    RemoteActorError,
    endpoint,
    get_or_spawn_singleton,
    spawn_actors,
    stop_singleton,
)


class EchoActor(Actor):
    def __init__(self, scale: float = 1.0):
        self.scale = scale
        self.state = {}

    @endpoint
    async def echo(self, x):
        return x

    @endpoint
    def scale_array(self, arr: np.ndarray) -> np.ndarray:
        return arr * self.scale

    @endpoint
    async def my_rank(self):
        return int(os.environ["RANK"]), int(os.environ["WORLD_SIZE"])

    @endpoint
    async def put(self, k, v):
        self.state[k] = v

    @endpoint
    async def get(self, k):
        return self.state[k]

    @endpoint
    async def boom(self):
        raise KeyError("kaboom")

    def not_an_endpoint(self):
        return "secret"

    @endpoint
    async def peer_get(self, ref, k):
        # Actor-to-actor call: refs must be usable from inside actor processes.
        return await ref.get.call_one(k)


@pytest.fixture
async def mesh():
    m = await spawn_actors(2, EchoActor, "echo", scale=3.0)
    yield m
    await m.stop()


async def test_call_one_roundtrip(mesh):
    assert await mesh.refs[0].echo.call_one({"a": [1, 2]}) == {"a": [1, 2]}


async def test_fanout_and_rank_env(mesh):
    ranks = await mesh.my_rank.call()
    assert ranks == [(0, 2), (1, 2)]


async def test_numpy_zero_copy_roundtrip(mesh):
    arr = np.arange(1_000_000, dtype=np.float32).reshape(1000, 1000)
    out = await mesh.refs[1].scale_array.call_one(arr)
    np.testing.assert_allclose(out, arr * 3.0)
    assert out.dtype == np.float32


async def test_state_persists_across_calls(mesh):
    await mesh.refs[0].put.call_one("k", np.ones(4))
    np.testing.assert_array_equal(await mesh.refs[0].get.call_one("k"), np.ones(4))


async def test_remote_exception_type_preserved(mesh):
    with pytest.raises(KeyError, match="kaboom"):
        await mesh.refs[0].boom.call_one()
    # Remote traceback is attached as the cause chain.
    try:
        await mesh.refs[0].boom.call_one()
    except KeyError as exc:
        assert isinstance(exc.__cause__, RemoteActorError)
        assert "kaboom" in str(exc.__cause__)


async def test_non_endpoint_rejected(mesh):
    with pytest.raises(RemoteActorError, match="not an @endpoint"):
        await mesh.refs[0].not_an_endpoint.call_one()


async def test_missing_key_error(mesh):
    with pytest.raises(KeyError):
        await mesh.refs[0].get.call_one("missing")


async def test_actor_to_actor_calls(mesh):
    await mesh.refs[1].put.call_one("shared", 42)
    ref = mesh.refs[1]
    assert await mesh.refs[0].peer_get.call_one(ref, "shared") == 42


async def test_mesh_ref_pickles_without_processes(mesh):
    import pickle

    m2 = pickle.loads(pickle.dumps(mesh))
    assert isinstance(m2, ActorMeshRef)
    assert await m2.refs[0].echo.call_one(7) == 7


async def test_concurrent_calls_multiplexed(mesh):
    outs = await asyncio.gather(*(mesh.refs[0].echo.call_one(i) for i in range(50)))
    assert outs == list(range(50))


async def test_mesh_indexing(mesh):
    sub = mesh[1]
    assert len(sub) == 1
    assert await sub.my_rank.call_one() == (1, 2)


async def test_call_one_on_multi_mesh_rejected(mesh):
    with pytest.raises(ValueError, match="mesh of size 2"):
        await mesh.my_rank.call_one()


async def test_singleton_registry():
    ref1 = await get_or_spawn_singleton("single_test", EchoActor, scale=2.0)
    ref2 = await get_or_spawn_singleton("single_test", EchoActor, scale=9.0)
    assert ref1.port == ref2.port  # cached, not respawned
    out = await ref1.scale_array.call_one(np.ones(2))
    np.testing.assert_array_equal(out, np.full(2, 2.0))
    await stop_singleton("single_test")


async def test_spawn_failure_surfaces():
    class Exploding(Actor):
        def __init__(self):
            raise RuntimeError("bad init")

    from torchstore_tpu.runtime import ActorDiedError

    with pytest.raises(ActorDiedError, match="bad init"):
        await spawn_actors(1, _ExplodingActor, "exploding")


class _ExplodingActor(Actor):
    def __init__(self):
        raise RuntimeError("bad init")
