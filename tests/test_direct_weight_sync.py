"""Direct (one-hop) weight sync tests: exact match, resharding overlap,
replica dedup, refresh semantics, transfer_dtype, TCP + SHM paths, and the
store-integrated handle flow (reference tests/test_direct_weight_sync.py)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.direct_weight_sync import (
    DirectWeightSyncDest,
    DirectWeightSyncSource,
)

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def make_sharded(arr, mesh_shape, names, spec):
    mesh = Mesh(np.array(jax.devices()[: int(np.prod(mesh_shape))]).reshape(mesh_shape), names)
    return jax.device_put(arr, NamedSharding(mesh, spec))


@pytest.fixture
async def pair():
    source = DirectWeightSyncSource(device=False)
    dest = DirectWeightSyncDest()
    yield source, dest
    await dest.close()
    await source.close()


async def test_exact_match_numpy(pair):
    source, dest = pair
    w = np.random.rand(16, 8).astype(np.float32)
    handles = await source.register({"w": w})
    out = await dest.pull(handles, {"w": np.zeros_like(w)})
    np.testing.assert_array_equal(out["w"], w)


async def test_tcp_path(tmp_path):
    source = DirectWeightSyncSource(use_shm=False, device=False)
    dest = DirectWeightSyncDest()
    try:
        w = np.random.rand(64).astype(np.float32)
        handles = await source.register({"w": w})
        assert handles["w"][0].shm_name is None
        out = await dest.pull(handles, {"w": np.zeros_like(w)})
        np.testing.assert_array_equal(out["w"], w)
    finally:
        await dest.close()
        await source.close()


@pytest.mark.parametrize("src_spec,dst_spec", [
    (P("x"), P(None, "x")),
    (P("x", None), P(None, "x")),
    (P(None, "x"), P("x", None)),
])
async def test_resharding_overlap(pair, src_spec, dst_spec):
    source, dest = pair
    w = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    src = make_sharded(w, (4,), ("x",), src_spec)
    handles = await source.register({"w": src})
    target = make_sharded(np.zeros_like(w), (4,), ("x",), dst_spec)
    out = await dest.pull(handles, {"w": target})
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    assert out["w"].sharding.spec == dst_spec


async def test_replicated_shards_deduped(pair):
    source, dest = pair
    w = np.random.rand(8, 4).astype(np.float32)
    # dp-replicated source: 2x2 mesh, sharded on one axis only -> each
    # region has 2 replicas across coords.
    src = make_sharded(w, (2, 2), ("dp", "x"), P("x"))
    handles = await source.register({"w": src})
    assert len(handles["w"]) == 4  # all shards registered
    out = await dest.pull(handles, {"w": np.zeros_like(w)})
    np.testing.assert_array_equal(out["w"], w)
    # The cached plan covers each region once despite replicas.
    regions = [(op.region.offsets, op.region.shape) for op in dest._plan]
    assert len(regions) == len(set(regions)) == 2


async def test_refresh_re_stages(pair):
    source, dest = pair
    w = np.zeros(8, np.float32)
    handles = await source.register({"w": w})
    out = await dest.pull(handles, {"w": np.zeros_like(w)})
    np.testing.assert_array_equal(out["w"], np.zeros(8))
    # Training step produced new values.
    source.update_sources({"w": np.full(8, 7.0, np.float32)})
    await source.refresh()
    out = await dest.pull(handles, {"w": np.zeros_like(w)})
    np.testing.assert_array_equal(out["w"], np.full(8, 7.0))


async def test_transfer_dtype_cast(pair):
    import ml_dtypes

    source, dest = pair
    w = np.random.rand(32).astype(np.float32)
    handles = await source.register({"w": w}, transfer_dtype=ml_dtypes.bfloat16)
    assert handles["w"][0].meta.dtype == "bfloat16"
    out = await dest.pull(
        handles, {"w": np.zeros(32, ml_dtypes.bfloat16)}
    )
    np.testing.assert_allclose(
        out["w"].astype(np.float32), w, atol=1e-2
    )


async def test_non_tensor_leaves_skipped(pair):
    source, dest = pair
    handles = await source.register({"w": np.ones(4), "cfg": {"lr": 1e-3}})
    assert "cfg/lr" not in handles
    out = await dest.pull(handles, {"w": np.zeros(4), "cfg": {"lr": 0.0}})
    np.testing.assert_array_equal(out["w"], np.ones(4))
    assert out["cfg"]["lr"] == 0.0  # untouched by the direct path


async def test_dead_buffer_raises(pair):
    source, dest = pair
    source_b = DirectWeightSyncSource(use_shm=False, device=False)
    handles = await source_b.register({"w": np.ones(4)})
    await source_b.close()
    # Re-register on a fresh source -> old buffer ids are gone server-side.
    source_c = DirectWeightSyncSource(use_shm=False, device=False)
    await source_c.register({"other": np.ones(2)})
    try:
        bad = {
            "w": [
                type(h)(**{**h.__dict__, "port": source_c.server.port, "buffer_id": 999})
                for h in handles["w"]
            ]
        }
        with pytest.raises(KeyError, match="no longer has buffer"):
            await dest.pull(bad, {"w": np.zeros(4)})
    finally:
        await source_c.close()


async def test_spec_target_direct_pull(pair):
    # ShapeDtypeStruct targets work on the direct path too (not silently
    # returned as metadata stubs).
    source, dest = pair
    w = np.random.rand(8, 8).astype(np.float32)
    src = make_sharded(w, (4,), ("x",), P("x"))
    handles = await source.register({"w": src})
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("a", "b"))
    spec = jax.ShapeDtypeStruct(
        w.shape, w.dtype, sharding=NamedSharding(mesh, P(None, "b"))
    )
    out = await dest.pull(handles, {"w": spec})
    assert shd_is_array(out["w"])
    np.testing.assert_array_equal(np.asarray(out["w"]), w)


def shd_is_array(x):
    import jax as _jax

    return isinstance(x, _jax.Array)


async def test_spec_dtype_honored_buffered():
    import ml_dtypes

    await ts.initialize(store_name="specdt")
    try:
        w = np.random.rand(8, 128).astype(np.float32)
        await ts.put("w", w, store_name="specdt")
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("x",))
        spec = jax.ShapeDtypeStruct(
            w.shape, ml_dtypes.bfloat16, sharding=NamedSharding(mesh, P("x"))
        )
        out = await ts.get("w", like=spec, store_name="specdt")
        assert str(out.dtype) == "bfloat16"
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), w, atol=1e-2
        )
    finally:
        await ts.shutdown("specdt")


async def test_ranged_tcp_reads_with_shard_target():
    # Shard targets pull only their region; over TCP the read is RANGED
    # (fewer bytes on the wire) and lands in the provided buffer.
    source = DirectWeightSyncSource(use_shm=False, device=False)
    dest = DirectWeightSyncDest()
    try:
        w = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
        handles = await source.register({"w": w})
        sl = ts.TensorSlice(
            offsets=(16, 0), local_shape=(8, 8), global_shape=(64, 8),
            coordinates=(0,), mesh_shape=(1,),
        )
        target = np.zeros((8, 8), np.float32)
        out = await dest.pull(handles, {"w": ts.Shard(target, sl)})
        assert out["w"] is target  # wrote straight into the provided buffer
        np.testing.assert_array_equal(target, w[16:24])
        # The planned read range really was partial.
        from torchstore_tpu.direct_weight_sync import _row_range

        (handle,) = handles["w"]
        assert _row_range(handle, dest._plan) == (16, 24)
    finally:
        await dest.close()
        await source.close()


async def test_bufferless_shard_target():
    source = DirectWeightSyncSource(device=False)
    dest = DirectWeightSyncDest()
    try:
        w = np.arange(32.0, dtype=np.float32).reshape(8, 4)
        handles = await source.register({"w": w})
        sl = ts.TensorSlice(
            offsets=(2, 0), local_shape=(4, 4), global_shape=(8, 4),
            coordinates=(0,), mesh_shape=(1,),
        )
        out = await dest.pull(handles, {"w": ts.Shard(None, sl)})
        np.testing.assert_array_equal(out["w"], w[2:6])
    finally:
        await dest.close()
        await source.close()


async def test_multi_rank_buffer_id_collision():
    # Two sources number their buffers from 0: the dest must key reads by
    # (host, port, id), never bare id, or ranks' shards collapse.
    s0 = DirectWeightSyncSource(use_shm=False, device=False)
    s1 = DirectWeightSyncSource(use_shm=False, device=False)
    dest = DirectWeightSyncDest()
    try:
        w = np.arange(64.0, dtype=np.float32).reshape(8, 8)
        # Emulate rank-local registration: each source holds one shard.
        h0 = await s0.register({"w": w[:4].copy()})
        h1 = await s1.register({"w": w[4:].copy()})
        # Rewrite slices so each covers its half of the global space.
        h0["w"][0].tensor_slice = ts.TensorSlice(
            (0, 0), (4, 8), (8, 8), (0,), (2,)
        )
        h1["w"][0].tensor_slice = ts.TensorSlice(
            (4, 0), (4, 8), (8, 8), (1,), (2,)
        )
        assert h0["w"][0].buffer_id == h1["w"][0].buffer_id  # the collision
        merged = {"w": [h0["w"][0], h1["w"][0]]}
        out = await dest.pull(merged, {"w": np.zeros_like(w)})
        np.testing.assert_array_equal(out["w"], w)
    finally:
        await dest.close()
        await s0.close()
        await s1.close()


async def test_volume_health_check():
    await ts.initialize(store_name="hc", num_storage_volumes=2,
                        strategy=ts.LocalRankStrategy())
    try:
        controller = ts.client("hc").controller
        health = await controller.check_volumes.call_one()
        assert health == {"0": "ok", "1": "ok"}
        from torchstore_tpu import api

        handle = api._stores["hc"]
        handle.volume_mesh._processes[1].terminate()
        handle.volume_mesh._processes[1].join(5)
        health = await controller.check_volumes.call_one(timeout=3.0)
        assert health["0"] == "ok" and health["1"].startswith("dead")
    finally:
        from torchstore_tpu import api
        from torchstore_tpu.runtime import stop_singleton

        handle = api._stores.pop("hc", None)
        if handle is not None:
            for proc in handle.volume_mesh._processes:
                if proc.is_alive():
                    proc.terminate()
                    proc.join(5)
        await stop_singleton("ts_hc_controller")


async def test_store_integrated_direct_sync():
    await ts.initialize(store_name="dws")
    try:
        w = np.random.rand(32, 16).astype(np.float32)
        sd = {"model": {"w": w}}
        await ts.put_state_dict("direct/v0", sd, direct=True, store_name="dws")
        out = await ts.get_state_dict(
            "direct/v0",
            user_state_dict={"model": {"w": np.zeros_like(w)}},
            direct=True,
            store_name="dws",
        )
        np.testing.assert_array_equal(out["model"]["w"], w)
        # Second publish refreshes the same registered buffers.
        sd2 = {"model": {"w": w * 2}}
        await ts.put_state_dict("direct/v0", sd2, direct=True, store_name="dws")
        out2 = await ts.get_state_dict(
            "direct/v0",
            user_state_dict={"model": {"w": np.zeros_like(w)}},
            direct=True,
            store_name="dws",
        )
        np.testing.assert_array_equal(out2["model"]["w"], w * 2)
    finally:
        await ts.shutdown("dws")


async def test_store_direct_missing_push():
    await ts.initialize(store_name="dws2")
    try:
        from torchstore_tpu.state_dict_utils import NoMatchingPush

        with pytest.raises(NoMatchingPush):
            await ts.get_state_dict(
                "never", user_state_dict={"w": np.zeros(2)}, direct=True,
                store_name="dws2",
            )
    finally:
        await ts.shutdown("dws2")


async def test_sharded_source_to_sharded_dest_e2e():
    # The flagship flow: trainer fsdp-8 -> generator tp-2x4, one hop.
    await ts.initialize(store_name="dws3")
    try:
        w = np.random.rand(64, 32).astype(np.float32)
        src = make_sharded(w, (8,), ("fsdp",), P("fsdp", None))
        await ts.put_state_dict("m", {"w": src}, direct=True, store_name="dws3")
        target = make_sharded(np.zeros_like(w), (2, 4), ("dp", "tp"), P(None, "tp"))
        out = await ts.get_state_dict(
            "m", user_state_dict={"w": target}, direct=True, store_name="dws3"
        )
        np.testing.assert_array_equal(np.asarray(out["w"]), w)
    finally:
        await ts.shutdown("dws3")


async def test_registered_staging_buffers_publish_in_place():
    """ts.direct_staging_buffers: a trainer that adopts the registered
    buffers makes later direct puts pure publishes — the refresh copy is
    skipped (alias detection) yet pulls see the freshly written weights."""
    await ts.initialize(store_name="stag")
    try:
        sd = {"layer": {"w": np.random.rand(512).astype(np.float32)}}
        user = {"layer": {"w": np.zeros(512, np.float32)}}
        await ts.put_state_dict("m", sd, direct=True, store_name="stag")
        staging = ts.direct_staging_buffers("m", store_name="stag")
        assert staging is not None
        # Buffers already hold the registered values; no re-seeding needed.
        np.testing.assert_array_equal(staging["layer"]["w"], sd["layer"]["w"])
        # Trainer writes a new step's weights straight into the buffers.
        staging["layer"]["w"][:] = 41.5
        await ts.put_state_dict("m", staging, direct=True, store_name="stag")
        out = await ts.get_state_dict(
            "m", user_state_dict=user, direct=True, store_name="stag"
        )
        np.testing.assert_array_equal(out["layer"]["w"], np.full(512, 41.5))
    finally:
        await ts.shutdown("stag")


async def test_staging_buffers_none_for_sharded_sources():
    source = DirectWeightSyncSource(use_shm=False, device=False)
    w = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    src = make_sharded(w, (4,), ("x",), P("x"))
    await source.register({"w": src})
    assert source.staging_state_dict() is None
    await source.close()


class TestGenerationStampedPulls:
    """Seqlock tear detection (VERDICT r2 item 4): a pull concurrent with
    refreshes must return an internally consistent dict — every tensor from
    the SAME published step, never a mix."""

    async def test_concurrent_refresh_pull_is_consistent(self):
        import asyncio

        source = DirectWeightSyncSource(device=False, use_shm=False)
        dest = DirectWeightSyncDest()
        try:
            # Two tensors whose values encode the step: a torn pull would
            # return a/b from different steps.
            step0 = {"a": np.full(256, 0.0, np.float32),
                     "b": np.full(256, 0.0, np.float32)}
            handles = await source.register(step0)

            stop = asyncio.Event()

            async def refresher():
                step = 0
                while not stop.is_set():
                    step += 1
                    source.update_sources(
                        {"a": np.full(256, float(step), np.float32),
                         "b": np.full(256, float(step), np.float32)}
                    )
                    await source.refresh()
                    # Hot but not 100%-duty-cycle: a publisher refreshing on
                    # every event-loop tick would starve ALL pulls (each
                    # would detect a tear on both attempts — still correct,
                    # but nothing to assert about delivered dicts).
                    await asyncio.sleep(0.003)

            task = asyncio.create_task(refresher())
            delivered = 0
            try:
                for _ in range(20):
                    try:
                        out = await dest.pull(
                            handles,
                            {"a": np.zeros(256, np.float32),
                             "b": np.zeros(256, np.float32)},
                        )
                    except RuntimeError as exc:
                        # A DETECTED tear (both attempts raced) is correct
                        # behavior — the contract is "never silently mixed".
                        assert "torn" in str(exc)
                        continue
                    delivered += 1
                    assert out["a"][0] == out["b"][0], (
                        f"torn pull: a@{out['a'][0]} b@{out['b'][0]}"
                    )
                    assert (out["a"] == out["a"][0]).all()
                    assert (out["b"] == out["b"][0]).all()
            finally:
                stop.set()
                await task
            assert delivered > 0  # the hot loop still makes progress
        finally:
            await dest.close()
            await source.close()

    async def test_gen_bumps_by_two_per_publish(self):
        source = DirectWeightSyncSource(device=False, use_shm=False)
        try:
            await source.register({"w": np.zeros(8, np.float32)})
            assert source._gen == 0
            source.update_sources({"w": np.ones(8, np.float32)})
            await source.refresh()
            assert source._gen == 2  # even at rest
        finally:
            await source.close()

    async def test_state_dict_layer_retries_pull_race(self, monkeypatch):
        """A PullRaceError (settle timeout / double tear under hot
        publishes) must not reach the caller on the first bounce: the
        state-dict layer drops its cached handles and retries once
        (ADVICE r3 low)."""
        import torchstore_tpu as ts
        from torchstore_tpu.direct_weight_sync import (
            DirectWeightSyncDest,
            PullRaceError,
        )

        await ts.initialize(store_name="race")
        try:
            sd = {"w": np.arange(32.0, dtype=np.float32)}
            await ts.put_state_dict("m", sd, direct=True, store_name="race")
            real_pull = DirectWeightSyncDest.pull
            calls = {"n": 0}

            async def flaky_pull(self, handles, dest):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise PullRaceError("source refresh never settled")
                return await real_pull(self, handles, dest)

            monkeypatch.setattr(DirectWeightSyncDest, "pull", flaky_pull)
            out = await ts.get_state_dict(
                "m",
                user_state_dict={"w": np.zeros(32, np.float32)},
                direct=True,
                store_name="race",
            )
            np.testing.assert_array_equal(out["w"], sd["w"])
            assert calls["n"] == 2  # failed once, retried with fresh state
        finally:
            await ts.shutdown("race")

    async def test_pull_detects_and_retries_once(self, monkeypatch):
        """Force a gen change between the pre- and post-read: the pull must
        retry (and succeed when the second attempt is stable)."""
        source = DirectWeightSyncSource(device=False, use_shm=False)
        dest = DirectWeightSyncDest()
        try:
            w = np.arange(64.0, dtype=np.float32)
            handles = await source.register({"w": w})
            real_read = dest._read_gen
            calls = {"n": 0}

            async def flaky_read(host, port):
                calls["n"] += 1
                if calls["n"] == 2:  # the post-read of attempt 1
                    return 1_000_000
                return await real_read(host, port)

            monkeypatch.setattr(dest, "_read_gen", flaky_read)
            out = await dest.pull(handles, {"w": np.zeros(64, np.float32)})
            np.testing.assert_array_equal(out["w"], w)
            assert calls["n"] >= 3  # pre, fake post, retry pre+post
        finally:
            await dest.close()
            await source.close()
