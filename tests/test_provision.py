"""Cold-start provisioning subsystem tests (ISSUE 3).

Three tiers:

- pure planner math (manifest derivation, segment/dial plans, replication
  fan-out, oversubscription clamping) — no store needed;
- fault injection: prewarm failures (broken volume executor, tmpfs too
  small, uninitialized store) must degrade to the lazy path — the
  subsequent sync succeeds, errors are reported + counted, nothing raises;
- tier-1 integration: first-put after ``ts.prewarm`` creates ZERO new pool
  segments (the volume's ``ts_shm_segments_created_total`` is flat across
  the put), bulk pre-dial reuse, the auto-hint path, the direct-path plan
  precompute, and controller capacity reservations.
"""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import provision
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.provision.manifest import StateDictManifest
from torchstore_tpu.provision.planner import (
    VolumePlan,
    clamp_to_grant,
    plan_provisioning,
)


# ---------------------------------------------------------------------------
# planner math (pure units)
# ---------------------------------------------------------------------------


def test_manifest_from_numpy_state_dict():
    sd = {
        "layers": {
            "0": np.zeros((4, 8), np.float32),  # 128 B
            "1": np.zeros((16,), np.float64),  # 128 B
        },
        "step": 7,  # object leaf: not provisioned
    }
    m = StateDictManifest.from_state_dict(sd)
    assert len(m.entries) == 2
    assert m.total_bytes == 256
    assert m.segment_sizes() == {128: 2}
    assert not m.device_resident


def test_manifest_transfer_dtype_halves_floating_leaves():
    sd = {"w": np.zeros((64,), np.float32), "ids": np.zeros((64,), np.int32)}
    m = StateDictManifest.from_state_dict(sd, transfer_dtype="bfloat16")
    sizes = m.segment_sizes()
    # float leaf casts to 2-byte bf16; int leaf crosses uncast.
    assert sizes == {128: 1, 256: 1}


def test_manifest_from_sharded_jax_array():
    jax = pytest.importorskip("jax")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchstore_tpu import parallel

    mesh = parallel.make_mesh({"x": 4})
    arr = jax.device_put(
        np.zeros((8, 4), np.float32), NamedSharding(mesh, P("x", None))
    )
    m = StateDictManifest.from_state_dict({"w": arr})
    (entry,) = m.entries
    # 4 shards of (2, 4) f32 = 32 B each; derived WITHOUT materializing.
    assert entry.request_nbytes == (32, 32, 32, 32)
    assert m.segment_sizes() == {32: 4}
    assert m.device_resident


def test_plan_replication_fanout_and_transport_split():
    sd = {"a": np.zeros((1024,), np.float32)}  # 4 KB
    m = StateDictManifest.from_state_dict(sd)
    plan = plan_provisioning(
        m,
        ["v0", "v1", "v2"],
        {"v0": "shm", "v1": "bulk", "v2": "rpc"},
    )
    assert plan.replicas == 3
    assert plan.volumes["v0"].segment_sizes == {4096: 1}
    assert plan.volumes["v0"].dials == 0
    assert plan.volumes["v1"].segment_sizes == {}
    assert plan.volumes["v1"].dials == 1  # below stripe threshold: main only
    assert plan.volumes["v2"].segment_sizes == {}
    assert plan.volumes["v2"].dials == 0
    assert plan.planned_bytes == 4096  # only the shm leg carries segments


def test_plan_bulk_stripe_dials_above_threshold():
    from torchstore_tpu.transport.bulk import STRIPE_CONNS, STRIPE_THRESHOLD

    m = StateDictManifest(
        entries=[
            provision.ManifestEntry(
                "big", (1,), "float32", (STRIPE_THRESHOLD + 1,)
            )
        ]
    )
    plan = plan_provisioning(m, ["v0"], {"v0": "bulk"})
    assert plan.volumes["v0"].dials == STRIPE_CONNS


def test_clamp_keeps_largest_segments_first():
    vp = VolumePlan(
        volume_id="v0",
        transport="shm",
        segment_sizes={100: 3, 1000: 2, 10: 5},
    )
    # Budget fits both 1000s and one 100: the big cold allocations win.
    clamp_to_grant(vp, 2150)
    assert vp.segment_sizes == {1000: 2, 100: 1, 10: 5}
    assert vp.clamped_bytes == 200
    assert vp.planned_bytes <= 2150


def test_clamp_zero_grant_drops_plan_and_none_is_ungoverned():
    vp = VolumePlan("v0", "shm", segment_sizes={64: 2})
    clamp_to_grant(vp, 0)
    assert vp.segment_sizes == {}
    assert vp.clamped_bytes == 128
    vp2 = VolumePlan("v0", "shm", segment_sizes={64: 2})
    clamp_to_grant(vp2, None)
    assert vp2.segment_sizes == {64: 2}
    assert vp2.clamped_bytes == 0


def test_clamp_ignores_non_shm_legs():
    vp = VolumePlan("v0", "bulk", dials=4)
    clamp_to_grant(vp, 0)
    assert vp.dials == 4


# ---------------------------------------------------------------------------
# fault injection: prewarm failure must never fail the sync
# ---------------------------------------------------------------------------


def _errors_total() -> float:
    metric = obs_metrics.counter(
        "ts_prewarm_errors_total", "Prewarm stage failures (lazy path proceeded)"
    )
    return metric.total()


async def test_prewarm_on_uninitialized_store_reports_not_raises():
    before = _errors_total()
    report = await ts.prewarm(
        {"w": np.zeros((8,), np.float32)}, store_name="no_such_store"
    )
    assert report["ok"] is False
    assert report["errors"]
    assert _errors_total() > before


async def test_prewarm_volume_executor_failure_degrades_to_lazy_path(
    monkeypatch,
):
    """A broken volume-side provisioner (colocated, so the monkeypatch
    reaches it) must leave prewarm ok=False with the stage named, count the
    error, and the subsequent put/get must work unchanged."""
    from torchstore_tpu.transport.shared_memory import ShmServerCache

    async def boom(self, sizes, hugepages=True, nthreads=0):
        raise RuntimeError("injected provision failure")

    await ts.initialize(store_name="pv_fault", colocated=True)
    try:
        monkeypatch.setattr(ShmServerCache, "provision", boom)
        sd = {"w": np.random.rand(65536).astype(np.float32)}  # 256 KB
        before = _errors_total()
        report = await ts.prewarm(sd, store_name="pv_fault")
        assert report["ok"] is False
        assert any(k.startswith("volume:") for k in report["errors"])
        assert _errors_total() > before
        # The lazy path proceeds untouched.
        await ts.put_state_dict("k/sd", sd, store_name="pv_fault")
        out = await ts.get_state_dict("k/sd", store_name="pv_fault")
        np.testing.assert_array_equal(out["w"], sd["w"])
    finally:
        await ts.shutdown("pv_fault")


async def test_prewarm_clamped_by_tiny_pool_then_sync_succeeds():
    """tmpfs-too-small analog: a pool cap far below the working set clamps
    the grant (segments mostly dropped, clamped bytes reported) and the
    sync still completes on the lazy path."""
    config = ts.StoreConfig(shm_pool_max_bytes=4096, prewarm_auto=False)
    await ts.initialize(store_name="pv_small", config=config)
    try:
        sd = {
            str(i): np.random.rand(65536).astype(np.float32) for i in range(4)
        }  # 4 x 256 KB >> 4 KB cap
        report = await ts.prewarm(sd, store_name="pv_small")
        assert report["segments"] == 0
        assert report["clamped_bytes"] >= 4 * 262144 - 4096
        await ts.put_state_dict("k/sd", sd, store_name="pv_small")
        out = await ts.get_state_dict("k/sd", store_name="pv_small")
        np.testing.assert_array_equal(out["0"], sd["0"])
    finally:
        await ts.shutdown("pv_small")


# ---------------------------------------------------------------------------
# tier-1 integration
# ---------------------------------------------------------------------------


async def _volume_created_total(store: str) -> float:
    stats = await ts.client(store).controller.stats.call_one(
        include_volumes=True
    )
    total = 0.0
    for vstats in stats["volumes"].values():
        metric = vstats["metrics"].get("ts_shm_segments_created_total")
        if metric:
            total += sum(s["value"] for s in metric["series"])
    return total


async def test_first_put_after_prewarm_creates_zero_segments():
    """THE acceptance invariant: after ts.prewarm of the working set, the
    first put draws every segment from the provisioned pool — the volume's
    segments-created counter does not move across the put, and the client's
    offers all hit."""
    await ts.initialize(
        store_name="pv_zero",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
        config=ts.StoreConfig(prewarm_auto=False),
    )
    try:
        sd = {
            "layers": {
                str(i): np.random.rand(65536).astype(np.float32)
                for i in range(3)
            }
        }  # 3 x 256 KB: above the inline-put ceiling, handshake path
        report = await ts.prewarm(sd, store_name="pv_zero")
        assert report["ok"] and not report["errors"], report
        # 256 KB sits AT the arena threshold: the three tensors pack into
        # ONE provisioned arena segment (steady-state pipeline), which is
        # exactly what the first put's handshake asks for.
        assert report["segments"] == 1
        assert report["bytes"] == 3 * 262144
        assert report.get("pre_attached") == 1
        created_before = await _volume_created_total("pv_zero")
        await ts.put_state_dict("m/sd", sd, store_name="pv_zero")
        created_after = await _volume_created_total("pv_zero")
        assert created_after == created_before, (
            "first put cold-created segments despite prewarm"
        )
        out = await ts.get_state_dict("m/sd", store_name="pv_zero")
        np.testing.assert_array_equal(out["layers"]["0"], sd["layers"]["0"])
    finally:
        await ts.shutdown("pv_zero")


async def test_prewarm_bulk_predials_promoted_connection():
    await ts.initialize(
        store_name="pv_bulk",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
        config=ts.StoreConfig(prewarm_auto=False),
    )
    try:
        from torchstore_tpu.transport.bulk import BulkClientCache

        sd = {"w": np.random.rand(65536).astype(np.float32)}
        report = await ts.prewarm(sd, store_name="pv_bulk")
        assert report["ok"] and not report["errors"], report
        assert report["dials"] == 1
        client = ts.client("pv_bulk")
        volume = next(iter(client._volume_refs.values()))
        cache = volume.transport_context.get_cache(BulkClientCache)
        assert cache.get_alive(volume.volume_id) is not None
        conn_before = cache.get_alive(volume.volume_id)
        await ts.put_state_dict("m/sd", sd, store_name="pv_bulk")
        # The put rode the PRE-DIALED promoted connection, not a fresh one.
        assert cache.get_alive(volume.volume_id) is conn_before
        out = await ts.get_state_dict("m/sd", store_name="pv_bulk")
        np.testing.assert_array_equal(out["w"], sd["w"])
    finally:
        await ts.shutdown("pv_bulk")


async def test_auto_prewarm_hint_fires_once_per_signature():
    runs = obs_metrics.counter(
        "ts_prewarm_runs_total", "Prewarm invocations (explicit + auto-hint)"
    )
    config = ts.StoreConfig(prewarm_auto=True, prewarm_auto_min_bytes=1024)
    await ts.initialize(store_name="pv_auto", config=config)
    try:
        sd = {"w": np.random.rand(65536).astype(np.float32)}
        before = runs.total()
        await ts.put_state_dict("m/sd", sd, store_name="pv_auto")
        assert runs.total() == before + 1  # hint fired ahead of the commit
        await ts.put_state_dict("m/sd", sd, store_name="pv_auto")
        assert runs.total() == before + 1  # same signature: once only
        tiny = {"w": np.zeros((4,), np.float32)}
        await ts.put_state_dict("tiny/sd", tiny, store_name="pv_auto")
        assert runs.total() == before + 1  # below min_bytes: no hint
    finally:
        await ts.shutdown("pv_auto")


async def test_prewarm_direct_acquire_precomputes_plan():
    hits = obs_metrics.counter(
        "ts_prewarm_plan_cache_hits_total",
        "Direct-sync pulls that hit a prewarm-built transfer plan",
    )
    await ts.initialize(
        store_name="pv_direct", config=ts.StoreConfig(prewarm_auto=False)
    )
    try:
        sd = {"w": np.random.rand(4096).astype(np.float32)}
        await ts.put_state_dict("d/sd", sd, direct=True, store_name="pv_direct")
        user = {"w": np.zeros(4096, np.float32)}
        report = await ts.prewarm(
            user, store_name="pv_direct", acquire_key="d/sd"
        )
        assert report["plan_ops"] == 1
        assert report["segments_attached"] == 1  # same-host shm staging
        before = hits.total()
        out = await ts.get_state_dict(
            "d/sd", user_state_dict=user, direct=True, store_name="pv_direct"
        )
        np.testing.assert_array_equal(out["w"], sd["w"])
        assert hits.total() == before + 1  # iteration 0 hit the preplan
    finally:
        await ts.shutdown("pv_direct")


async def test_prewarm_direct_source_draws_local_staging():
    from torchstore_tpu.provision.pool import local_pool

    await ts.initialize(
        store_name="pv_src", config=ts.StoreConfig(prewarm_auto=False)
    )
    try:
        sd = {"w": np.random.rand(65536).astype(np.float32)}
        report = await ts.prewarm(sd, store_name="pv_src", direct=True)
        assert report["local_segments"] == 1
        assert local_pool().pooled_bytes == 262144
        # register() (first direct publish) draws the provisioned segment.
        await ts.put_state_dict("d/sd", sd, direct=True, store_name="pv_src")
        assert local_pool().pooled_bytes == 0
        user = {"w": np.zeros(65536, np.float32)}
        out = await ts.get_state_dict(
            "d/sd", user_state_dict=user, direct=True, store_name="pv_src"
        )
        np.testing.assert_array_equal(out["w"], sd["w"])
    finally:
        await ts.shutdown("pv_src")


async def test_reservations_prevent_oversubscription():
    """Two concurrent reservations can't both get the full headroom; release
    returns the capacity."""
    await ts.initialize(
        store_name="pv_res", config=ts.StoreConfig(prewarm_auto=False)
    )
    try:
        client = ts.client("pv_res")
        await client._ensure_setup()
        vid = next(iter(client._volume_refs))
        cap = await client._volume_refs[vid].actor.shm_capacity.call_one()
        headroom = min(
            cap["available_bytes"], cap["pool_cap"] - cap["pool_bytes"]
        )
        ask = headroom  # first reservation takes everything
        r1 = await client.controller.reserve_prewarm.call_one("r1", {vid: ask})
        assert r1["grants"][vid] == ask
        r2 = await client.controller.reserve_prewarm.call_one("r2", {vid: ask})
        assert r2["grants"][vid] == 0  # fully reserved: nothing left
        await client.controller.release_prewarm.call_one("r1")
        r3 = await client.controller.reserve_prewarm.call_one("r3", {vid: ask})
        assert r3["grants"][vid] == ask  # release returned the capacity
        await client.controller.release_prewarm.call_one("r2")
        await client.controller.release_prewarm.call_one("r3")
    finally:
        await ts.shutdown("pv_res")


async def test_reservations_net_tmpfs_per_host():
    """Volumes co-located on one host share /dev/shm: grants across them
    must be netted against ONE host budget, not each volume's independent
    view of the same tmpfs."""
    from torchstore_tpu.transport.shared_memory import shm_available_bytes

    await ts.initialize(
        store_name="pv_host",
        num_storage_volumes=2,
        config=ts.StoreConfig(prewarm_auto=False),
    )
    try:
        client = ts.client("pv_host")
        await client._ensure_setup()
        vids = sorted(client._volume_refs)
        avail = shm_available_bytes()
        # Pool caps far above tmpfs so the HOST budget is the binding
        # constraint; each volume asks 80% of the tmpfs.
        big = ts.StoreConfig(
            shm_pool_max_bytes=avail * 4, prewarm_auto=False
        )
        ask = int(avail * 0.8)
        res = await client.controller.reserve_prewarm.call_one(
            "host1", {vids[0]: ask, vids[1]: ask}, config=big
        )
        grants = res["grants"]
        assert sum(grants.values()) <= avail, (grants, avail)
        assert grants[vids[1]] < ask  # second volume got the remainder only
        await client.controller.release_prewarm.call_one("host1")
    finally:
        await ts.shutdown("pv_host")


async def test_concurrent_reservations_cannot_overgrant():
    """Two reservations issued CONCURRENTLY (the endpoint suspends on the
    volumes' capacity RPCs) must not collectively grant more than the
    volume's headroom — the placeholder-before-await closes the
    read-compute-write race."""
    import asyncio

    await ts.initialize(
        store_name="pv_race", config=ts.StoreConfig(prewarm_auto=False)
    )
    try:
        client = ts.client("pv_race")
        await client._ensure_setup()
        vid = next(iter(client._volume_refs))
        cap = await client._volume_refs[vid].actor.shm_capacity.call_one()
        headroom = min(
            cap["available_bytes"], cap["pool_cap"] - cap["pool_bytes"]
        )
        r1, r2 = await asyncio.gather(
            client.controller.reserve_prewarm.call_one("c1", {vid: headroom}),
            client.controller.reserve_prewarm.call_one("c2", {vid: headroom}),
        )
        assert r1["grants"][vid] + r2["grants"][vid] <= headroom, (r1, r2)
        await client.controller.release_prewarm.call_one("c1")
        await client.controller.release_prewarm.call_one("c2")
    finally:
        await ts.shutdown("pv_race")


async def test_weight_publisher_register_prewarms_channel():
    await ts.initialize(
        store_name="pv_chan", config=ts.StoreConfig(prewarm_auto=False)
    )
    try:
        sd = {"w": np.random.rand(65536).astype(np.float32)}
        pub = ts.WeightPublisher("policy", store_name="pv_chan")
        report = await pub.register(sd)
        assert report["ok"] and report["segments"] == 1, report
        version = await pub.publish(sd)
        sub = ts.WeightSubscriber("policy", store_name="pv_chan")
        out, got = await sub.acquire(timeout=60.0)
        assert got == version
        np.testing.assert_array_equal(out["w"], sd["w"])
    finally:
        await ts.shutdown("pv_chan")
