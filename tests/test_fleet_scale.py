"""Fleet-scale load harness + stage-attribution scoreboard (ISSUE 15).

Covers the loadgen package (arrival patterns, churn schedules, the
multi-process driver, report merging), the stage-attribution layer
(``observe_stage`` / ``ts.slo_report()`` naming the dominant stage of a
violated SLO under an injected ``shm.landing_stamp`` delay), the new
overload-signal gauges, the flight-recorder dump rate limit, and the
chaos leg: a volume killed mid-loadgen-run with zero committed loss and
the kill visible in the scoreboard's violation counts.
"""

import asyncio
import json
import os
import random
import statistics
import time

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import faults
from torchstore_tpu.loadgen import (
    LoadSpec,
    churn_sessions,
    make_pattern,
    merge_driver_reports,
    merge_slo_reports,
    run_fleet_load,
)
from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline
from torchstore_tpu.strategy import LocalRankStrategy


@pytest.fixture
def fast_health(monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "2")


@pytest.fixture
def fresh_digests():
    """Isolate the rolling op/stage digests and the SLO violation counter
    from whatever earlier tests in this process observed."""
    obs_timeline.op_quantiles().reset()
    obs_timeline.stage_quantiles().reset()
    violations = obs_metrics.get_registry().get("ts_slo_violations_total")
    if violations is not None:
        violations.clear()
    yield
    obs_timeline.op_quantiles().reset()
    obs_timeline.stage_quantiles().reset()


# --------------------------------------------------------------------------
# arrival patterns + churn schedules (pure units)
# --------------------------------------------------------------------------


class TestArrivals:
    def test_poisson_mean_gap_matches_rate(self):
        pattern = make_pattern({"kind": "poisson", "rate_hz": 50.0})
        rng = random.Random(7)
        gaps = [pattern.next_gap(0.0, rng) for _ in range(4000)]
        assert statistics.mean(gaps) == pytest.approx(1 / 50.0, rel=0.1)

    def test_steady_is_a_metronome(self):
        pattern = make_pattern({"kind": "steady", "rate_hz": 10.0})
        rng = random.Random(1)
        assert pattern.next_gap(3.0, rng) == pytest.approx(0.1)

    def test_burst_rate_modulates_square_wave(self):
        pattern = make_pattern(
            {
                "kind": "burst",
                "rate_hz": 10.0,
                "peak_rate_hz": 100.0,
                "period_s": 1.0,
                "burst_frac": 0.25,
            }
        )
        assert pattern.rate_at(0.1) == 100.0  # inside the burst window
        assert pattern.rate_at(0.9) == 10.0  # baseline
        assert pattern.rate_at(1.2) == 100.0  # next period's burst

    def test_diurnal_stays_between_base_and_peak(self):
        pattern = make_pattern(
            {
                "kind": "diurnal",
                "rate_hz": 5.0,
                "peak_rate_hz": 50.0,
                "period_s": 4.0,
            }
        )
        rates = [pattern.rate_at(t / 10) for t in range(80)]
        assert min(rates) >= 5.0 - 1e-9 and max(rates) <= 50.0 + 1e-9
        assert max(rates) > 40 and min(rates) < 15  # actually swings

    def test_unknown_pattern_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival pattern"):
            make_pattern("lunar")

    def test_determinism_per_seed(self):
        pattern = make_pattern("poisson")
        a = [pattern.next_gap(0.0, random.Random(42)) for _ in range(1)]
        b = [pattern.next_gap(0.0, random.Random(42)) for _ in range(1)]
        assert a == b

    def test_churn_sessions_cover_run_without_overlap(self):
        rng = random.Random(3)
        sessions = churn_sessions(30.0, churn_rate_hz=0.5, rng=rng)
        assert sessions, "churn produced no sessions"
        prev_leave = -1.0
        for join_t, leave_t in sessions:
            assert 0.0 <= join_t < leave_t <= 30.0
            assert join_t > prev_leave  # ordered, non-overlapping
            prev_leave = leave_t
        assert len(sessions) >= 2, "0.5 Hz churn over 30 s should cycle"

    def test_no_churn_is_one_full_session(self):
        assert churn_sessions(5.0, 0.0, random.Random(0)) == [(0.0, 5.0)]


# --------------------------------------------------------------------------
# stage digests + scoreboard (process-local units)
# --------------------------------------------------------------------------


class TestStageAttribution:
    def test_unregistered_stage_raises(self):
        with pytest.raises(ValueError, match="unregistered stage"):
            obs_timeline.observe_stage("get", "warp_drive", 0.01)

    def test_dominant_stage_tracks_largest_total(self):
        digests = obs_timeline.StageQuantiles()
        for _ in range(20):
            digests.observe("get", "transport", 0.001)
            digests.observe("get", "landing", 0.010)
        assert digests.dominant("get") == "landing"
        rows = digests.breakdown("get")
        assert rows["landing"]["share"] > 0.8
        assert rows["transport"]["samples"] == 20

    def test_stage_totals_sum_true_wall_time_across_ring_wraps(self):
        """Regression (review finding): totals must decay in WALL TIME,
        never per-stage sample count — a count-triggered halving
        normalizes the sample rate away and votes by mean segment
        duration instead of aggregate wall time. Over a sub-second run
        the decayed totals must equal the true sums even though the
        sample ring wrapped multiple times."""
        digests = obs_timeline.StageQuantiles()
        for _ in range(2000):  # ~4x the 512 ring: the old code halved 3x
            digests.observe("get", "transport", 0.001)  # 2.0 s aggregate
        for _ in range(100):
            digests.observe("get", "landing", 0.005)  # 0.5 s aggregate
        rows = digests.breakdown("get")
        assert rows["transport"]["total_s"] == pytest.approx(2.0, rel=0.05)
        assert rows["landing"]["total_s"] == pytest.approx(0.5, rel=0.05)
        assert digests.dominant("get") == "transport"

    def test_slo_report_reads_thresholds_and_current(
        self, monkeypatch, fresh_digests
    ):
        monkeypatch.setenv("TORCHSTORE_TPU_SLO_PUT_P99_MS", "1.0")
        monkeypatch.setenv("TORCHSTORE_TPU_SLO_CUSTOM_BAR", "7")
        for _ in range(4):
            obs_timeline.observe_op("put", 0.005)  # 5 ms > 1 ms SLO
            obs_timeline.observe_stage("put", "notify", 0.004)
        report = obs_timeline.slo_report()
        row = report["slos"]["put_p99_ms"]
        assert row["threshold"] == 1.0
        assert row["current"] > 1.0 and row["violated"]
        assert row["violations"] >= 1
        assert row["dominant_stage"] == "notify"
        # Operator-extension knobs under the prefix appear on the board.
        assert report["slos"]["custom_bar"]["threshold"] == 7.0
        json.dumps(report)


# --------------------------------------------------------------------------
# report merging (pure units)
# --------------------------------------------------------------------------


class TestReportMerge:
    def test_merge_concatenates_samples_and_uses_max_window(self):
        reports = [
            {
                "counts": {"get": 3},
                "errors": {},
                "samples": {"get": [0.001, 0.002, 0.003]},
                "window_s": 2.0,
                "slo": None,
            },
            {
                "counts": {"get": 1, "put": 2},
                "errors": {"put": 1},
                "samples": {"get": [0.100], "put": [0.004, 0.005]},
                "window_s": 1.0,
                "slo": None,
            },
        ]
        merged = merge_driver_reports(reports)
        assert merged["ops"] == 6 and merged["errors"] == 1
        assert merged["ops_per_s"] == pytest.approx(3.0)
        # p99 over the CONCATENATED samples sees driver 2's 100 ms tail.
        assert merged["by_op"]["get"]["p99_ms"] == pytest.approx(100.0)
        assert merged["by_op"]["put"]["errors"] == 1

    def test_slo_merge_recomputes_dominant_from_summed_stage_time(self):
        def board(landing_s, transport_s, violations):
            return {
                "slos": {
                    "get_p99_ms": {
                        "env": "TORCHSTORE_TPU_SLO_GET_P99_MS",
                        "threshold": 5.0,
                        "worse": "above",
                        "op": "get",
                        "current": 6.0,
                        "violations": violations,
                        "violated": violations > 0,
                    }
                },
                "stages": {
                    "get": {
                        "landing": {
                            "samples": 10,
                            "total_s": landing_s,
                            "p99_s": 0.02,
                        },
                        "transport": {
                            "samples": 10,
                            "total_s": transport_s,
                            "p99_s": 0.01,
                        },
                    }
                },
            }

        # One driver (mis)votes transport; the fleet's summed wall time
        # still lands on landing.
        merged = merge_slo_reports(
            [board(0.9, 0.1, 2), board(0.2, 0.3, 1)]
        )
        row = merged["slos"]["get_p99_ms"]
        assert row["violations"] == 3 and row["violated"]
        assert row["dominant_stage"] == "landing"
        assert merged["stages"]["get"]["landing"]["total_s"] == pytest.approx(
            1.1
        )


# --------------------------------------------------------------------------
# flight-recorder dump rate limit (satellite)
# --------------------------------------------------------------------------


class TestFlightDumpRateLimit:
    def test_one_dump_per_kind_per_interval(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S", "60")
        dropped = obs_metrics.get_registry().get(
            "ts_flight_dumps_dropped_total"
        )
        rec = obs_recorder.FlightRecorder(maxlen=16)
        rec.record("fault", "volume.put", action="die")
        assert rec.dump("storm:1") is not None
        before = dropped.value(reason="storm")
        # Same kind inside the interval: suppressed + counted.
        assert rec.dump("storm:2") is None
        assert dropped.value(reason="storm") == before + 1
        # A DIFFERENT kind is never shadowed by the storm.
        assert rec.dump("quarantine:v1") is not None

    def test_interval_zero_disables_and_reinit_clears(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S", "0")
        rec = obs_recorder.FlightRecorder(maxlen=16)
        rec.record("error", "x")
        assert rec.dump("storm:a") is not None
        assert rec.dump("storm:b") is not None  # limit disabled
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S", "60")
        assert rec.dump("storm:c") is None
        rec._last_dump["storm"] = time.monotonic() - 120
        assert rec.dump("storm:d") is not None  # interval elapsed


# --------------------------------------------------------------------------
# fleet: injected landing delay -> scoreboard names the landing stage
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_slo_report_names_landing_dominant_under_injected_fault(
    monkeypatch, fresh_digests
):
    """ISSUE-15 acceptance: a ``shm.landing_stamp`` delay (held inside the
    one-sided landing-copy window) must blow the GET p99 SLO with the
    LANDING stage dominant in ``ts.slo_report()`` — stage attribution, not
    just an end-to-end timer."""
    await ts.initialize(
        store_name="slo_fault",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        items = {
            f"sf/{i}": np.random.rand(1024).astype(np.float32)
            for i in range(8)
        }
        await ts.put_batch(items, store_name="slo_fault")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        # Record the one-sided plans BEFORE arming (recording gets ride
        # the RPC path, which the faultpoint does not cover).
        await ts.get_batch(dict(dests), store_name="slo_fault")
        obs_timeline.op_quantiles().reset()
        obs_timeline.stage_quantiles().reset()
        monkeypatch.setenv("TORCHSTORE_TPU_SLO_GET_P99_MS", "5")
        faults.arm("shm.landing_stamp", "delay", delay_ms=15)
        try:
            for _ in range(6):
                await ts.get_batch(dict(dests), store_name="slo_fault")
        finally:
            faults.disarm("shm.landing_stamp")
        report = await ts.slo_report(store_name="slo_fault")
        row = report["slos"]["get_p99_ms"]
        assert row["violations"] >= 1, report["slos"]
        assert row["dominant_stage"] == "landing", row
        assert row["stages"]["landing"]["share"] > 0.5, row["stages"]
        # Overload signals ride the same report, per volume.
        vols = report["overload"]["volumes"]
        assert vols, report["overload"]
        for signals in vols.values():
            assert signals["landing_inflight"] == 0  # settled fleet
            assert "doorbell_plans" in signals
            assert signals["window_ops"] >= 0
        json.dumps(report)
    finally:
        await ts.shutdown("slo_fault")


@pytest.mark.anyio
async def test_landing_inflight_gauge_settles_to_zero():
    """Satellite: the volume publishes ``ts_landing_inflight`` from its
    landing bracket — present after traffic and settled back to 0."""
    await ts.initialize(
        store_name="gauge_t",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        await ts.put(
            "g/x", np.random.rand(512).astype(np.float32),
            store_name="gauge_t",
        )
        client = ts.client("gauge_t")
        vid = next(iter(client._volume_refs))
        stats = await client._volume_refs[vid].actor.stats.call_one()
        series = stats["metrics"]["ts_landing_inflight"]["series"]
        assert series and all(s["value"] == 0 for s in series), series
        assert stats["overload"]["landing_inflight"] == 0
        # The volume's own stage digests rode stats() too.
        assert "landing" in (stats["stages"].get("put") or {}), stats[
            "stages"
        ]
    finally:
        await ts.shutdown("gauge_t")


# --------------------------------------------------------------------------
# loadgen: multi-process run + chaos kill mid-run
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_loadgen_run_mixed_ops_and_scoreboard():
    """A small but real loadgen run: 2 driver processes x 4 logical
    clients with a bursty get/put/stream mix, slow readers, and churn.
    The merged report carries every op kind, zero errors, and the
    configured SLO on the scoreboard."""
    await ts.initialize(num_storage_volumes=2, store_name="lg_run")
    try:
        # Seed + seal a streamed publish for the "stream" op.
        stream = ts.state_dict_stream("lg_run/sd", store_name="lg_run")
        await stream.put(
            {"w": {str(i): np.random.rand(256).astype(np.float32)
                   for i in range(3)}}
        )
        await stream.seal()
        spec = LoadSpec(
            store_name="lg_run",
            duration_s=1.5,
            processes=2,
            clients_per_process=4,
            pattern={
                "kind": "burst",
                "rate_hz": 10.0,
                "peak_rate_hz": 40.0,
                "period_s": 0.5,
                "burst_frac": 0.3,
            },
            mix={"get": 0.6, "put": 0.2, "stream": 0.2},
            stream_key="lg_run/sd",
            shared_keys=8,
            value_kb=2.0,
            slow_reader_frac=0.25,
            slow_reader_ms=2.0,
            churn_rate_hz=1.0,
            seed=11,
            env={"TORCHSTORE_TPU_SLO_GET_P99_MS": "10000"},
        )
        merged = await run_fleet_load(spec)
        assert merged["failed_drivers"] == 0, merged.get("driver_errors")
        assert merged["errors"] == 0, merged["by_op"]
        assert merged["ops"] > 0 and merged["ops_per_s"] > 0
        assert merged["logical_clients"] == 8
        for op in ("get", "put", "stream"):
            assert merged["by_op"].get(op, {}).get("count", 0) > 0, (
                merged["by_op"]
            )
            assert merged["by_op"][op]["p99_ms"] is not None
        board = merged["slo"]["slos"]
        assert "get_p99_ms" in board and not board["get_p99_ms"]["violated"]
        json.dumps(merged)
    finally:
        await ts.shutdown("lg_run")


async def _kill_volume(store_name: str, volume_id: str) -> None:
    from torchstore_tpu import api

    client = ts.client(store_name)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap[volume_id]["ref"]
    handle = api._stores[store_name]
    for mesh in [handle.volume_mesh, *(handle.repair_meshes or [])]:
        if mesh is None:
            continue
        for idx, ref in enumerate(mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = mesh._processes[idx]
                proc.kill()
                proc.join(5)
                return
    raise AssertionError(f"no process found for volume {volume_id!r}")


@pytest.mark.anyio
async def test_loadgen_chaos_kill_zero_loss_and_scoreboard_violations(
    fast_health,
):
    """ISSUE-15 chaos leg: kill one volume mid-loadgen-run (replicated
    fleet, churning clients). The run must finish with zero failed
    drivers and zero client-visible op errors (failover owns the
    transients), every committed shared key must still be readable with
    its exact seeded bytes (zero committed-generation loss), and the kill
    must be visible in the merged scoreboard's violation counts (the
    failover latency spike breaches the GET p99 SLO)."""
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="lg_chaos",
    )
    try:
        spec = LoadSpec(
            store_name="lg_chaos",
            duration_s=4.0,
            processes=2,
            clients_per_process=6,
            pattern="poisson",
            rate_hz=15.0,
            mix={"get": 0.8, "put": 0.2},
            shared_keys=12,
            value_kb=2.0,
            churn_rate_hz=0.5,
            seed=23,
            # One-sided reads are kill-RESILIENT (stamped reads serve from
            # the dead volume's still-mapped segments, so warm gets never
            # even notice — a deliberate property). This chaos leg is
            # about the RPC plane's failover, so drivers run with the
            # one-sided path off: gets that hit the dead volume pay the
            # retry/failover spike the SLO then catches.
            env={"TORCHSTORE_TPU_SLO_GET_P99_MS": "40"},
            config_overrides={"one_sided": False},
        )
        load = asyncio.ensure_future(run_fleet_load(spec))
        client = ts.client("lg_chaos")
        await client._ensure_setup()
        # Kill only once every driver's measured window is OPEN (the
        # ready markers): driver boot costs seconds of import — a
        # wall-clock sleep would kill before any measured op, and the
        # supervisor would route everything around the corpse before a
        # single get could spike.
        deadline = time.monotonic() + 30
        for d in range(spec.processes):
            while not await ts.exists(
                f"lg_chaos/ctl/ready/{d}", store_name="lg_chaos"
            ):
                assert time.monotonic() < deadline, (
                    f"driver {d} never opened its window"
                )
                await asyncio.sleep(0.1)
        await asyncio.sleep(0.5)  # well inside every window
        located = await client.controller.locate_volumes.call_one(
            ["lg_chaos/shared/0"]
        )
        victim = sorted(located["lg_chaos/shared/0"])[0]
        await _kill_volume("lg_chaos", victim)
        merged = await load
        assert merged["failed_drivers"] == 0, merged.get("driver_errors")
        assert merged["errors"] == 0, merged["by_op"]
        # Kill visible on the scoreboard: failover spikes breached the SLO.
        row = merged["slo"]["slos"].get("get_p99_ms") or {}
        assert row.get("violations", 0) > 0, merged["slo"]
        # Zero committed-generation loss: every seeded shared key still
        # serves its exact bytes (replication + supervisor failover).
        n_elem = max(1, int(spec.value_kb * 1024 // 4))
        seed_rng = np.random.default_rng(spec.seed)
        expect = {
            f"lg_chaos/shared/{i}": seed_rng.standard_normal(
                n_elem, dtype=np.float32
            )
            for i in range(spec.shared_keys)
        }
        got = await ts.get_batch(list(expect), store_name="lg_chaos")
        for key, want in expect.items():
            np.testing.assert_array_equal(got[key], want)
        # The dead volume surfaces in the fleet overload scrape.
        report = await ts.slo_report(store_name="lg_chaos")
        assert victim in report["overload"]["errors"] or (
            victim not in report["overload"]["volumes"]
        ), report["overload"]
    finally:
        await ts.shutdown("lg_chaos")


# --------------------------------------------------------------------------
# diurnal shape reconstruction from ts.history() alone (ISSUE 17)
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_diurnal_arrival_shape_reconstructable_from_history():
    """ISSUE 17 acceptance: a diurnal loadgen run's arrival shape is
    reconstructable from the history rings alone — no per-op samples, just
    the merged ``history.ops_per_s`` artifact. A least-squares sinusoid
    fit at the spec'd period recovers a peak/trough ops/s ratio within
    25% of the spec'd ``peak_rate_hz / rate_hz`` ratio."""
    period_s = 8.0
    base_hz, peak_hz = 12.0, 48.0  # per client; spec ratio 4.0
    await ts.initialize(num_storage_volumes=2, store_name="lg_diurnal")
    try:
        spec = LoadSpec(
            store_name="lg_diurnal",
            # 1.5 periods: a full period survives in the interior even
            # when a loaded machine delays the driver's first buckets.
            duration_s=12.0,
            processes=1,  # one driver: one arrival-process phase to fit
            clients_per_process=6,
            pattern={
                "kind": "diurnal",
                "rate_hz": base_hz,
                "peak_rate_hz": peak_hz,
                "period_s": period_s,
            },
            mix={"get": 0.7, "put": 0.3},
            shared_keys=8,
            value_kb=1.0,
            seed=23,
            # Tight sampler cadence: bucket closing values land within
            # 0.1s of the bucket boundary, so per-bucket counter diffs
            # track the true 1s arrival counts.
            env={"TORCHSTORE_TPU_HISTORY_INTERVAL_S": "0.1"},
        )
        merged = await run_fleet_load(spec)
        assert merged["failed_drivers"] == 0, merged.get("driver_errors")
        assert merged["errors"] == 0, merged["by_op"]
        hist = merged.get("history") or {}
        assert hist.get("step_s") == 1.0, hist.keys()
        assert hist.get("get_p99_ms"), "p99 gauge series missing"
        rows = hist["ops_per_s"]
        # Drop the ramp-up/teardown edge buckets; the interior must still
        # cover at least one full period.
        interior = rows[1:-1]
        assert len(interior) >= period_s, rows
        t = np.array([r[0] for r in interior], dtype=np.float64)
        y = np.array([r[1] for r in interior], dtype=np.float64)
        # Unknown phase (wall-clock bucket grid vs run start): fit
        # mean + a*sin + b*cos at the KNOWN period, amplitude = |(a, b)|.
        w = 2.0 * np.pi / period_s
        design = np.stack(
            [np.ones_like(t), np.sin(w * t), np.cos(w * t)], axis=1
        )
        (mean, a, b), *_ = np.linalg.lstsq(design, y, rcond=None)
        amp = float(np.hypot(a, b))
        assert mean > 0 and amp > 0 and amp < mean, (mean, amp)
        measured_ratio = (mean + amp) / (mean - amp)
        spec_ratio = peak_hz / base_hz
        assert spec_ratio * 0.75 <= measured_ratio <= spec_ratio * 1.25, (
            f"reconstructed peak/trough {measured_ratio:.2f} vs spec "
            f"{spec_ratio:.1f}: interior={interior}"
        )
    finally:
        await ts.shutdown("lg_diurnal")
