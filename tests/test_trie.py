import pytest

from torchstore_tpu.storage_utils.trie import Trie


def test_basic_mapping():
    t = Trie()
    t["a/b/c"] = 1
    t["a/b"] = 2
    t["x"] = 3
    assert t["a/b/c"] == 1
    assert t["a/b"] == 2
    assert len(t) == 3
    assert "a/b" in t
    assert "a" not in t  # interior node, no value
    del t["a/b"]
    assert "a/b" not in t
    assert t["a/b/c"] == 1
    assert len(t) == 2


def test_missing_key():
    t = Trie()
    with pytest.raises(KeyError):
        t["nope"]
    with pytest.raises(KeyError):
        del t["nope"]


def test_overwrite():
    t = Trie()
    t["k"] = 1
    t["k"] = 2
    assert t["k"] == 2 and len(t) == 1


def test_prefix_listing():
    t = Trie()
    for k in ["sd/v0/layer1", "sd/v0/layer2", "sd/v1/layer1", "other"]:
        t[k] = True
    assert sorted(t.keys().filter_by_prefix("sd/v0")) == [
        "sd/v0/layer1",
        "sd/v0/layer2",
    ]
    assert sorted(t.keys().filter_by_prefix("sd")) == [
        "sd/v0/layer1",
        "sd/v0/layer2",
        "sd/v1/layer1",
    ]
    assert list(t.keys().filter_by_prefix("nothing")) == []
    assert sorted(t.keys()) == sorted(
        ["sd/v0/layer1", "sd/v0/layer2", "sd/v1/layer1", "other"]
    )


def test_prefix_is_segment_wise():
    t = Trie()
    t["ab/c"] = 1
    t["abc/d"] = 2
    # "ab" matches only the segment path ab/..., not abc/...
    assert list(t.keys().filter_by_prefix("ab")) == ["ab/c"]


def test_exact_key_in_prefix_listing():
    t = Trie()
    t["a"] = 1
    t["a/b"] = 2
    assert sorted(t.keys().filter_by_prefix("a")) == ["a", "a/b"]


def test_pruning_keeps_siblings():
    t = Trie()
    t["a/b/c"] = 1
    t["a/b/d"] = 2
    del t["a/b/c"]
    assert list(t.keys()) == ["a/b/d"]
