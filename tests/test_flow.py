"""Unit tests for the CFG/dataflow engine (torchstore_tpu/analysis/flow.py),
independent of any checker.

The flow-aware rules are only as sound as the graph underneath them, so the
lowering cases that historically hide bugs are pinned here directly:
try/finally with a raise inside the handler, nested brackets, loop-carried
opens, ``return`` inside ``with``, and the exception edge every ``await``
must carry (CancelledError surfaces at each one)."""

import ast
import pathlib
import sys
import textwrap

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from torchstore_tpu.analysis.flow import (  # noqa: E402
    build_cfg,
    dominated_by,
    escaping_opens,
    iter_cfgs,
    nodes_between,
    post_dominated_by,
)


def _cfg(src, name=None):
    tree = ast.parse(textwrap.dedent(src))
    for cfg in iter_cfgs(tree):
        if name is None or cfg.name == name:
            return cfg
    raise AssertionError(f"no function {name!r} in source")


def _tail(call):
    f = call.func
    return getattr(f, "attr", getattr(f, "id", None))


def _calls(node, name):
    return node.stmt is not None and any(_tail(c) == name for c in node.calls)


def _node_calling(cfg, name):
    for n in cfg.stmt_nodes():
        if _calls(n, name):
            return n
    raise AssertionError(f"no node calling {name!r}")


def _escapes(cfg, opn="open_b", close="close_b", **kw):
    pairs = escaping_opens(
        cfg, lambda n: _calls(n, opn), lambda n: _calls(n, close), **kw
    )
    return sorted({(n.lineno, why) for n, why in pairs})


# --------------------------------------------------------------------------
# Graph shape
# --------------------------------------------------------------------------


def test_straight_line_has_exception_edges_everywhere():
    cfg = _cfg(
        """
        def f():
            a = setup()
            b = a.compute()
            return b
        """
    )
    stmts = [n for n in cfg.stmt_nodes() if n.stmt is not None]
    assert len(stmts) == 3
    # Even plain assignments can raise: every real statement carries an
    # exception edge to the synthetic raise exit.
    assert all(cfg.raise_id in n.exc for n in stmts)


def test_loop_has_back_edge():
    cfg = _cfg(
        """
        def f(items):
            for it in items:
                work(it)
            done()
        """
    )
    head = next(n for n in cfg.stmt_nodes() if n.label == "for")
    body = _node_calling(cfg, "work")
    assert head.id in body.succ  # back-edge
    assert body.id in head.succ


def test_await_nodes_are_annotated_and_raise():
    cfg = _cfg(
        """
        async def f(x):
            y = await fetch(x)
            z = plain(y)
            return z
        """
    )
    fetch = _node_calling(cfg, "fetch")
    plain = _node_calling(cfg, "plain")
    assert fetch.has_await and not plain.has_await
    # CancelledError can surface at the await: exception edge mandatory.
    assert cfg.raise_id in fetch.exc


def test_async_for_and_async_with_headers_count_as_awaits():
    cfg = _cfg(
        """
        async def f(src, lock):
            async with lock:
                async for item in src:
                    use(item)
        """
    )
    labels = {n.label: n for n in cfg.stmt_nodes() if n.stmt is not None}
    assert labels["with"].has_await
    assert labels["for"].has_await


def test_nested_def_bodies_are_opaque():
    cfg = _cfg(
        """
        def outer():
            open_b()
            def inner():
                close_b()
            return inner
        """,
        name="outer",
    )
    # inner's close_b is not visible in outer's CFG...
    assert not any(_calls(n, "close_b") for n in cfg.stmt_nodes())
    # ...so the open escapes on both exits.
    assert _escapes(cfg) == [(3, "raise"), (3, "return")]


# --------------------------------------------------------------------------
# Bracket escapes (the PR 7 shape and friends)
# --------------------------------------------------------------------------


def test_bare_open_escapes_on_raise_but_finally_covers():
    bare = _cfg(
        """
        def f():
            open_b()
            work()
            close_b()
        """
    )
    assert _escapes(bare) == [(3, "raise")]

    covered = _cfg(
        """
        def f():
            open_b()
            try:
                work()
            finally:
                close_b()
        """
    )
    assert _escapes(covered) == []


def test_try_finally_with_raise_in_handler_still_closes():
    # A handler that re-raises a DIFFERENT exception still traverses the
    # finally on its way out — the close must be seen on that path.
    cfg = _cfg(
        """
        def f():
            open_b()
            try:
                work()
            except ValueError:
                note()
                raise RuntimeError("wrapped")
            finally:
                close_b()
        """
    )
    assert _escapes(cfg) == []


def test_raise_in_handler_without_finally_escapes():
    cfg = _cfg(
        """
        def f():
            open_b()
            try:
                work()
                close_b()
            except ValueError:
                raise RuntimeError("wrapped")
        """
    )
    # The handler path exits with the bracket open; so does a non-ValueError
    # raise out of work().
    assert (3, "raise") in _escapes(cfg)


def test_except_without_catch_all_keeps_escape_edge():
    caught = _cfg(
        """
        def f():
            open_b()
            try:
                work()
            except BaseException:
                close_b()
                raise
            close_b()
        """
    )
    assert _escapes(caught) == []

    narrow = _cfg(
        """
        def f():
            open_b()
            try:
                work()
            except ValueError:
                close_b()
                raise
            close_b()
        """
    )
    # A TypeError out of work() matches no handler and escapes open.
    assert _escapes(narrow) == [(3, "raise")]


def test_nested_brackets_inner_escape_only():
    cfg = _cfg(
        """
        def f():
            open_a()
            try:
                open_b()
                work()
                close_b()
            finally:
                close_a()
        """
    )
    # Outer bracket is finally-covered; inner one leaks if work() raises.
    assert _escapes(cfg, "open_a", "close_a") == []
    assert _escapes(cfg, "open_b", "close_b") == [(5, "raise")]


def test_loop_carried_open_escapes_only_on_raise():
    cfg = _cfg(
        """
        def f(items):
            for it in items:
                open_b(it)
                work(it)
                close_b(it)
        """
    )
    # Every normal iteration closes before the back-edge; only a raise out
    # of work() leaves the bracket open.
    assert _escapes(cfg) == [(4, "raise")]


def test_open_closed_on_break_path_vs_not():
    leaky = _cfg(
        """
        def f(items):
            for it in items:
                open_b(it)
                if stop(it):
                    break
                close_b(it)
            done()
        """
    )
    assert (4, "return") in _escapes(leaky)

    clean = _cfg(
        """
        def f(items):
            for it in items:
                open_b(it)
                try:
                    if stop(it):
                        break
                finally:
                    close_b(it)
            done()
        """
    )
    # break traverses the finally copy: closed on the way out of the loop.
    assert _escapes(clean) == []


def test_return_inside_with_escapes_open():
    cfg = _cfg(
        """
        def f():
            open_b()
            with ctx():
                if fast:
                    return early()
            close_b()
        """
    )
    esc = _escapes(cfg)
    assert (3, "return") in esc  # the early return skips the close
    assert (3, "raise") in esc  # and ctx()/early() can raise

    covered = _cfg(
        """
        def f():
            open_b()
            try:
                with ctx():
                    if fast:
                        return early()
            finally:
                close_b()
        """
    )
    # return-through-finally: the close runs before the function exits.
    assert _escapes(covered) == []


def test_escape_normal_ok_licenses_return_not_raise():
    cfg = _cfg(
        """
        async def f():
            open_b()
            await hook()
        """
    )
    assert _escapes(cfg, escape_normal_ok=True) == [(3, "raise")]
    fixed = _cfg(
        """
        async def f():
            open_b()
            try:
                await hook()
            except BaseException:
                close_b()
                raise
        """
    )
    assert _escapes(fixed, escape_normal_ok=True) == []


def test_open_own_exception_edge_is_not_an_escape():
    # If the open call itself raises, the bracket never opened.
    cfg = _cfg(
        """
        def f():
            open_b()
            close_b()
        """
    )
    assert _escapes(cfg) == []


# --------------------------------------------------------------------------
# nodes_between / dominance
# --------------------------------------------------------------------------


def test_nodes_between_sees_awaits_on_exception_paths_too():
    cfg = _cfg(
        """
        async def f():
            open_b()
            try:
                quick()
            except ValueError:
                await slow_recover()
            close_b()
        """
    )
    opn = _node_calling(cfg, "open_b")
    mids = nodes_between(cfg, opn, lambda n: _calls(n, "close_b"))
    assert any(n.has_await for n in mids)  # the handler await is inside


def test_post_dominated_by_over_normal_edges():
    cfg = _cfg(
        """
        def f(self):
            mutate()
            if bad:
                raise ValueError("aborted")
            bump()
        """
    )
    mut = _node_calling(cfg, "mutate")
    # The raise path terminates without reaching the exit: vacuously fine.
    assert post_dominated_by(cfg, mut, lambda n: _calls(n, "bump"))

    leaky = _cfg(
        """
        def f(self):
            mutate()
            if some:
                bump()
        """
    )
    mut2 = _node_calling(leaky, "mutate")
    assert not post_dominated_by(leaky, mut2, lambda n: _calls(n, "bump"))


def test_dominated_by_requires_fact_on_every_path_in():
    cfg = _cfg(
        """
        def f(self):
            audit()
            act()
        """
    )
    act = _node_calling(cfg, "act")
    assert dominated_by(cfg, act, lambda n: _calls(n, "audit"))

    branchy = _cfg(
        """
        def f(self):
            if loud:
                audit()
            act()
        """
    )
    act2 = _node_calling(branchy, "act")
    assert not dominated_by(branchy, act2, lambda n: _calls(n, "audit"))


def test_build_cfg_smoke_over_live_tree():
    # Every function in the shipped package must lower without error (the
    # checkers iterate all of them on every run).
    count = 0
    for path in (REPO_ROOT / "torchstore_tpu").rglob("*.py"):
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cfg = build_cfg(node)
                assert cfg.entry.succ, f"{path}:{node.name} has no entry edge"
                count += 1
    assert count > 500, count
