"""Elastic fleet autoscaling (torchstore_tpu/autoscale/, ISSUE 18).

Two layers, mirroring tests/test_control_plane.py:

- **Solver**: a pure function over a frozen ``TelemetrySnapshot`` plus the
  engine-side ``FleetView`` — every scaling behavior is pinned over
  hand-built inputs with no fleet and no clock: saturation/overload/mean-
  window scale-out, the idle-rounds drain entry, drain continuation →
  retire, the size envelope, and every anti-flap rule (cooldown, reversal
  damping, one-drain-at-a-time, max_actions).
- **Fleet**: ``ts.autoscale_plan()`` / ``ts.autoscale()`` end to end on a
  real store — scale-out actually spawns + attaches a volume, the idle
  fleet drains it back through graceful key migration, the retired
  process is stopped, and every committed key survives the round trip.

The chaos legs (volume killed mid-drain, kill-all → cold restore) live in
tests/test_chaos.py; the blob tier's own unit tests in
tests/test_blob_tier.py.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.control.snapshot import TelemetrySnapshot, VolumeLoad
from torchstore_tpu.control.solver import ActionRecord
from torchstore_tpu.autoscale.solver import (
    BLOB_DEMOTE,
    DRAIN,
    RETIRE,
    SCALE_OUT,
    AutoscalePolicy,
    FleetView,
    solve,
)

NOW = 1000.0

# KB-scale thresholds so fixtures stay readable.
POLICY = AutoscalePolicy(
    min_volumes=1,
    max_volumes=4,
    out_inflight=8,
    out_window_bytes=10_000,
    idle_window_bytes=1_000,
    idle_rounds=3,
    cooldown_s=60.0,
)


def _vol(vid, window=0, stored=0, entries=0, inflight=0):
    return VolumeLoad(
        volume_id=vid,
        host="h",
        entries=entries,
        stored_bytes=stored,
        window_bytes=window,
        landing_inflight=inflight,
    )


def _snap(volumes, sustained=None):
    return TelemetrySnapshot(
        generated_ts=NOW,
        volumes={v.volume_id: v for v in volumes},
        sustained_overload=sustained or {},
    )


def _kinds(actions):
    return [a.kind for a in actions]


# ---------------------------------------------------------------------------
# solver: scale-out triggers
# ---------------------------------------------------------------------------


class TestScaleOut:
    def test_saturated_landing_brackets(self):
        snap = _snap([_vol("v0", inflight=9), _vol("v1")])
        actions = solve(snap, FleetView(max_volumes=4), POLICY)
        assert _kinds(actions) == [SCALE_OUT]
        assert actions[0].subject == "fleet" and actions[0].count == 1
        assert "saturated" in actions[0].reason

    def test_fleet_mean_window(self):
        snap = _snap([_vol("v0", window=15_000), _vol("v1", window=9_000)])
        actions = solve(snap, FleetView(max_volumes=4), POLICY)
        assert _kinds(actions) == [SCALE_OUT]
        assert "fleet-mean window" in actions[0].reason

    def test_sustained_overload_trend(self):
        """The PR 17 history detectors' sustained fold votes for scale-out
        even when the point-in-time snapshot looks calm."""
        snap = _snap(
            [_vol("v0", window=100), _vol("v1")],
            sustained={"v0": {"landing_inflight": {"kind": "sustained"}}},
        )
        actions = solve(snap, FleetView(max_volumes=4), POLICY)
        assert _kinds(actions) == [SCALE_OUT]
        assert "sustained overload trend" in actions[0].reason

    def test_quiet_fleet_plans_nothing(self):
        snap = _snap([_vol("v0", window=500), _vol("v1", window=500)])
        assert solve(snap, FleetView(max_volumes=4), POLICY) == []

    def test_max_volumes_ceiling(self):
        snap = _snap([_vol(f"v{i}", inflight=9) for i in range(4)])
        assert solve(snap, FleetView(max_volumes=4), POLICY) == []

    def test_cooldown_suppresses_repeat(self):
        snap = _snap([_vol("v0", inflight=9)])
        hist = [ActionRecord(ts=NOW - 10, kind=SCALE_OUT, subject="fleet")]
        assert solve(snap, FleetView(max_volumes=4), POLICY, hist) == []
        # Past the window the same signal fires again.
        hist = [ActionRecord(ts=NOW - 100, kind=SCALE_OUT, subject="fleet")]
        assert _kinds(
            solve(snap, FleetView(max_volumes=4), POLICY, hist)
        ) == [SCALE_OUT]

    def test_reversal_damping_after_drain(self):
        """A diurnal edge right after scale-in must not saw-tooth: a
        recent drain/retire suppresses scale-out regardless of signals."""
        snap = _snap([_vol("v0", inflight=9)])
        for kind in (DRAIN, RETIRE):
            hist = [ActionRecord(ts=NOW - 10, kind=kind, subject="v9")]
            assert solve(snap, FleetView(max_volumes=4), POLICY, hist) == []

    def test_no_scale_out_while_draining(self):
        snap = _snap([_vol("v0", inflight=9), _vol("v1", entries=3)])
        actions = solve(
            snap, FleetView(draining=frozenset({"v1"}), max_volumes=4), POLICY
        )
        assert _kinds(actions) == [DRAIN]  # continuation only, no out


# ---------------------------------------------------------------------------
# solver: scale-in (drain entry) + drain lifecycle
# ---------------------------------------------------------------------------


class TestScaleIn:
    IDLE = [_vol("v0", window=100, stored=900), _vol("v1", window=50, stored=100)]

    def test_idle_rounds_hysteresis(self):
        snap = _snap(self.IDLE)
        assert solve(snap, FleetView(idle_rounds=2), POLICY) == []
        actions = solve(snap, FleetView(idle_rounds=3), POLICY)
        assert _kinds(actions) == [DRAIN]
        # Victim: the emptiest volume, so the drain moves the least data.
        assert actions[0].subject == "v1"
        assert actions[0].count == POLICY.drain_keys_per_round

    def test_min_volumes_floor(self):
        snap = _snap([_vol("v0", window=10)])
        assert solve(snap, FleetView(idle_rounds=99), POLICY) == []

    def test_busy_volume_blocks_idle(self):
        for busy in (_vol("v1", window=5_000), _vol("v1", inflight=1)):
            snap = _snap([_vol("v0", window=100), busy])
            assert solve(snap, FleetView(idle_rounds=99), POLICY) == []

    def test_sustained_overload_blocks_idle(self):
        snap = _snap(
            self.IDLE,
            sustained={"v0": {"landing_inflight": {"kind": "sustained"}}},
        )
        assert _kinds(solve(snap, FleetView(idle_rounds=99), POLICY)) == [
            SCALE_OUT
        ]

    def test_reversal_damping_after_scale_out(self):
        snap = _snap(self.IDLE)
        hist = [ActionRecord(ts=NOW - 10, kind=SCALE_OUT, subject="fleet")]
        assert solve(snap, FleetView(idle_rounds=99), POLICY, hist) == []

    def test_one_drain_at_a_time(self):
        """Three idle volumes, one already draining: the round continues
        that drain and never opens a second one."""
        snap = _snap(self.IDLE + [_vol("v2", entries=5)])
        actions = solve(
            snap, FleetView(draining=frozenset({"v2"}), idle_rounds=99), POLICY
        )
        assert [(a.kind, a.subject) for a in actions] == [(DRAIN, "v2")]

    def test_drain_continues_through_cooldown(self):
        """Continuation is NOT cooldown-gated: a started drain converges
        one batch per round instead of stalling a window per batch."""
        snap = _snap([_vol("v0"), _vol("v1", entries=7)])
        hist = [ActionRecord(ts=NOW - 1, kind=DRAIN, subject="v1")]
        actions = solve(
            snap, FleetView(draining=frozenset({"v1"})), POLICY, hist
        )
        assert [(a.kind, a.subject) for a in actions] == [(DRAIN, "v1")]
        assert "7 entries remain" in actions[0].reason

    def test_empty_draining_volume_retires(self):
        snap = _snap([_vol("v0"), _vol("v1", entries=0)])
        actions = solve(snap, FleetView(draining=frozenset({"v1"})), POLICY)
        assert [(a.kind, a.subject) for a in actions] == [(RETIRE, "v1")]


# ---------------------------------------------------------------------------
# solver: blob demotion + budget
# ---------------------------------------------------------------------------


class TestBlobDemote:
    def test_demotes_spilled_backlog_when_enabled(self):
        snap = _snap([_vol("v0"), _vol("v1")])
        fleet = FleetView(blob_enabled=True, spilled_keys={"v0": 5, "v1": 0})
        actions = solve(snap, fleet, POLICY)
        assert [(a.kind, a.subject) for a in actions] == [(BLOB_DEMOTE, "v0")]
        assert actions[0].count == POLICY.blob_keys_per_round

    def test_disabled_or_overloaded_skips(self):
        snap = _snap([_vol("v0")])
        assert solve(snap, FleetView(spilled_keys={"v0": 5}), POLICY) == []
        hot = _snap([_vol("v0", inflight=9)])
        fleet = FleetView(
            blob_enabled=True, max_volumes=4, spilled_keys={"v0": 5}
        )
        assert _kinds(solve(hot, fleet, POLICY)) == [SCALE_OUT]

    def test_per_volume_cooldown(self):
        snap = _snap([_vol("v0")])
        fleet = FleetView(blob_enabled=True, spilled_keys={"v0": 5})
        hist = [ActionRecord(ts=NOW - 10, kind=BLOB_DEMOTE, subject="v0")]
        assert solve(snap, fleet, POLICY, hist) == []

    def test_max_actions_budget(self):
        snap = _snap([_vol(f"v{i}", entries=2) for i in range(6)])
        fleet = FleetView(
            draining=frozenset(f"v{i}" for i in range(6)), max_volumes=8
        )
        policy = AutoscalePolicy(max_actions=2)
        assert len(solve(snap, fleet, policy)) == 2


# ---------------------------------------------------------------------------
# fleet: ts.autoscale() end to end
# ---------------------------------------------------------------------------


@pytest.fixture
def elastic_env(monkeypatch):
    """Tight thresholds + 1 s ledger windows so the diurnal cycle runs in
    seconds: a few puts trigger scale-out, and the traffic window decays
    fast enough for the idle drain to follow."""
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_OUT_WINDOW_BYTES", "4096")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS", "2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S", "0.2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_MAX_VOLUMES", "2")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND", "8")
    monkeypatch.setenv("TORCHSTORE_TPU_LEDGER_WINDOW_S", "1")


async def test_autoscale_plan_quiet_fleet(elastic_env):
    await ts.initialize(store_name="asq")
    try:
        plan = await ts.autoscale_plan(store_name="asq")
        assert plan["actions"] == []
        assert plan["fleet"]["volumes"] == 1
        assert plan["fleet"]["draining"] == []
    finally:
        await ts.shutdown("asq")


async def test_scale_out_drain_retire_cycle(elastic_env):
    """The full diurnal story on one box: load → ts.autoscale() spawns and
    attaches a volume (placement-visible immediately), idle → the fleet
    drains it gracefully (every key migrated, zero loss) and retires the
    actor process; every decision lands in the flight recorder."""
    await ts.initialize(store_name="ascyc")
    try:
        arrs = {
            f"k{i}": np.arange(2000, dtype=np.float32) + i for i in range(8)
        }
        for k, v in arrs.items():
            await ts.put(k, v, store_name="ascyc")
        r = await ts.autoscale(store_name="ascyc")
        assert r["spawned"] == ["scale-0"], r["actions"]
        c = ts.client("ascyc")
        vmap = await c.controller.get_volume_map.call_one()
        assert len(vmap) == 2 and "scale-0" in vmap
        # At the ceiling now: a second round must not spawn a third.
        r = await ts.autoscale(store_name="ascyc")
        assert not r["spawned"]
        # Go idle; the window decays and the fleet converges back to 1.
        for _ in range(30):
            await asyncio.sleep(0.5)
            r = await ts.autoscale(store_name="ascyc")
            vmap = await c.controller.get_volume_map.call_one()
            if len(vmap) == 1:
                break
        assert len(vmap) == 1, vmap
        assert r["stopped"] == ["scale-0"]
        for k, v in arrs.items():
            got = await ts.get(k, store_name="ascyc")
            assert np.array_equal(got, v), k
        # Audit trail: every scale transition is a decision event.
        record = await ts.flight_record(store_name="ascyc")
        decided = {
            e["name"]
            for e in record["events"]
            if e.get("kind") == "decision"
            and str(e.get("name", "")).startswith("autoscale/")
        }
        assert "autoscale/scale_out" in decided, decided
        assert "autoscale/drain_volume" in decided, decided
        assert "autoscale/retire_volume" in decided, decided
    finally:
        await ts.shutdown("ascyc")


async def test_periodic_retire_reclaims_spawned_process(elastic_env):
    """A volume retired by the controller's PERIODIC loop (a round no
    client participates in) must still get its actor process reclaimed:
    the next ts.autoscale() reconciles spawned meshes against the live
    volume map instead of relying on the retire action landing in its
    own round — otherwise the process idles until shutdown, negating
    the volume-seconds saving scale-in exists for."""
    await ts.initialize(store_name="asper")
    try:
        for i in range(8):
            await ts.put(
                f"p{i}",
                np.arange(2000, dtype=np.float32) + i,
                store_name="asper",
            )
        r = await ts.autoscale(store_name="asper")
        assert r["spawned"] == ["scale-0"], r["actions"]
        c = ts.client("asper")
        # Drive the drain → retire cycle through the CONTROLLER endpoint
        # — the same path the periodic loop takes; no mesh stop can
        # happen in these rounds.
        vmap: dict = {}
        for _ in range(40):
            await asyncio.sleep(0.25)
            await c.controller.autoscale_reconcile.call_one()
            vmap = await c.controller.get_volume_map.call_one()
            if "scale-0" not in vmap:
                break
        assert "scale-0" not in vmap, vmap
        # The orphaned actor process is reclaimed by the NEXT manual
        # round, whatever that round itself decides.
        r = await ts.autoscale(store_name="asper")
        assert r["stopped"] == ["scale-0"], r
    finally:
        await ts.shutdown("asper")


async def test_draining_volume_excluded_from_placement(elastic_env, monkeypatch):
    """While a volume drains, clients stop offering it for new puts (the
    volume map exposes health="draining") — but reads of keys still
    resident there keep serving until the migration empties it."""
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS", "1")
    await ts.initialize(num_storage_volumes=2, store_name="asdr")
    try:
        c = ts.client("asdr")
        await c._ensure_setup()
        old = {f"d{i}": np.arange(64, dtype=np.float32) + i for i in range(6)}
        for k, v in old.items():
            await ts.put(k, v, store_name="asdr")
        # Idle out until the engine marks a victim draining; with a
        # 1-key-per-round quantum it stays mid-drain for several rounds.
        draining: list[str] = []
        vmap: dict = {}
        for _ in range(30):
            await asyncio.sleep(0.5)
            await ts.autoscale(store_name="asdr")
            vmap = await c.controller.get_volume_map.call_one()
            draining = [
                vid
                for vid, info in vmap.items()
                if info.get("health") == "draining"
            ]
            if draining:
                break
        assert draining, vmap
        victim = draining[0]
        await c._refresh_health()
        new = {f"n{i}": np.arange(64, dtype=np.float32) - i for i in range(6)}
        for k, v in new.items():
            await ts.put(k, v, store_name="asdr")
        locs = await c.controller.locate_volumes.call_one(sorted(new))
        for key, vols in locs.items():
            assert victim not in vols, (key, victim, vols)
        for k, v in {**old, **new}.items():
            got = await ts.get(k, store_name="asdr")
            assert np.array_equal(got, v), k
    finally:
        await ts.shutdown("asdr")
