"""Gated real-checkpoint e2e: TinyLlama safetensors -> ``hf_convert`` ->
publish through the store -> resharded re-acquire -> pinned greedy decode.

Env-gated like the reference's HF-model test
(/root/reference/tests/test_models.py:33-136 gates on ``HF_TOKEN``):

- ``TORCHSTORE_TPU_TINYLLAMA_DIR``: local checkpoint directory holding the
  ``config.json`` + ``*.safetensors`` of a TinyLlama-class Llama checkpoint
  (e.g. a snapshot of TinyLlama/TinyLlama-1.1B-Chat-v1.0); or
- ``HF_TOKEN``: download the checkpoint from the hub via ``transformers``.

Skipped (not failed) when neither is set — this is the slow, realism tier;
logits-parity on synthetic weights stays in tier-1 (tests/test_hf_convert.py).

The decode pin is SELF-REFERENTIAL by design: greedy tokens from the
converted params BEFORE the store round trip must equal greedy tokens from
the re-acquired (resharded) params — bit-exact weights through publish +
reshard, demonstrated at the level users observe (generated token ids),
with no fixture file to go stale.
"""

import glob
import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

jax = pytest.importorskip("jax")
transformers = pytest.importorskip("transformers")
safetensors_np = pytest.importorskip("safetensors.numpy")

import jax.numpy as jnp  # noqa: E402

import torchstore_tpu as ts  # noqa: E402
from torchstore_tpu import parallel  # noqa: E402
from torchstore_tpu.models.generate import Decoder  # noqa: E402
from torchstore_tpu.models.hf_convert import (  # noqa: E402
    config_from_hf,
    convert_hf_llama,
)

CKPT_DIR_ENV = "TORCHSTORE_TPU_TINYLLAMA_DIR"
HF_REPO = "TinyLlama/TinyLlama-1.1B-Chat-v1.0"


def _load_checkpoint():
    """(hf_config, hf_state_dict as numpy) from the gated source."""
    local_dir = os.environ.get(CKPT_DIR_ENV)
    if local_dir:
        hf_config = transformers.AutoConfig.from_pretrained(local_dir)
        sd: dict = {}
        files = sorted(glob.glob(os.path.join(local_dir, "*.safetensors")))
        if not files:
            pytest.skip(f"{CKPT_DIR_ENV}={local_dir} holds no *.safetensors")
        for path in files:
            sd.update(safetensors_np.load_file(path))
        return hf_config, sd
    if os.environ.get("HF_TOKEN"):
        import torch

        model = transformers.AutoModelForCausalLM.from_pretrained(
            HF_REPO, torch_dtype=torch.float32
        )
        return model.config, {
            k: v.numpy() for k, v in model.state_dict().items()
        }
    pytest.skip(
        f"real-checkpoint e2e is gated: set {CKPT_DIR_ENV} to a local "
        f"TinyLlama safetensors dir, or HF_TOKEN to download {HF_REPO}"
    )


async def test_tinyllama_publish_reshard_decode():
    import dataclasses

    hf_config, hf_sd = _load_checkpoint()
    cfg = config_from_hf(hf_config)
    # fp32 end to end: the pin is exact token equality, which float32
    # matmuls on one host reproduce deterministically.
    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(hf_sd, cfg)
    params = jax.tree.map(lambda x: np.asarray(x, np.float32), params)

    prompt = np.array([[1, 450, 4996, 17354, 1701, 29916]], dtype=np.int32)
    decoder = Decoder(cfg, max_len=prompt.shape[1] + 16)
    ref_tokens = np.asarray(
        decoder.generate(
            jax.tree.map(jnp.asarray, params), prompt, max_new_tokens=16
        )
    )

    n_dev = len(jax.devices())
    mesh = parallel.make_mesh({"tp": n_dev})
    from jax.sharding import NamedSharding, PartitionSpec as P

    def target(leaf):
        spec = (
            P("tp")
            if leaf.ndim and leaf.shape[0] % n_dev == 0
            else P()
        )
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
        )

    targets = jax.tree.map(target, params)

    await ts.initialize(store_name="tinyllama")
    try:
        # Cold-start provisioning of the full checkpoint working set, then
        # publish (the prewarm path at real-model scale).
        report = await ts.prewarm(params, store_name="tinyllama")
        assert report["ok"], report
        await ts.put_state_dict("ckpt/v0", params, store_name="tinyllama")
        resharded = await ts.get_state_dict(
            "ckpt/v0", user_state_dict=targets, store_name="tinyllama"
        )
    finally:
        await ts.shutdown("tinyllama")

    # Every re-acquired leaf is bit-exact vs the converted original.
    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(resharded)[0]
    assert len(flat_a) == len(flat_b)
    for (path_a, a), (path_b, b) in zip(flat_a, flat_b):
        assert path_a == path_b
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Pinned greedy decode: token ids from the resharded params must equal
    # the pre-publish reference exactly.
    host_params = jax.tree.map(
        lambda x: jnp.asarray(np.asarray(x)), resharded
    )
    got_tokens = np.asarray(
        decoder.generate(host_params, prompt, max_new_tokens=16)
    )
    np.testing.assert_array_equal(got_tokens, ref_tokens)
