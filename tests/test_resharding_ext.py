"""Extended resharding matrix: all shard-dim permutations across mesh
shapes (reference tests/test_resharding_ext.py:19-133); the full cross
product is gated by TORCHSTORE_TPU_ENABLE_SLOW_TESTS like the reference's
slow-test env gate."""

import itertools
import os

import numpy as np
import pytest

import torchstore_tpu as ts

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

GLOBAL = np.arange(16 * 16 * 8, dtype=np.float32).reshape(16, 16, 8)

MESHES = [((8,), ("x",)), ((2, 4), ("x", "y")), ((4, 2), ("x", "y"))]
# Specs shard dims 0/1 over available axes in every permutation.
SPECS_1D = [P("x"), P(None, "x"), P()]
SPECS_2D = [P("x", "y"), P("y", "x"), P("x"), P(None, "y"), P()]


def cases():
    out = []
    for (sshape, snames), (dshape, dnames) in itertools.product(MESHES, MESHES):
        sspecs = SPECS_1D if len(sshape) == 1 else SPECS_2D
        dspecs = SPECS_1D if len(dshape) == 1 else SPECS_2D
        for sspec, dspec in itertools.product(sspecs, dspecs):
            out.append((sshape, snames, sspec, dshape, dnames, dspec))
    return out


ALL_CASES = cases()
if not os.environ.get("TORCHSTORE_TPU_ENABLE_SLOW_TESTS"):
    # Representative subset for CI; full matrix under the slow gate.
    ALL_CASES = ALL_CASES[:: max(1, len(ALL_CASES) // 12)]


@pytest.fixture(scope="module")
def anyio_backend():
    # Module-scoped override so the module-scoped store fixture can be async.
    return "asyncio"


@pytest.fixture(scope="module")
async def store(anyio_backend):
    await ts.initialize(store_name="rext")
    yield "rext"
    await ts.shutdown("rext")


def _sharded(value, shape, names, spec):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return jax.device_put(value, NamedSharding(Mesh(devs, names), spec))


@pytest.mark.parametrize("case", ALL_CASES, ids=lambda c: f"{c[0]}{c[2]}->{c[3]}{c[5]}")
async def test_permutation(store, case):
    sshape, snames, sspec, dshape, dnames, dspec = case
    src = _sharded(GLOBAL, sshape, snames, sspec)
    await ts.put("w", src, store_name=store)
    like = _sharded(np.zeros_like(GLOBAL), dshape, dnames, dspec)
    out = await ts.get("w", like=like, store_name=store)
    np.testing.assert_array_equal(np.asarray(out), GLOBAL)
    assert out.sharding == like.sharding
    await ts.delete("w", store_name=store)
