"""One-sided data plane (ISSUE 7): seqlock-stamped warm gets + doorbells.

Covers the stamp protocol at unit level (stale / torn / borrow semantics
against a hand-built stamp table), the fleet-level zero-RPC warm get
(asserted via metrics snapshots on BOTH sides), the ``shm.landing_stamp``
faultpoint (writer visibly mid-landing -> reader falls back loudly, never
serves mixed-generation bytes), epoch-bump plan drops, the bulk doorbell
vertical, and get_batch's batch-level plan seeding.
"""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.transport import shared_memory as shm_mod

pytestmark = pytest.mark.anyio


def _counter(name: str, **labels) -> float:
    snap = obs_metrics.metrics_snapshot()
    return sum(
        s["value"]
        for s in snap.get(name, {}).get("series", [])
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


async def _volume_get_rpcs(client) -> float:
    total = 0.0
    for ref in client._volume_refs.values():
        stats = await ref.actor.stats.call_one()
        total += sum(
            s["value"]
            for s in stats["metrics"]
            .get("ts_volume_get_ops_total", {})
            .get("series", [])
        )
    return total


# --------------------------------------------------------------------------
# unit: the seqlock protocol itself
# --------------------------------------------------------------------------


@pytest.fixture
def stamped_plan():
    """A hand-built (segment, stamp table, plan, client cache) quartet —
    the stamped-read protocol without any fleet."""
    if not shm_mod.is_available():
        pytest.skip("/dev/shm unavailable")
    from torchstore_tpu.transport.types import TensorMeta

    data = np.arange(1024, dtype=np.float32)
    seg = shm_mod.ShmSegment.create(data.nbytes)
    seg.view(TensorMeta.of(data))[:] = data
    table = shm_mod.StampTable.create()
    slot = 7
    table.write(slot, 4)  # even: stable at generation 4
    meta = TensorMeta.of(data)
    plan = {
        "volume_id": "v0",
        "segment": seg.name,
        "segment_size": seg.size,
        "offset": 0,
        "strides": None,
        "meta": meta,
        "nbytes": meta.nbytes,
        "shape": tuple(meta.shape),
        "npdtype": meta.np_dtype,
        "stamp_name": table.seg.name,
        "stamp_size": table.seg.size,
        "slot": slot,
        "gen": 4,
    }
    cache = shm_mod.ShmClientCache()
    try:
        yield data, seg, table, plan, cache
    finally:
        cache.clear()
        seg.unlink()
        table.seg.unlink()


async def test_stamped_read_serves_and_validates(stamped_plan):
    data, _seg, table, plan, cache = stamped_plan
    out, extra = shm_mod.stamped_read(cache, plan)
    assert extra is None
    assert np.array_equal(out, data)
    # In-place destination.
    dest = np.zeros_like(data)
    out2, _ = shm_mod.stamped_read(cache, plan, dest=dest)
    assert out2 is dest and np.array_equal(dest, data)
    # Stale stamp (entry replaced since the plan was recorded).
    table.write(plan["slot"], 6)
    with pytest.raises(shm_mod.OneSidedMiss) as exc:
        shm_mod.stamped_read(cache, plan)
    assert exc.value.reason == "stale_stamp"
    # Odd stamp (writer in flight) is stale too.
    table.write(plan["slot"], 5)
    with pytest.raises(shm_mod.OneSidedMiss):
        shm_mod.stamped_read(cache, plan)


async def test_stamped_read_detects_torn_copy(stamped_plan, monkeypatch):
    """A stamp that moves MID-COPY (writer landed while we memcpy'd) must
    discard the copy: mixed-generation bytes are never returned."""
    data, _seg, table, plan, cache = stamped_plan
    real_copy = shm_mod.copy_into

    def tearing_copy(dst, src):
        real_copy(dst, src)
        table.write(plan["slot"], 6)  # the landing settled mid-copy

    monkeypatch.setattr(shm_mod, "copy_into", tearing_copy)
    torn0 = _counter("ts_one_sided_torn_total", transport="shm")
    with pytest.raises(shm_mod.OneSidedMiss) as exc:
        shm_mod.stamped_read(cache, plan)
    assert exc.value.reason == "torn"
    assert _counter("ts_one_sided_torn_total", transport="shm") > torn0


async def test_stamped_read_borrow_recheck(stamped_plan):
    data, _seg, table, plan, cache = stamped_plan
    view, recheck = shm_mod.stamped_read(cache, plan, borrow=True)
    assert np.array_equal(view, data)
    assert not view.flags.writeable
    assert recheck() is True
    table.write(plan["slot"], 6)
    assert recheck() is False


async def test_overlapping_write_brackets_stay_odd():
    """Two puts of one key overlap (endpoints dispatch as independent
    tasks): the entry stamp may only settle EVEN when the LAST bracket
    closes — settling at the first close would let a reader validate
    against bytes the second put is still writing."""
    if not shm_mod.is_available():
        pytest.skip("/dev/shm unavailable")
    from torchstore_tpu.transport.types import TensorMeta

    cache = shm_mod.ShmServerCache()
    data = np.arange(64, dtype=np.float32)
    seg = shm_mod.ShmSegment.create(data.nbytes)
    try:
        cache.put("k", None, seg, TensorMeta.of(data))
        pair = [("k", None)]
        cache.begin_writes(pair)
        cache.end_writes(pair)  # first landing settles a slot, even gen
        entry = cache.lookup("k", None)
        base = cache.stamps.read(entry.slot)
        assert base % 2 == 0

        cache.begin_writes(pair)  # put A opens
        cache.begin_writes(pair)  # put B overlaps
        assert cache.stamps.read(entry.slot) % 2 == 1
        cache.end_writes(pair)  # A closes: B still writing -> stays odd
        assert cache.stamps.read(entry.slot) % 2 == 1
        cache.end_writes(pair)  # last close settles the next even gen
        after = cache.stamps.read(entry.slot)
        assert after % 2 == 0 and after > base
        assert not cache._write_nesting
    finally:
        cache.clear()


async def test_stamped_read_batch_all_or_nothing(stamped_plan):
    data, _seg, table, plan, cache = stamped_plan
    good = dict(plan)
    bad = dict(plan)
    bad["gen"] = 2  # recorded against an older generation
    dests = [np.zeros_like(data), np.zeros_like(data)]
    with pytest.raises(shm_mod.OneSidedMiss):
        await shm_mod.stamped_read_batch(cache, [good, bad], dests)
    # The good plan alone serves.
    out = await shm_mod.stamped_read_batch(cache, [good], [dests[0]])
    assert np.array_equal(out[0], data)


# --------------------------------------------------------------------------
# fleet: zero-RPC warm gets (SHM)
# --------------------------------------------------------------------------


async def test_warm_get_zero_rpcs_and_invalidation():
    """The acceptance assertion: a warm same-host get is served with ZERO
    get RPCs (volume-side op counter flat, client-side one-sided counter
    up), and an overwrite invalidates the plan without ever serving stale
    or torn bytes."""
    await ts.initialize(
        store_name="os_shm",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        a = np.random.rand(512).astype(np.float32)
        await ts.put("k", a, store_name="os_shm")
        out1 = await ts.get("k", like=np.zeros_like(a), store_name="os_shm")
        assert np.array_equal(np.asarray(out1), a)

        client = ts.client("os_shm")
        rpcs0 = await _volume_get_rpcs(client)
        reads0 = _counter("ts_one_sided_reads_total", transport="shm")
        out2 = await ts.get("k", like=np.zeros_like(a), store_name="os_shm")
        assert np.array_equal(np.asarray(out2), a)
        assert _counter("ts_one_sided_reads_total", transport="shm") > reads0
        assert await _volume_get_rpcs(client) == rpcs0, (
            "warm same-host get issued a get RPC"
        )

        # Overwrite: the stamped plan goes stale; the next get serves the
        # NEW bytes (loud fallback, then a fresh plan serves one-sided).
        b = (a * 2).astype(np.float32)
        await ts.put("k", b, store_name="os_shm")
        out3 = await ts.get("k", like=np.zeros_like(a), store_name="os_shm")
        assert np.array_equal(np.asarray(out3), b)
        reads1 = _counter("ts_one_sided_reads_total", transport="shm")
        out4 = await ts.get("k", like=np.zeros_like(a), store_name="os_shm")
        assert np.array_equal(np.asarray(out4), b)
        assert _counter("ts_one_sided_reads_total", transport="shm") > reads1
    finally:
        await ts.shutdown("os_shm")


async def test_landing_stamp_faultpoint_forces_loud_fallback():
    """The new ``shm.landing_stamp`` faultpoint: a writer wedged inside the
    landing bracket holds the entry stamp ODD — a concurrent one-sided
    reader observes it, falls back to the RPC path (metric bumps), and the
    value it returns is a CONSISTENT generation (old or new, never mixed)."""
    await ts.initialize(
        store_name="os_fault",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        a = np.full(256, 1.0, dtype=np.float32)
        b = np.full(256, 2.0, dtype=np.float32)
        await ts.put("k", a, store_name="os_fault")
        warm = await ts.get("k", like=np.zeros_like(a), store_name="os_fault")
        assert np.array_equal(np.asarray(warm), a)

        await ts.inject_fault(
            "shm.landing_stamp",
            "delay",
            count=1,
            delay_ms=1200,
            scope="volumes",
            store_name="os_fault",
        )
        put_task = asyncio.create_task(ts.put("k", b, store_name="os_fault"))
        await asyncio.sleep(0.4)  # the put is now inside the bracket
        fb0 = _counter("ts_one_sided_fallbacks_total")
        out = await ts.get("k", like=np.zeros_like(a), store_name="os_fault")
        got = np.asarray(out)
        assert np.array_equal(got, a) or np.array_equal(got, b), (
            "mixed-generation bytes served during a landing"
        )
        assert _counter("ts_one_sided_fallbacks_total") > fb0, (
            "reader did not fall back while the stamp was odd"
        )
        await put_task
        # Settled: the new generation serves one-sided again.
        out2 = await ts.get("k", like=np.zeros_like(a), store_name="os_fault")
        assert np.array_equal(np.asarray(out2), b)
        await ts.clear_faults(store_name="os_fault")
    finally:
        await ts.shutdown("os_fault")


async def test_epoch_bump_drops_one_sided_plans():
    """Quarantine/repair transitions bump the placement epoch; the client
    must drop every cached one-sided plan with it (stale placement)."""
    await ts.initialize(
        store_name="os_epoch",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        a = np.random.rand(64).astype(np.float32)
        await ts.put("k", a, store_name="os_epoch")
        await ts.get("k", like=np.zeros_like(a), store_name="os_epoch")
        client = ts.client("os_epoch")
        cache = client._ctx.peek(shm_mod.ShmClientCache)
        assert cache is not None and cache.one_sided, "plan was not recorded"
        await client.bump_placement_epoch()
        assert not cache.one_sided, "epoch bump did not drop one-sided plans"
        # Correctness after the drop: the RPC path re-records and serves.
        out = await ts.get("k", like=np.zeros_like(a), store_name="os_epoch")
        assert np.array_equal(np.asarray(out), a)
        assert cache.one_sided
    finally:
        await ts.shutdown("os_epoch")


# --------------------------------------------------------------------------
# fleet: bulk doorbell
# --------------------------------------------------------------------------


async def test_bulk_doorbell_warm_batch():
    """Cross-host rung (bulk transport): the second identical get_batch
    rings ONE doorbell instead of the get RPC + per-key frames, serves
    fresh bytes against the cached plan after an overwrite, and falls back
    loudly when the volume no longer knows the plan."""
    await ts.initialize(
        store_name="os_bulk",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    try:
        items = {
            f"d/{i}": np.random.rand(128).astype(np.float32) for i in range(4)
        }
        await ts.put_batch(items, store_name="os_bulk")
        out1 = await ts.get_batch(list(items), store_name="os_bulk")
        for k, v in items.items():
            assert np.array_equal(np.asarray(out1[k]), v)
        reads0 = _counter("ts_one_sided_reads_total", transport="bulk")
        out2 = await ts.get_batch(list(items), store_name="os_bulk")
        for k, v in items.items():
            assert np.array_equal(np.asarray(out2[k]), v)
        assert (
            _counter("ts_one_sided_reads_total", transport="bulk")
            >= reads0 + len(items)
        ), "warm batch did not ride the doorbell"

        # Same cached plan, NEW bytes: that is the point of the doorbell.
        items2 = {k: (v * 3).astype(np.float32) for k, v in items.items()}
        await ts.put_batch(items2, store_name="os_bulk")
        out3 = await ts.get_batch(list(items), store_name="os_bulk")
        for k, v in items2.items():
            assert np.array_equal(np.asarray(out3[k]), v)

        # Unknown plan at the volume -> miss frame -> loud RPC fallback.
        from torchstore_tpu.transport.bulk import BulkClientCache

        client = ts.client("os_bulk")
        bcache = client._ctx.peek(BulkClientCache)
        assert bcache is not None and bcache.doorbells
        for entry in bcache.doorbells.values():
            entry["plan_id"] = 12345
        fb0 = _counter(
            "ts_one_sided_fallbacks_total", reason="doorbell_unknown_plan"
        )
        out4 = await ts.get_batch(list(items), store_name="os_bulk")
        for k, v in items2.items():
            assert np.array_equal(np.asarray(out4[k]), v)
        assert (
            _counter(
                "ts_one_sided_fallbacks_total", reason="doorbell_unknown_plan"
            )
            > fb0
        )
    finally:
        await ts.shutdown("os_bulk")


# --------------------------------------------------------------------------
# get_batch plan seeding
# --------------------------------------------------------------------------


async def test_get_batch_seeds_plan_cache_and_goes_zero_rpc():
    """The satellite fix: get_batch populates the iteration-stable plan
    cache (previously only state-dict ops did), and a warm fully-covered
    batch is served one-sided with ZERO RPCs — no locate, no epoch check,
    no gets (the covered-batch fast path runs before the plan-cache layer
    even looks, so the hit counter stays put while the one-sided read
    counter moves)."""
    await ts.initialize(
        store_name="os_batch",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        items = {
            f"b/{i}": np.random.rand(64).astype(np.float32) for i in range(8)
        }
        await ts.put_batch(items, store_name="os_batch")
        targets = {k: np.zeros_like(v) for k, v in items.items()}
        await ts.get_batch(dict(targets), store_name="os_batch")
        client = ts.client("os_batch")
        # The cold batch seeded an iteration-stable plan (satellite claim).
        assert any(
            op == "get_batch" for op, _, _ in client.plan_cache.entries
        ), "cold get_batch did not seed the plan cache"
        rpcs0 = await _volume_get_rpcs(client)
        reads0 = _counter("ts_one_sided_reads_total", transport="shm")
        out = await ts.get_batch(
            {k: np.zeros_like(v) for k, v in items.items()},
            store_name="os_batch",
        )
        for k, v in items.items():
            assert np.array_equal(out[k], v)
        assert (
            _counter("ts_one_sided_reads_total", transport="shm")
            >= reads0 + len(items)
        ), "warm covered batch was not served one-sided"
        assert await _volume_get_rpcs(client) == rpcs0, (
            "warm covered batch still issued get RPCs"
        )
    finally:
        await ts.shutdown("os_batch")
