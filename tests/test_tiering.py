"""Tiered capacity & multi-version serving (torchstore_tpu/tiering/).

Covers the ISSUE-12 subsystem: cohort retention leases (TTL lifecycle, the
controller's delete guard, lease-aware publisher GC), the per-volume spill
tier (watermark demotion, leased-hot exemption, fault-in through the normal
get path, crash-safe abort), version-pinned acquires, and the
``ts.version_catalog()`` operator view. The chaos-scheduled cohort test
(kill mid-spill / mid-fault-in) lives in tests/test_chaos.py.
"""

import asyncio
import time

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import tiering
from torchstore_tpu.tiering.leases import LeaseRegistry


# ---------------------------------------------------------------------------
# unit: version grouping + lease registry
# ---------------------------------------------------------------------------


class TestVersionGroup:
    def test_channel_version_keys(self):
        assert tiering.version_group("chan/v7/w0") == ("chan", 7)
        assert tiering.version_group("a/b/v12/MAPPING") == ("a/b", 12)
        assert tiering.version_group("chan/v7") == ("chan", 7)

    def test_non_version_keys(self):
        assert tiering.version_group("chan/LATEST") is None
        assert tiering.version_group("plain_key") is None
        assert tiering.version_group("chan/vx/w0") is None
        # A bare leading v-segment has no channel in front of it.
        assert tiering.version_group("v3/w0") is None

    def test_first_version_segment_wins(self):
        assert tiering.version_group("a/v1/b/v2/c") == ("a", 1)


class TestLeaseRegistry:
    def test_acquire_renew_release(self):
        reg = LeaseRegistry(ttl_s=30)
        lease = reg.acquire("eval", "chan", 3)
        assert reg.is_pinned("chan", 3) and not reg.is_pinned("chan", 4)
        assert reg.pinned_groups() == {"chan/v3"}
        assert reg.blocks_delete("chan/v3/w0")
        assert not reg.blocks_delete("chan/v4/w0")
        assert not reg.blocks_delete("chan/LATEST")
        renewed = reg.renew(lease["lease_id"], ttl_s=60)
        assert renewed["ttl_s"] == 60
        assert reg.release(lease["lease_id"]) is True
        assert reg.release(lease["lease_id"]) is False  # idempotent
        assert not reg.is_pinned("chan", 3)

    def test_ttl_expiry(self):
        reg = LeaseRegistry(ttl_s=0.05)
        lease = reg.acquire("eval", "chan", 1)
        assert reg.is_pinned("chan", 1)
        time.sleep(0.08)
        assert not reg.is_pinned("chan", 1)  # lazy expiry on every query
        with pytest.raises(KeyError):
            reg.renew(lease["lease_id"])  # expired: re-acquire instead

    def test_reacquire_renews_instead_of_stacking(self):
        reg = LeaseRegistry(ttl_s=30)
        a = reg.acquire("eval", "chan", 1)
        b = reg.acquire("eval", "chan", 1, ttl_s=90)
        assert a["lease_id"] == b["lease_id"] and len(reg) == 1
        # The coalesce is reported: a read-scoped caller must not release
        # a pin it merely refreshed.
        assert a["renewed"] is False and b["renewed"] is True
        # A DIFFERENT cohort's pin on the same version is its own lease.
        reg.acquire("canary", "chan", 1)
        assert len(reg) == 2
        assert sorted(reg.pins("chan")["chan"][1]) == ["canary", "eval"]


# ---------------------------------------------------------------------------
# fleet: spill + fault-in + leases end to end
# ---------------------------------------------------------------------------

N_KEYS = 4
N_ELEM = 1024  # 4 KB per tensor


def _sd(version: int) -> dict:
    return {
        f"w{i}": np.full(N_ELEM, float(version), np.float32)
        for i in range(N_KEYS)
    }


def _assert_version(sd: dict, version: int) -> None:
    for key, arr in sd.items():
        vals = np.unique(np.asarray(arr))
        assert vals.size == 1 and vals[0] == float(version), (
            f"{key}: {vals} != v{version}"
        )


@pytest.fixture
def tiered_store(monkeypatch, tmp_path):
    """Env for a spill-enabled fleet: budget sized so ~2 versions fit
    resident (high 0.5 / low 0.25 of 32 KB), background sweeper off —
    tests drive deterministic ts.tier_sweep() calls."""
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_ENABLED", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_DIR", str(tmp_path / "tier"))
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_BUDGET_BYTES", str(32 * 1024))
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_HIGH_PCT", "0.5")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_LOW_PCT", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S", "0")


async def test_spill_faults_in_and_exempts_leased(tiered_store):
    await ts.initialize(store_name="tier1")
    try:
        pub = ts.WeightPublisher("cap", store_name="tier1", keep=10)
        for v in range(4):
            assert await pub.publish(_sd(v)) == v
        client = ts.client("tier1")
        lease = await client.lease_acquire("hot-cohort", "cap", 1)
        assert lease["resident_keys"] == N_KEYS + 1  # tensors + MAPPING
        report = await ts.tier_sweep("tier1")
        (vid,) = report
        assert report[vid]["spilled"] > 0
        catalog = await ts.version_catalog("cap", store_name="tier1")
        # The leased version is exempt: fully resident; cold versions
        # demoted to disk (budget only fits ~2 versions of 4).
        assert catalog["cap"][1]["spilled_keys"] == 0
        assert [le["cohort"] for le in catalog["cap"][1]["leases"]] == [
            "hot-cohort"
        ]
        spilled_versions = [
            v
            for v, rec in catalog["cap"].items()
            if rec["keys"] and rec["spilled_keys"] == rec["keys"]
        ]
        assert spilled_versions, catalog
        # Fault-in: a get of a spilled version serves the CORRECT bytes
        # through the normal get path (no new API, no repair).
        v = spilled_versions[0]
        sd = await ts.get_state_dict(f"cap/v{v}", store_name="tier1")
        _assert_version(sd, v)
        # The next sweep reports the promotions and the catalog flips the
        # faulted keys back to resident.
        await ts.tier_sweep("tier1")
        catalog = await ts.version_catalog("cap", store_name="tier1")
        assert catalog["cap"][v]["spilled_keys"] == 0
        # Disk-tier traffic is its own matrix section, never a wire edge.
        matrix = await ts.traffic_matrix("tier1")
        assert matrix["disk"][vid]["spill_bytes"] > 0
        assert matrix["disk"][vid]["fault_in_bytes"] > 0
        await client.lease_release(lease["lease_id"])
    finally:
        await ts.shutdown("tier1")


async def test_delete_guard_and_lease_aware_gc(tiered_store):
    await ts.initialize(store_name="tier2")
    try:
        client = ts.client("tier2")
        pub = ts.WeightPublisher("gc", store_name="tier2", keep=2)
        for v in range(3):
            await pub.publish(_sd(v))
        # Pin v1 (still retained under keep=2), then advance LATEST far
        # enough that an unleased v1 would have been GC'd.
        lease = await client.lease_acquire("eval", "gc", 1, ttl_s=120)
        for v in range(3, 6):
            await pub.publish(_sd(v))
        sd, version = await ts.WeightSubscriber(
            "gc", store_name="tier2", cohort="eval"
        ).acquire(version=1)
        assert version == 1
        _assert_version(sd, 1)
        # Unleased old versions were reaped as usual.
        assert await client.keys("gc/v0") == []
        assert await client.keys("gc/v2") == []
        # A raw delete against the leased version is refused at the
        # controller (the hard guard, independent of the GC's courtesy).
        await client.delete_prefix("gc/v1")
        assert len(await client.keys("gc/v1")) == N_KEYS + 1
        # Released -> the next publish's GC reaps it.
        await client.lease_release(lease["lease_id"])
        await pub.publish(_sd(6))
        assert await client.keys("gc/v1") == []
        with pytest.raises(KeyError, match="does not retain"):
            await ts.WeightSubscriber("gc", store_name="tier2").acquire(
                version=1
            )
    finally:
        await ts.shutdown("tier2")


async def test_pinned_streamed_acquire(tiered_store):
    await ts.initialize(store_name="tier3")
    try:
        pub = ts.WeightPublisher("st", store_name="tier3", keep=10)
        for v in range(2):
            cs = pub.stream()
            for key, arr in _sd(v).items():
                await cs.put({key: arr})
            assert await cs.seal() == v
        client = ts.client("tier3")
        lease = await client.lease_acquire("replay", "st", 0, ttl_s=120)
        await ts.tier_sweep("tier3")
        served = []
        sub = ts.WeightSubscriber("st", store_name="tier3", cohort="replay")
        sd, version = await sub.acquire_streamed(
            version=0,
            key_order=[f"w{i}" for i in range(N_KEYS)],
            on_layer=lambda fk, val: served.append(fk),
            timeout=30,
        )
        assert version == 0
        _assert_version(sd, 0)
        assert served == [f"w{i}" for i in range(N_KEYS)]
        # The read-scoped lease released; only the explicit pin remains.
        catalog = await ts.version_catalog("st", store_name="tier3")
        assert [le["cohort"] for le in catalog["st"][0]["leases"]] == [
            "replay"
        ]
        await client.lease_release(lease["lease_id"])
    finally:
        await ts.shutdown("tier3")


async def test_expired_lease_unpins(tiered_store):
    await ts.initialize(store_name="tier4")
    try:
        client = ts.client("tier4")
        pub = ts.WeightPublisher("ttl", store_name="tier4", keep=2)
        for v in range(3):
            await pub.publish(_sd(v))
        await client.lease_acquire("flaky", "ttl", 1, ttl_s=0.2)
        await asyncio.sleep(0.3)
        # The pin lapsed: the next publish's GC reaps v1 (cutoff = 2).
        await pub.publish(_sd(3))
        await pub.publish(_sd(4))
        assert await client.keys("ttl/v1") == []
        catalog = await ts.version_catalog("ttl", store_name="tier4")
        assert 1 not in catalog.get("ttl", {})
    finally:
        await ts.shutdown("tier4")


async def test_failed_spill_leaves_entry_resident(tiered_store):
    """A spill aborted mid-write (volume.spill raise) must leave the entry
    fully resident and served — no half-demoted state, no spill record."""
    await ts.initialize(store_name="tier5")
    try:
        pub = ts.WeightPublisher("ab", store_name="tier5", keep=10)
        for v in range(4):
            await pub.publish(_sd(v))
        # Every spill attempt this sweep raises at the faultpoint.
        await ts.inject_fault(
            "volume.spill", "raise", count=100, scope="volumes",
            store_name="tier5",
        )
        report = await ts.tier_sweep("tier5")
        (vid,) = report
        assert report[vid]["spilled"] == 0
        assert report[vid]["spilled_keys"] == 0
        await ts.clear_faults(store_name="tier5")
        for v in range(4):
            sd = await ts.get_state_dict(f"ab/v{v}", store_name="tier5")
            _assert_version(sd, v)
        # With the fault cleared the policy proceeds normally.
        report = await ts.tier_sweep("tier5")
        assert report[vid]["spilled"] > 0
    finally:
        await ts.clear_faults(store_name="tier5")
        await ts.shutdown("tier5")


async def test_overwrite_discards_stale_disk_copy(tiered_store):
    """Re-publishing a spilled key lands fresh resident bytes and drops
    the stale disk copy — a later sweep+get must serve the NEW bytes."""
    await ts.initialize(store_name="tier6")
    try:
        client = ts.client("tier6")
        items = {
            f"ow/v0/w{i}": np.full(N_ELEM, 1.0, np.float32)
            for i in range(N_KEYS)
        }
        await ts.put_batch(items, store_name="tier6")
        # Fill well past the watermark with other versions, then spill.
        for v in range(1, 4):
            await ts.put_batch(
                {
                    f"ow/v{v}/w{i}": np.full(N_ELEM, float(v + 1), np.float32)
                    for i in range(N_KEYS)
                },
                store_name="tier6",
            )
        await client.tier_sweep()
        catalog = await ts.version_catalog("ow", store_name="tier6")
        assert catalog["ow"][0]["spilled_keys"] == catalog["ow"][0]["keys"]
        # Overwrite the spilled version with fresh bytes.
        await ts.put_batch(
            {k: np.full(N_ELEM, 9.0, np.float32) for k in items},
            store_name="tier6",
        )
        out = await ts.get("ow/v0/w0", store_name="tier6")
        assert float(np.asarray(out)[0]) == 9.0
        # Spill + fault back in: still the fresh bytes, never the stale
        # disk copy.
        await client.tier_sweep()
        out = await ts.get("ow/v0/w1", store_name="tier6")
        assert float(np.asarray(out)[0]) == 9.0
    finally:
        await ts.shutdown("tier6")


async def test_shared_cohort_pinned_reads_hold_independent_leases(
    tiered_store,
):
    """Two fleet members sharing a NAMED cohort (the documented fleet
    pattern) must hold independent read-scoped leases: same cohort, same
    (channel, version), same read ordinal must NOT coalesce into one
    lease the first finisher's release drops under the other's
    mid-flight read."""
    await ts.initialize(store_name="tier8")
    try:
        client = ts.client("tier8")
        pub = ts.WeightPublisher("fleet", store_name="tier8", keep=10)
        for v in range(2):
            await pub.publish(_sd(v))
        a = ts.WeightSubscriber(
            "fleet", store_name="tier8", cohort="eval-fleet-2"
        )
        b = ts.WeightSubscriber(
            "fleet", store_name="tier8", cohort="eval-fleet-2"
        )
        lease_a = await a._pinned_lease(client, 1)
        lease_b = await b._pinned_lease(client, 1)
        assert lease_a["lease_id"] != lease_b["lease_id"]
        assert not lease_a["renewed"] and not lease_b["renewed"]
        # The first finisher's release leaves the other's pin live, and
        # the owner keeps the cohort prefix for catalog attribution.
        await client.lease_release(lease_a["lease_id"])
        catalog = await ts.version_catalog("fleet", store_name="tier8")
        owners = [le["cohort"] for le in catalog["fleet"][1]["leases"]]
        assert len(owners) == 1 and owners[0].startswith("eval-fleet-2:")
        await client.lease_release(lease_b["lease_id"])
        # End to end: concurrent same-cohort pinned reads both succeed
        # and leak no leases.
        for sd, version in await asyncio.gather(
            a.acquire(version=1), b.acquire(version=1)
        ):
            assert version == 1
            _assert_version(sd, 1)
        catalog = await ts.version_catalog("fleet", store_name="tier8")
        assert catalog["fleet"][1]["leases"] == []
    finally:
        await ts.shutdown("tier8")


async def test_resumed_publisher_skips_leased_survivor(tiered_store):
    """A leased version beyond the committed pointer survives partial
    reclaim — and the resumed publisher's numbering must skip PAST it,
    never publishing fresh keys into the survivor's directory (where
    they would mix with its stale keys into a two-generation dict)."""
    await ts.initialize(store_name="tier9")
    try:
        client = ts.client("tier9")
        pub = ts.WeightPublisher("res", store_name="tier9", keep=10)
        for v in range(3):
            await pub.publish(_sd(v))  # LATEST = 2
        # A crashed publisher's un-sealed stream left keys at v5, pinned
        # by a canary cohort before the crash.
        await ts.put_batch(
            {
                f"res/v5/w{i}": np.full(N_ELEM, 5.0, np.float32)
                for i in range(N_KEYS)
            },
            store_name="tier9",
        )
        lease = await client.lease_acquire("canary", "res", 5, ttl_s=120)
        pub2 = ts.WeightPublisher("res", store_name="tier9", keep=10)
        assert await pub2.publish(_sd(6)) == 6  # past the survivor
        assert len(await client.keys("res/v5")) == N_KEYS
        survivor = await ts.get("res/v5/w0", store_name="tier9")
        assert float(np.asarray(survivor)[0]) == 5.0
        await client.lease_release(lease["lease_id"])
        # With the lease gone the skipped partial is NOT a leak: numbering
        # moved past it, so a later publish's GC cutoff reaps it.
        for v in range(7, 7 + 10):
            await pub2.publish(_sd(v))
        assert await client.keys("res/v5") == []
    finally:
        await ts.shutdown("tier9")


async def test_resume_jump_keeps_gc_window(tiered_store):
    """The GC retention window counts EXISTING versions: a publisher that
    resumed past a leased survivor (numbering gap) must not let its first
    publish's GC leap across the gap and reap the previous LATEST out
    from under a mid-pull subscriber."""
    await ts.initialize(store_name="tier13")
    try:
        client = ts.client("tier13")
        pub = ts.WeightPublisher("gap", store_name="tier13", keep=2)
        for v in range(3):
            await pub.publish(_sd(v))  # LATEST = 2, v1+v2 retained
        # A crashed publisher's partial far beyond the pointer, leased.
        await ts.put_batch(
            {
                f"gap/v6/w{i}": np.full(N_ELEM, 6.0, np.float32)
                for i in range(N_KEYS)
            },
            store_name="tier13",
        )
        lease = await client.lease_acquire("canary", "gap", 6, ttl_s=120)
        pub2 = ts.WeightPublisher("gap", store_name="tier13", keep=2)
        assert await pub2.publish(_sd(7)) == 7
        # keep=2 of the EXISTING window {1, 2, 7}: v2 (the previous
        # LATEST a subscriber may still be pulling) survives; a numeric
        # cutoff (7 - 2 = 5) would have reaped it.
        assert len(await client.keys("gap/v2")) == N_KEYS + 1
        assert await client.keys("gap/v1") == []
        sd = await ts.get_state_dict("gap/v2", store_name="tier13")
        _assert_version(sd, 2)
        # The next publish rolls the window forward as usual.
        assert await pub2.publish(_sd(8)) == 8
        assert await client.keys("gap/v2") == []
        await client.lease_release(lease["lease_id"])
    finally:
        await ts.shutdown("tier13")


async def test_guard_refused_reclaim_still_advances_numbering(
    tiered_store,
):
    """A lease-plane hiccup (lease_list failing) must not let a resumed
    publisher publish into a guard-retained version: survivors are also
    derived from keys still present after the refused delete."""
    await ts.initialize(store_name="tier14")
    try:
        client = ts.client("tier14")
        pub = ts.WeightPublisher("hic", store_name="tier14", keep=10)
        for v in range(3):
            await pub.publish(_sd(v))  # LATEST = 2
        await ts.put_batch(
            {
                f"hic/v5/w{i}": np.full(N_ELEM, 5.0, np.float32)
                for i in range(N_KEYS)
            },
            store_name="tier14",
        )
        lease = await client.lease_acquire("canary", "hic", 5, ttl_s=120)

        async def broken_lease_list(channel=None):
            raise RuntimeError("lease plane unavailable")

        real_lease_list = client.lease_list
        client.lease_list = broken_lease_list
        try:
            pub2 = ts.WeightPublisher("hic", store_name="tier14", keep=10)
            # The reclaim's delete of v5 is refused by the controller's
            # lease guard; numbering must still skip past the survivor.
            assert await pub2.publish(_sd(6)) == 6
        finally:
            client.lease_list = real_lease_list
        assert len(await client.keys("hic/v5")) == N_KEYS
        survivor = await ts.get("hic/v5/w0", store_name="tier14")
        assert float(np.asarray(survivor)[0]) == 5.0
        await client.lease_release(lease["lease_id"])
    finally:
        await ts.shutdown("tier14")


async def test_recreated_channel_numbering_skips_leased_survivor(
    tiered_store,
):
    """close(delete=True) leaves leased versions behind; the recreated
    channel's fresh-epoch numbering (restarting at 0) must skip past
    them instead of eventually publishing into the retained directory."""
    await ts.initialize(store_name="tier10")
    try:
        client = ts.client("tier10")
        pub = ts.WeightPublisher("re", store_name="tier10", keep=10)
        for v in range(3):
            await pub.publish(_sd(v))
        lease = await client.lease_acquire("replay", "re", 1, ttl_s=120)
        await pub.close(delete=True)
        assert len(await client.keys("re/v1")) == N_KEYS + 1  # survived
        pub2 = ts.WeightPublisher("re", store_name="tier10", keep=10)
        assert await pub2.publish(_sd(9)) == 2  # fresh epoch, past v1
        sd, version = await ts.WeightSubscriber(
            "re", store_name="tier10", cohort="replay"
        ).acquire(version=1)
        assert version == 1
        _assert_version(sd, 1)
        await client.lease_release(lease["lease_id"])
    finally:
        await ts.shutdown("tier10")


async def test_pinned_acquire_timeout_enforced(tiered_store, monkeypatch):
    """acquire(version=..., timeout=...) bounds the pull itself — and a
    timed-out pinned read releases its lease on the way out."""
    from torchstore_tpu import state_dict_utils

    await ts.initialize(store_name="tier11")
    try:
        pub = ts.WeightPublisher("to", store_name="tier11", keep=10)
        await pub.publish(_sd(0))
        real = state_dict_utils.get_state_dict

        async def slow_get(*args, **kwargs):
            await asyncio.sleep(5.0)
            return await real(*args, **kwargs)

        monkeypatch.setattr(state_dict_utils, "get_state_dict", slow_get)
        sub = ts.WeightSubscriber("to", store_name="tier11")
        with pytest.raises(TimeoutError):
            await sub.acquire(version=0, timeout=0.2)
        catalog = await ts.version_catalog("to", store_name="tier11")
        assert catalog["to"][0]["leases"] == []
    finally:
        await ts.shutdown("tier11")


async def test_pinned_read_outlives_lease_ttl(tiered_store, monkeypatch):
    """A pull longer than the lease TTL stays protected: the read-scoped
    lease is heartbeat-renewed, so GC under publish pressure cannot reap
    the pinned version mid-read."""
    from torchstore_tpu import state_dict_utils

    monkeypatch.setenv("TORCHSTORE_TPU_LEASE_TTL_S", "0.3")
    await ts.initialize(store_name="tier12")
    try:
        pub = ts.WeightPublisher("slow", store_name="tier12", keep=1)
        await pub.publish(_sd(0))
        real = state_dict_utils.get_state_dict

        async def slow_get(*args, **kwargs):
            # 3x the TTL: without renewal the lease lapses mid-read and
            # the publishes below reap v0 under the pull.
            await asyncio.sleep(0.9)
            return await real(*args, **kwargs)

        monkeypatch.setattr(state_dict_utils, "get_state_dict", slow_get)
        sub = ts.WeightSubscriber(
            "slow", store_name="tier12", cohort="reader"
        )
        read = asyncio.ensure_future(sub.acquire(version=0))
        # keep=1 makes v0 GC-eligible the moment its lease lapses.
        for v in range(1, 4):
            await asyncio.sleep(0.2)
            await pub.publish(_sd(v))
        sd, version = await read
        assert version == 0
        _assert_version(sd, 0)
    finally:
        await ts.shutdown("tier12")


async def test_tier_disabled_is_inert():
    """Without TORCHSTORE_TPU_TIER_ENABLED nothing spills, sweeps report
    disabled, and the new surface stays queryable (empty catalog tiers)."""
    await ts.initialize(store_name="tier7")
    try:
        pub = ts.WeightPublisher("off", store_name="tier7", keep=10)
        for v in range(3):
            await pub.publish(_sd(v))
        report = await ts.tier_sweep("tier7")
        assert all(rep.get("enabled") is False for rep in report.values())
        catalog = await ts.version_catalog("off", store_name="tier7")
        assert all(
            rec["spilled_keys"] == 0 for rec in catalog["off"].values()
        )
        sd, version = await ts.WeightSubscriber(
            "off", store_name="tier7"
        ).acquire(version=1)
        assert version == 1
        _assert_version(sd, 1)
    finally:
        await ts.shutdown("tier7")
