"""state_dict layer tests: flatten/unflatten fidelity, commit-marker
protocol, dtype cast, in-place + resharded fetches, flax/optax round trips
(reference tests/test_state_dict.py; oracle here = the dense source dict)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import sharding as shd
from torchstore_tpu.state_dict_utils import (
    NoMatchingPush,
    cast_floating_tensors,
    flatten_state_dict,
    unflatten_state_dict,
)

jax = pytest.importorskip("jax")
import flax.linen as nn  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


class TestFlatten:
    def test_nested_roundtrip(self):
        sd = {
            "model": {"layer1": {"w": np.ones((2, 2)), "b": np.zeros(2)}},
            "step": 7,
            "lists": [np.ones(1), {"deep": np.zeros(1)}],
            "tup": (1, 2),
        }
        flat, mapping = flatten_state_dict(sd)
        assert "model/layer1/w" in flat and "lists/1/deep" in flat
        out = unflatten_state_dict(flat, mapping)
        assert out["step"] == 7
        assert isinstance(out["lists"], list) and isinstance(out["tup"], tuple)
        np.testing.assert_array_equal(out["model"]["layer1"]["w"], np.ones((2, 2)))

    def test_int_keys_preserved(self):
        sd = {"layers": {0: np.ones(1), 1: np.zeros(1)}}
        flat, mapping = flatten_state_dict(sd)
        out = unflatten_state_dict(flat, mapping)
        assert set(out["layers"].keys()) == {0, 1}

    def test_namedtuple_roundtrip(self):
        state = optax.ScaleByAdamState(
            count=np.zeros((), np.int32), mu={"w": np.ones(2)}, nu={"w": np.ones(2)}
        )
        flat, mapping = flatten_state_dict({"opt": state})
        out = unflatten_state_dict(flat, mapping)
        assert isinstance(out["opt"], optax.ScaleByAdamState)
        np.testing.assert_array_equal(out["opt"].mu["w"], np.ones(2))

    def test_cast_floating_only(self):
        flat = {"w": np.ones(2, np.float32), "step": np.array(3, np.int32), "s": "x"}
        out = cast_floating_tensors(flat, np.float16)
        assert out["w"].dtype == np.float16
        assert out["step"].dtype == np.int32
        assert out["s"] == "x"


@pytest.fixture
async def store():
    await ts.initialize(store_name="sd")
    yield "sd"
    await ts.shutdown("sd")


async def test_roundtrip_plain(store):
    sd = {
        "w1": np.random.rand(4, 4).astype(np.float32),
        "meta": {"epoch": 3, "name": "run1"},
        "nested": {"b": np.arange(5.0)},
    }
    await ts.put_state_dict("v0", sd, store_name=store)
    out = await ts.get_state_dict("v0", store_name=store)
    np.testing.assert_array_equal(out["w1"], sd["w1"])
    assert out["meta"] == {"epoch": 3, "name": "run1"}
    np.testing.assert_array_equal(out["nested"]["b"], np.arange(5.0))


async def test_commit_marker_required(store):
    # Entries without the MAPPING marker are invisible to get_state_dict.
    await ts.put("v1/w", np.ones(2), store_name=store)
    with pytest.raises(NoMatchingPush, match="no matching push"):
        await ts.get_state_dict("v1", store_name=store)


async def test_inplace_user_dict(store):
    sd = {"a": np.arange(6.0).reshape(2, 3), "b": {"c": np.ones(4)}}
    await ts.put_state_dict("v2", sd, store_name=store)
    user = {"a": np.zeros((2, 3)), "b": {"c": np.zeros(4)}}
    out = await ts.get_state_dict("v2", user_state_dict=user, store_name=store)
    np.testing.assert_array_equal(out["a"], sd["a"])
    # numpy targets are filled in place
    np.testing.assert_array_equal(user["a"], sd["a"])


async def test_structure_mismatch_strict(store):
    await ts.put_state_dict("v3", {"a": np.ones(2), "b": np.ones(2)}, store_name=store)
    # Unknown keys always rejected.
    with pytest.raises(ValueError, match="not present in push"):
        await ts.get_state_dict(
            "v3", user_state_dict={"a": np.zeros(2), "extra": np.zeros(1)},
            store_name=store,
        )
    # Missing keys rejected only in strict mode.
    with pytest.raises(ValueError, match="structure mismatch"):
        await ts.get_state_dict(
            "v3", user_state_dict={"a": np.zeros(2)}, store_name=store
        )


async def test_transfer_dtype_cast(store):
    import ml_dtypes

    sd = {"w": np.random.rand(8, 8).astype(np.float32), "step": np.array(1)}
    await ts.put_state_dict("v4", sd, transfer_dtype=ml_dtypes.bfloat16, store_name=store)
    out = await ts.get_state_dict("v4", store_name=store)
    assert out["w"].dtype == ml_dtypes.bfloat16
    assert out["step"].dtype == sd["step"].dtype
    np.testing.assert_allclose(
        out["w"].astype(np.float32), sd["w"], atol=1e-2
    )


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(8)(x)


async def test_flax_params_and_optax_state_roundtrip(store):
    model = MLP()
    params = model.init(jax.random.key(0), jnp.ones((1, 16)))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    await ts.put_state_dict("ckpt", {"params": params, "opt": opt_state}, store_name=store)
    out = await ts.get_state_dict("ckpt", store_name=store)
    # Model still runs with restored params.
    restored = jax.tree.map(jnp.asarray, out["params"])
    y0 = model.apply(params, jnp.ones((2, 16)))
    y1 = model.apply(restored, jnp.ones((2, 16)))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1), rtol=1e-6)


async def test_sharded_state_dict_reshard_on_get(store):
    # The RL weight-sync core: trainer publishes 8-way sharded params,
    # generator pulls them 2x4-sharded.
    devs = np.array(jax.devices())
    mesh_src = Mesh(devs.reshape(8), ("fsdp",))
    mesh_dst = Mesh(devs.reshape(2, 4), ("dp", "tp"))
    w = np.random.rand(16, 32).astype(np.float32)
    b = np.random.rand(32).astype(np.float32)
    sd = {
        "w": jax.device_put(w, NamedSharding(mesh_src, P("fsdp", None))),
        "b": jax.device_put(b, NamedSharding(mesh_src, P())),
    }
    await ts.put_state_dict("weights", sd, store_name=store)
    user = {
        "w": jax.device_put(np.zeros_like(w), NamedSharding(mesh_dst, P(None, "tp"))),
        "b": jax.device_put(np.zeros_like(b), NamedSharding(mesh_dst, P())),
    }
    out = await ts.get_state_dict("weights", user_state_dict=user, store_name=store)
    assert out["w"].sharding.spec == P(None, "tp")
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    np.testing.assert_array_equal(np.asarray(out["b"]), b)


async def test_versioned_checkpoints_coexist(store):
    await ts.put_state_dict("v0", {"w": np.zeros(2)}, store_name=store)
    await ts.put_state_dict("v1", {"w": np.ones(2)}, store_name=store)
    out0 = await ts.get_state_dict("v0", store_name=store)
    out1 = await ts.get_state_dict("v1", store_name=store)
    np.testing.assert_array_equal(out0["w"], np.zeros(2))
    np.testing.assert_array_equal(out1["w"], np.ones(2))


async def test_partial_pull_with_strict_false(store):
    sd = {"lm_head": np.random.rand(8, 4).astype(np.float32),
          "layers": {"0": np.ones(4), "1": np.ones(4)}}
    await ts.put_state_dict("big", sd, store_name=store)
    # Pull just the head.
    out = await ts.get_state_dict(
        "big", user_state_dict={"lm_head": np.zeros((8, 4), np.float32)},
        strict=False, store_name=store,
    )
    np.testing.assert_array_equal(out["lm_head"], sd["lm_head"])
    assert "layers" not in out
    # Unknown keys still rejected even when non-strict.
    with pytest.raises(ValueError, match="not present in push"):
        await ts.get_state_dict(
            "big", user_state_dict={"typo": np.zeros(2)}, strict=False,
            store_name=store,
        )


async def test_plain_shape_dtype_struct_targets():
    """Sharding-less ShapeDtypeStructs are first-class fetch targets on both
    the buffered and direct paths (default-placed device arrays out)."""
    import jax
    import jax.numpy as jnp

    await ts.initialize(store_name="sds")
    try:
        sd = {"w": np.arange(32.0, dtype=np.float32)}
        await ts.put_state_dict("m", sd, store_name="sds")
        target = {"w": jax.ShapeDtypeStruct((32,), jnp.bfloat16)}
        out = await ts.get_state_dict("m", user_state_dict=target, store_name="sds")
        assert hasattr(out["w"], "sharding")  # a device array
        assert out["w"].dtype == jnp.bfloat16  # spec dtype honored
        np.testing.assert_allclose(
            np.asarray(out["w"], dtype=np.float32), sd["w"], rtol=1e-2
        )
        # direct path (host sources -> host pull -> device placement)
        await ts.put_state_dict("d", sd, direct=True, store_name="sds")
        out2 = await ts.get_state_dict(
            "d", user_state_dict={"w": jax.ShapeDtypeStruct((32,), jnp.float32)},
            direct=True, store_name="sds",
        )
        assert hasattr(out2["w"], "sharding")
        np.testing.assert_array_equal(np.asarray(out2["w"]), sd["w"])
        # bare ts.get with a plain spec
        await ts.put("solo", sd["w"], store_name="sds")
        out3 = await ts.get(
            "solo", like=jax.ShapeDtypeStruct((32,), jnp.float32), store_name="sds"
        )
        assert hasattr(out3, "sharding")
        np.testing.assert_array_equal(np.asarray(out3), sd["w"])
    finally:
        await ts.shutdown("sds")


class TestBoxedParamTrees:
    """Trees straight out of model.init with nn.with_logical_partitioning
    carry flax AxisMetadata boxes; flatten must unbox (arrays take the
    tensor path) and unflatten must restore the exact boxed structure.
    Regression: boxed leaves used to ride the object path whole — pickled
    device arrays materialized inside storage volumes (which on a TPU host
    initializes the backend there and wedges the volume)."""

    def test_flatten_unboxes_and_restores(self):
        jax = pytest.importorskip("jax")
        import flax.linen as nn
        import jax.numpy as jnp

        boxed = nn.with_logical_partitioning(
            lambda: jnp.arange(8.0), ("embed",)
        )()
        sd = {"layer": {"w": boxed, "plain": np.ones(3, np.float32)}}
        flat, mapping = flatten_state_dict(sd)
        assert shd.is_jax_array(flat["layer/w"])  # unboxed to the array
        rebuilt = unflatten_state_dict(flat, mapping)
        from flax.core import meta as flax_meta

        out = rebuilt["layer"]["w"]
        assert isinstance(out, flax_meta.AxisMetadata)
        assert out.names == boxed.names
        np.testing.assert_array_equal(np.asarray(out.unbox()), np.arange(8.0))

    async def test_boxed_tree_store_roundtrip(self):
        jax = pytest.importorskip("jax")
        import flax.linen as nn
        import jax.numpy as jnp

        import torchstore_tpu as ts

        await ts.initialize(store_name="boxed")
        try:
            boxed = nn.with_logical_partitioning(
                lambda: jnp.arange(16.0).reshape(4, 4), ("a", "b")
            )()
            sd = {"params": {"w": boxed}}
            await ts.put_state_dict("m", sd, store_name="boxed")
            out = await ts.get_state_dict("m", store_name="boxed")
            got = out["params"]["w"]
            from flax.core import meta as flax_meta

            assert isinstance(got, flax_meta.AxisMetadata)
            assert got.names == ("a", "b")
            np.testing.assert_array_equal(
                np.asarray(got.unbox()), np.arange(16.0).reshape(4, 4)
            )
        finally:
            await ts.shutdown("boxed")


class TestOpaqueObjectEnvelope:
    """Object values are pickled in the CLIENT and carried opaque: volumes
    never materialize user types (no foreign imports / backend init in
    storage processes)."""

    def test_client_wraps_objects(self):
        from torchstore_tpu.client import LocalClient
        from torchstore_tpu.transport.types import OpaqueBlob

        (req,) = LocalClient._value_to_requests("k", {"arbitrary": "dict"})
        assert req.is_object and isinstance(req.objects, OpaqueBlob)
        assert req.objects.unwrap() == {"arbitrary": "dict"}
        (req2,) = LocalClient._value_to_requests("k", 7)
        assert isinstance(req2.objects, OpaqueBlob) and req2.objects.unwrap() == 7

    async def test_object_roundtrip_through_store(self):
        import torchstore_tpu as ts

        await ts.initialize(store_name="opq")
        try:
            payload = {"nested": [1, 2, {"x": "y"}], "t": (3, 4)}
            await ts.put("obj", payload, store_name="opq")
            out = await ts.get("obj", store_name="opq")
            assert out == payload
        finally:
            await ts.shutdown("opq")
