"""Layer-streamed weight sync (ISSUE 9): publish/acquire as a pipeline.

Covers the satellite checklist: out-of-order layer publish with in-order
delivery, a subscriber joining mid-stream seeing only the previous SEALED
version, a publisher crash mid-stream leaving the previous version
acquirable (and GC reclaiming the partial), the per-subscriber lag gauge
moving during a stream — plus mixed-generation protection under racing
publishes, the direct-path key order, the doorbell-striping leg, and the
llama train→publish→decode driver (decode tokens identical to the barrier
path while layers stream in forward order)."""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.observability import metrics as obs_metrics


def _counter(name: str, **labels) -> float:
    snap = obs_metrics.metrics_snapshot()
    return sum(
        s["value"]
        for s in snap.get(name, {}).get("series", [])
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


def _gauge(name: str) -> float:
    snap = obs_metrics.metrics_snapshot()
    series = snap.get(name, {}).get("series", [])
    return series[0]["value"] if series else 0.0


# --------------------------------------------------------------------------
# core protocol: out-of-order publish, in-order delivery, consistency
# --------------------------------------------------------------------------


async def test_out_of_order_publish_in_order_delivery():
    """Layers published 1,0,3,2 are DELIVERED 0,1,2,3 under key_order —
    each the moment its watermark (and its predecessors') lands — with the
    consumer starting before the publisher's first layer, and the barrier
    reader untouched (wakes only on the sealed, complete dict)."""
    await ts.initialize(store_name="ss_order")
    try:
        order = [f"layers/{i}/w" for i in range(4)]
        events: list[str] = []
        consumer = asyncio.ensure_future(
            ts.get_state_dict_streamed(
                "m/sd",
                key_order=order,
                on_layer=lambda fk, v: events.append(fk),
                wait_for_stream_s=30,
                timeout=60,
                store_name="ss_order",
            )
        )
        await asyncio.sleep(0.05)
        stream = ts.state_dict_stream("m/sd", store_name="ss_order")
        for i in (1, 0, 3, 2):  # out-of-order arrival
            await stream.put(
                {"layers": {str(i): {"w": np.full(64, float(i), np.float32)}}}
            )
            await asyncio.sleep(0.01)
        version = await stream.seal()
        assert version == 1
        sd = await consumer
        assert events == order, events
        for i in range(4):
            assert sd["layers"][str(i)]["w"][0] == float(i)
        # Barrier path serves the sealed dict exactly as before.
        sd2 = await ts.get_state_dict("m/sd", store_name="ss_order")
        assert sd2["layers"]["3"]["w"][0] == 3.0
        assert _counter("ts_stream_acquires_total") >= 1
    finally:
        await ts.shutdown("ss_order")


async def test_streamed_get_with_in_place_destinations():
    """get_state_dict(stream=True) with a user dict lands layers in place
    (numpy destinations) and validates structure strictly."""
    await ts.initialize(store_name="ss_dest")
    try:
        stream = ts.state_dict_stream("d/sd", store_name="ss_dest")
        src = {f"w{i}": np.full(128, float(i) + 1, np.float32) for i in range(3)}
        for k, v in src.items():
            await stream.put({k: v})
        await stream.seal()
        user = {k: np.zeros(128, np.float32) for k in src}
        out = await ts.get_state_dict(
            "d/sd", user_state_dict=user, stream=True, store_name="ss_dest"
        )
        for k, v in src.items():
            assert out[k] is user[k]  # in-place landing
            np.testing.assert_array_equal(user[k], v)
        # Strict structure check still fires.
        with pytest.raises(ValueError, match="not present"):
            await ts.get_state_dict(
                "d/sd",
                user_state_dict={**user, "extra": np.zeros(4, np.float32)},
                stream=True,
                store_name="ss_dest",
            )
    finally:
        await ts.shutdown("ss_dest")


async def test_superseded_stream_restarts_to_newest_consistent():
    """A faster publisher overwriting the same key mid-acquire: the
    consumer restarts LOUDLY (ts_stream_fallbacks_total) and returns the
    newest version's dict — never a mix of generations."""
    await ts.initialize(store_name="ss_race")
    try:
        keys = [f"w{i}" for i in range(3)]
        served_first = asyncio.Event()
        resume = asyncio.Event()

        async def on_layer(fk, v):
            served_first.set()
            await resume.wait()

        stream1 = ts.state_dict_stream("r/sd", store_name="ss_race")
        await stream1.put({keys[0]: np.full(64, 10.0, np.float32)})
        consumer = asyncio.ensure_future(
            ts.get_state_dict_streamed(
                "r/sd",
                on_layer=on_layer,
                timeout=60,
                store_name="ss_race",
            )
        )
        await asyncio.wait_for(served_first.wait(), 30)
        # Supersede: a second stream republishes EVERY key and seals while
        # the consumer is still blocked inside layer 0 of stream 1.
        stream2 = ts.state_dict_stream("r/sd", store_name="ss_race")
        for k in keys:
            await stream2.put({k: np.full(64, 20.0, np.float32)})
        await stream2.seal()
        fb0 = _counter("ts_stream_fallbacks_total", reason="superseded")
        resume.set()
        sd = await consumer
        for k in keys:
            vals = np.unique(np.asarray(sd[k]))
            assert vals.size == 1 and vals[0] == 20.0, (k, vals)
        assert (
            _counter("ts_stream_fallbacks_total", reason="superseded")
            > fb0
            or _counter("ts_stream_fallbacks_total", reason="mixed_generation")
            > 0
        )
    finally:
        await ts.shutdown("ss_race")


async def test_lag_gauge_moves_during_stream():
    """ts_stream_lag_keys: watermarked-but-unserved keys of the in-flight
    acquire — nonzero while the subscriber trails the publisher, 0 after."""
    await ts.initialize(store_name="ss_lag")
    try:
        stream = ts.state_dict_stream("l/sd", store_name="ss_lag")
        for i in range(4):
            await stream.put({f"w{i}": np.full(64, float(i), np.float32)})
        await stream.seal()
        observed: list[float] = []

        async def on_layer(fk, v):
            observed.append(_gauge("ts_stream_lag_keys"))

        await ts.get_state_dict_streamed(
            "l/sd", on_layer=on_layer, timeout=60, store_name="ss_lag"
        )
        # All four keys were ready before the first serve: the lag gauge
        # read 4 - served_so_far during the wave (nonzero mid-stream).
        assert len(observed) == 4
        assert _gauge("ts_stream_lag_keys") == 0
    finally:
        await ts.shutdown("ss_lag")


async def test_barrier_republish_over_streamed_key_falls_back():
    """A BARRIER put_state_dict over a previously streamed key leaves a
    stale stream record behind (barrier notifies never touch it): the
    streamed get must serve the barrier dict via the marker-drift
    fallback, not burn its retries into MixedGenerationError."""
    await ts.initialize(store_name="ss_drift")
    try:
        stream = ts.state_dict_stream("b/sd", store_name="ss_drift")
        await stream.put({"w": np.full(32, 1.0, np.float32)})
        await stream.seal()
        await ts.put_state_dict(
            "b/sd", {"w": np.full(32, 2.0, np.float32)}, store_name="ss_drift"
        )
        fb0 = _counter("ts_stream_fallbacks_total", reason="marker_drift")
        out = await ts.get_state_dict("b/sd", stream=True, store_name="ss_drift")
        assert np.asarray(out["w"])[0] == 2.0  # the barrier dict, served
        assert _counter("ts_stream_fallbacks_total", reason="marker_drift") > fb0
    finally:
        await ts.shutdown("ss_drift")


async def test_record_cap_evicts_sealed_not_live_streams():
    """256 one-shot sealed streams must not evict a hot channel's LIVE
    (unsealed) record: eviction prefers sealed records and touch order."""
    await ts.initialize(store_name="ss_cap")
    try:
        client = ts.client("ss_cap")
        live = await client.stream_begin("hot/sd")  # in flight, never sealed
        for i in range(300):  # > MAX_STREAMS one-shot sealed records
            key = f"cold/{i}"
            await client.stream_begin(key)
            await client.stream_seal(key, 1)
        state = await client.stream_state("hot/sd")
        assert state is not None and state["version"] == live
    finally:
        await ts.shutdown("ss_cap")


async def test_phantom_key_order_entry_still_completes_in_order():
    """A key_order entry the publisher never pushes blocks in-order
    delivery until the seal (only the seal proves it absent) but the
    acquire still completes, with on_layer in key_order positions."""
    await ts.initialize(store_name="ss_phantom")
    try:
        stream = ts.state_dict_stream("p/sd", store_name="ss_phantom")
        for i in range(3):
            await stream.put({f"w{i}": np.full(32, float(i), np.float32)})
        await stream.seal()
        served: list[str] = []
        out = await ts.get_state_dict_streamed(
            "p/sd",
            key_order=["w0", "phantom", "w2", "w1"],
            on_layer=lambda fk, v: served.append(fk),
            timeout=60,
            store_name="ss_phantom",
        )
        # w0 serves pre-phantom; the rest at seal, still in caller order.
        assert served == ["w0", "w2", "w1"]
        assert all(np.asarray(out[f"w{i}"])[0] == float(i) for i in range(3))
    finally:
        await ts.shutdown("ss_phantom")


async def test_stream_record_retired_with_its_keys():
    """Deleting a streamed state dict (its MAPPING marker rides the
    prefix delete) retires the controller's stream record: a later
    streamed get falls back to the barrier path's loud NoMatchingPush
    instead of chasing stale watermarks into missing bytes (regression:
    an off-by-one in the MAPPING-suffix strip left records alive
    forever, eventually evicting LIVE streams at the record cap)."""
    from torchstore_tpu.state_dict_utils import NoMatchingPush

    await ts.initialize(store_name="ss_retire")
    try:
        stream = ts.state_dict_stream("g/sd", store_name="ss_retire")
        await stream.put({"w": np.ones(32, np.float32)})
        await stream.seal()
        client = ts.client("ss_retire")
        assert await client.stream_state("g/sd") is not None
        removed = await ts.delete_prefix("g/sd", store_name="ss_retire")
        assert removed >= 2  # the layer key and the marker
        assert await client.stream_state("g/sd") is None
        with pytest.raises(NoMatchingPush):
            await ts.get_state_dict(
                "g/sd", stream=True, store_name="ss_retire"
            )
    finally:
        await ts.shutdown("ss_retire")


# --------------------------------------------------------------------------
# weight channel: mid-stream join, crash + partial GC
# --------------------------------------------------------------------------


async def test_mid_stream_join_gets_previous_sealed_version():
    """A barrier subscriber joining while v1 streams (unsealed) gets v0 —
    partial versions are invisible outside the streamed acquire path."""
    await ts.initialize(store_name="ss_join")
    try:
        pub = ts.WeightPublisher("chan", store_name="ss_join", keep=2)
        cs0 = pub.stream()
        for i in range(3):
            await cs0.put({f"w{i}": np.full(64, 0.0, np.float32)})
        assert await cs0.seal() == 0
        # v1 in flight: two of three layers published, NOT sealed.
        cs1 = pub.stream()
        await cs1.put({"w0": np.full(64, 1.0, np.float32)})
        await cs1.put({"w1": np.full(64, 1.0, np.float32)})
        sub = ts.WeightSubscriber("chan", store_name="ss_join")
        sd, version = await sub.acquire(timeout=15)
        assert version == 0
        assert all(np.asarray(sd[f"w{i}"])[0] == 0.0 for i in range(3))
        # Sealing v1 wakes the same subscriber with the complete dict.
        await cs1.put({"w2": np.full(64, 1.0, np.float32)})
        assert await cs1.seal() == 1
        sd, version = await sub.acquire(timeout=15)
        assert version == 1
        assert all(np.asarray(sd[f"w{i}"])[0] == 1.0 for i in range(3))
    finally:
        await ts.shutdown("ss_join")


async def test_publisher_crash_leaves_previous_acquirable_and_gc_reclaims():
    """A publisher dying mid-stream: the previous sealed version stays
    fully acquirable, and the NEXT publisher's resume reclaims the
    partial version's keys before republishing the same version number."""
    await ts.initialize(store_name="ss_crash")
    try:
        pub = ts.WeightPublisher("chan", store_name="ss_crash", keep=2)
        v0 = await pub.publish(
            {f"w{i}": np.full(64, 0.0, np.float32) for i in range(3)}
        )
        assert v0 == 0
        crashed = pub.stream()
        await crashed.put({"w0": np.full(64, 1.0, np.float32)})
        del crashed  # crash: never sealed, never advanced a pointer
        partial = await ts.keys("chan/v1", store_name="ss_crash")
        assert partial, "partial stream left no keys to reclaim?"
        # Previous version still served (barrier AND streamed acquire).
        sub = ts.WeightSubscriber("chan", store_name="ss_crash")
        sd, version = await sub.acquire(timeout=15)
        assert version == 0 and np.asarray(sd["w1"])[0] == 0.0
        # Resumed publisher reclaims the partial, then reuses v1.
        pub2 = ts.WeightPublisher("chan", store_name="ss_crash", keep=2)
        v1 = await pub2.publish(
            {f"w{i}": np.full(64, 5.0, np.float32) for i in range(3)}
        )
        assert v1 == 1
        sd, version = await sub.acquire(timeout=15)
        assert version == 1
        assert all(np.asarray(sd[f"w{i}"])[0] == 5.0 for i in range(3))
    finally:
        await ts.shutdown("ss_crash")


async def test_channel_streamed_acquire_overlaps_publish():
    """acquire_streamed wakes on the in-flight announce and serves layers
    BEFORE the seal: the first on_layer fires while the publisher still
    has layers to push (the overlap the whole PR exists for)."""
    await ts.initialize(store_name="ss_chan")
    try:
        pub = ts.WeightPublisher("chan", store_name="ss_chan", keep=2)
        sub = ts.WeightSubscriber("chan", store_name="ss_chan")
        first_sertwo = asyncio.Event()
        served: list[str] = []

        def on_layer(fk, v):
            served.append(fk)
            first_sertwo.set()

        task = asyncio.ensure_future(
            sub.acquire_streamed(
                key_order=[f"w{i}" for i in range(3)],
                on_layer=on_layer,
                timeout=60,
            )
        )
        await asyncio.sleep(0.05)
        cs = pub.stream()
        await cs.put({"w0": np.full(64, 7.0, np.float32)})
        # The consumer serves layer 0 while w1/w2 are still unpublished.
        await asyncio.wait_for(first_sertwo.wait(), 30)
        assert served == ["w0"]
        await cs.put({"w1": np.full(64, 7.0, np.float32)})
        await cs.put({"w2": np.full(64, 7.0, np.float32)})
        version = await cs.seal()
        sd, got = await task
        assert got == version == 0
        assert served == [f"w{i}" for i in range(3)]
        assert all(np.asarray(sd[f"w{i}"])[0] == 7.0 for i in range(3))
    finally:
        await ts.shutdown("ss_chan")


# --------------------------------------------------------------------------
# direct path: ordered pull
# --------------------------------------------------------------------------


async def test_direct_pull_key_order_and_on_layer():
    """The one-hop direct path honors key_order/on_layer: layers land and
    are reported in forward order, values exact, in place."""
    await ts.initialize(store_name="ss_direct")
    try:
        src = {f"w{i}": np.full(256, float(i) + 1, np.float32) for i in range(4)}
        await ts.put_state_dict(
            "dk/sd", src, direct=True, store_name="ss_direct"
        )
        user = {k: np.zeros(256, np.float32) for k in src}
        order = [f"w{i}" for i in (0, 1, 2, 3)]
        served: list[str] = []
        out = await ts.get_state_dict(
            "dk/sd",
            user_state_dict=user,
            direct=True,
            key_order=order,
            on_layer=lambda fk, v: served.append(fk),
            store_name="ss_direct",
        )
        assert served == order
        for k, v in src.items():
            np.testing.assert_array_equal(np.asarray(out[k]), v)
    finally:
        await ts.shutdown("ss_direct")


# --------------------------------------------------------------------------
# doorbell striping (ROADMAP item-4 remaining depth)
# --------------------------------------------------------------------------


async def test_doorbell_packed_reply_stripes_above_threshold(monkeypatch):
    """IDX_PACKED doorbell replies above the striping threshold split
    across the pre-opened stripe set: the volume counts a doorbell-striped
    transfer and the client reassembles identical bytes."""
    from torchstore_tpu.transport import bulk

    # Client side reads the module global at call time; the forked volume
    # re-imports bulk under the forwarded env, so both sides see 8 KB.
    monkeypatch.setenv("TORCHSTORE_TPU_BULK_STRIPE_THRESHOLD", "8192")
    monkeypatch.setattr(bulk, "STRIPE_THRESHOLD", 8192)
    await ts.initialize(
        store_name="ss_stripe",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    try:
        items = {
            f"s/{i}": np.random.rand(2048).astype(np.float32)  # 8 KB each
            for i in range(4)
        }
        await ts.put_batch(items, store_name="ss_stripe")
        dests = {k: np.zeros(2048, np.float32) for k in items}
        # Recording get registers the doorbell plan; the warm repeat rings
        # it and — with a ~32 KB packed reply over an 8 KB threshold —
        # receives a striped reply.
        await ts.get_batch(dict(dests), store_name="ss_stripe")
        reads0 = _counter("ts_one_sided_reads_total", transport="bulk")
        await ts.get_batch(dict(dests), store_name="ss_stripe")
        assert (
            _counter("ts_one_sided_reads_total", transport="bulk")
            >= reads0 + len(items)
        ), "warm batch did not ride the doorbell"
        for k, v in items.items():
            np.testing.assert_array_equal(dests[k], v)
        # The stripe counter lives in the VOLUME process: read it through
        # the controller's stats fan-out.
        client = ts.client("ss_stripe")
        stats = await client.controller.stats.call_one(include_volumes=True)
        striped = 0.0
        for vstats in stats["volumes"].values():
            for s in (
                vstats.get("metrics", {})
                .get("ts_bulk_striped_transfers_total", {})
                .get("series", [])
            ):
                if s["labels"].get("direction") == "doorbell":
                    striped += s["value"]
        assert striped > 0, "doorbell reply did not stripe"
    finally:
        await ts.shutdown("ss_stripe")


# --------------------------------------------------------------------------
# the llama train→publish→decode driver
# --------------------------------------------------------------------------


async def test_llama_streamed_decode_matches_barrier():
    """The real model loop: tiny-llama params stream-published per module
    in forward order, acquired streamed (decode-side key order from
    models.generate.forward_key_order), and greedy decode produces tokens
    IDENTICAL to the barrier path — while the acquire provably overlapped
    the publish (first layer served before the last was published)."""
    import jax

    from torchstore_tpu.models.generate import Decoder, forward_key_order
    from torchstore_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig.tiny()
    _, params = init_params(cfg)
    await ts.initialize(store_name="ss_llama")
    try:
        # Barrier publish + acquire: the reference tokens.
        await ts.put_state_dict("llama/sd", params, store_name="ss_llama")
        barrier_params = await ts.get_state_dict(
            "llama/sd", store_name="ss_llama"
        )
        prompt = np.array([[1, 2, 3, 4]], np.int32)
        dec = Decoder(cfg, max_len=16)
        ref_tokens = np.asarray(
            dec.generate(barrier_params, prompt, max_new_tokens=4)
        )

        # Streamed publish per top-level module (embed, layer_0, ...).
        served: list[str] = []
        first_served = asyncio.Event()
        publish_done = asyncio.Event()
        overlap_seen = asyncio.Event()

        async def publisher():
            stream = ts.state_dict_stream("llama/sds", store_name="ss_llama")
            await stream.begin()
            modules = list(params["params"])
            for name in modules:
                await stream.put({"params": {name: params["params"][name]}})
                if name == modules[0]:
                    # Hold the stream open until the consumer demonstrably
                    # served the first module — the overlap assertion.
                    await asyncio.wait_for(first_served.wait(), 30)
                    overlap_seen.set()
            await stream.seal()
            publish_done.set()

        def on_layer(fk, value):
            served.append(fk)
            first_served.set()

        order = forward_key_order(params)
        _, streamed_params = await asyncio.gather(
            publisher(),
            ts.get_state_dict_streamed(
                "llama/sds",
                key_order=order,
                on_layer=on_layer,
                wait_for_stream_s=30,
                timeout=120,
                store_name="ss_llama",
            ),
        )
        assert overlap_seen.is_set() and publish_done.is_set()
        assert served == order  # forward order, every leaf exactly once
        # Embedding leaves served before any layer_1 leaf: decode-side
        # forward order held even though publish order was module order.
        emb_last = max(i for i, k in enumerate(served) if "embed" in k)
        l1_first = min(i for i, k in enumerate(served) if "layer_1" in k)
        assert emb_last < l1_first
        tokens = np.asarray(
            dec.generate(streamed_params, prompt, max_new_tokens=4)
        )
        np.testing.assert_array_equal(tokens, ref_tokens)
        jax.block_until_ready(tokens)
    finally:
        await ts.shutdown("ss_llama")


# --------------------------------------------------------------------------
# manifest / generate key-order helpers
# --------------------------------------------------------------------------


def test_manifest_key_order_preserves_insertion_order():
    from torchstore_tpu.provision import StateDictManifest

    sd = {
        "embed": np.zeros(8, np.float32),
        "layer_1": np.zeros(8, np.float32),
        "layer_0": np.zeros(8, np.float32),
        "meta": "not-a-tensor",
    }
    manifest = StateDictManifest.from_state_dict(sd)
    # entries stay name-sorted for pool planning; key_order preserves the
    # source dict's (model-forward) insertion order, tensors only.
    assert [e.key for e in manifest.entries] == ["embed", "layer_0", "layer_1"]
    assert manifest.key_order == ["embed", "layer_1", "layer_0"]


def test_forward_key_order_ranks_modules():
    from torchstore_tpu.models.generate import forward_key_order

    params = {
        "params": {
            "lm_head": {"kernel": np.zeros(4, np.float32)},
            "layer_10": {"w": np.zeros(4, np.float32)},
            "layer_2": {"w": np.zeros(4, np.float32)},
            "final_norm": {"scale": np.zeros(4, np.float32)},
            "embed": {"embedding": np.zeros(4, np.float32)},
        }
    }
    order = forward_key_order(params)
    assert order == [
        "params/embed/embedding",
        "params/layer_2/w",
        "params/layer_10/w",  # numeric, not lexical
        "params/final_norm/scale",
        "params/lm_head/kernel",
    ]
