"""Tier-1 smoke for scripts/bench_compare.py: the r01-r05 trajectory gate
must actually read both record shapes, apply direction-aware thresholds,
and exit non-zero on a regression (the satellite contract of ISSUE 10)."""

import importlib.util
import json
import pathlib

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _load():
    spec = importlib.util.spec_from_file_location(
        "bench_compare", REPO_ROOT / "scripts" / "bench_compare.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(path, doc):
    path.write_text(json.dumps(doc))
    return str(path)


def test_clean_pair_passes_and_regression_fails(tmp_path, capsys):
    bc = _load()
    base = _write(
        tmp_path / "BENCH_a.json",
        {
            "metric": "state_dict_weight_sync_round_trip",
            "value": 10.0,
            "per_key_get_us": 12.0,
            "overlap_ratio": 0.9,
            "p50_get_1kb_ms": 0.2,
        },
    )
    # Within budget: tiny wobble both directions.
    ok = _write(
        tmp_path / "BENCH_b.json",
        {
            "value": 9.5,
            "per_key_get_us": 13.0,
            "overlap_ratio": 0.88,
            "p50_get_1kb_ms": 0.21,
        },
    )
    assert bc.main([base, ok]) == 0
    # Collapse: headline halves AND per-key get triples — both breach.
    bad = _write(
        tmp_path / "BENCH_c.json",
        {
            "value": 4.0,
            "per_key_get_us": 40.0,
            "overlap_ratio": 0.9,
            "p50_get_1kb_ms": 0.2,
        },
    )
    assert bc.main([base, bad]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "value" in out and "per_key_get_us" in out


def test_wrapper_shape_and_tail_recovery(tmp_path):
    """The driver wrapper ({"parsed", "tail"}) must compare as richly as a
    raw record: the full headline JSON embedded in ``tail`` is recovered,
    and a crashed round (parsed: null, no JSON in tail) is a usage error
    rather than a silent pass."""
    bc = _load()
    headline = {"metric": "x", "value": 8.0, "per_key_get_us": 15.0}
    wrapper = _write(
        tmp_path / "BENCH_w.json",
        {
            "n": 1,
            "cmd": "python bench.py",
            "rc": 0,
            "parsed": {"metric": "x", "value": 8.0, "unit": "GB/s"},
            "tail": "# noise\n" + json.dumps(headline) + "\n# more",
        },
    )
    raw = _write(
        tmp_path / "BENCH_x.json", {"value": 7.8, "per_key_get_us": 16.0}
    )
    assert bc.main([wrapper, raw]) == 0
    crashed = _write(
        tmp_path / "BENCH_crash.json",
        {"n": 5, "cmd": "python bench.py", "rc": 1, "parsed": None,
         "tail": "Traceback ..."},
    )
    assert bc.main([raw, crashed]) == 2  # candidate carries nothing


def test_baseline_modes_and_json_output(tmp_path, capsys):
    bc = _load()
    files = [
        _write(tmp_path / f"BENCH_{i}.json", {"value": v})
        for i, v in enumerate((6.0, 12.0, 7.0))
    ]
    cand = _write(tmp_path / "BENCH_cand.json", {"value": 7.5})
    # prev baseline = 7.0 -> +7% improvement: fine.
    assert bc.main([*files, cand, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rows"] and doc["regressed"] == []
    # best baseline = 12.0 -> 37.5% drop: breaches the 30% budget...
    assert bc.main([*files, cand, "--baseline", "best"]) == 1
    capsys.readouterr()
    # ...unless the operator loosens thresholds for a noisy host.
    assert bc.main([*files, cand, "--baseline", "best", "--scale", "2"]) == 0


def test_absolute_thresholds_survive_negative_baselines(tmp_path):
    """ledger_overhead_pct legitimately sits near (or below) zero under
    host noise — a fractional comparison against a negative baseline
    inverts the verdict, so it budgets in absolute percentage points."""
    bc = _load()
    base = _write(
        tmp_path / "BENCH_a.json", {"ledger_overhead_pct": -0.3}
    )
    # A real regression past the 2-point budget must FAIL even though the
    # fractional delta against a negative baseline is negative...
    bad = _write(tmp_path / "BENCH_b.json", {"ledger_overhead_pct": 5.0})
    assert bc.main([base, bad]) == 1
    # ...and an improvement must PASS even though its fractional delta
    # against the negative baseline is large and positive.
    good = _write(tmp_path / "BENCH_c.json", {"ledger_overhead_pct": -2.0})
    assert bc.main([base, good]) == 0
    # Relative metrics with a non-positive baseline are skipped, not
    # mis-judged (a zeroed round must not wave any candidate through).
    zero = _write(tmp_path / "BENCH_z.json", {"value": 0.0})
    cand = _write(tmp_path / "BENCH_d.json", {"value": 0.001})
    rows = bc.compare([bc.load(zero)], bc.load(cand))
    (row,) = [r for r in rows if r["metric"] == "value"]
    assert row["regression"] is None and not row["regressed"]


def test_real_trajectory_files_parse():
    """The committed BENCH_r* records must stay machine-readable (this is
    the exact artifact set the tool exists for). No regression assertion —
    the trajectory spans known host-weather swings — just that at least
    one round yields metrics and the tool runs end to end."""
    bc = _load()
    paths = sorted(str(p) for p in REPO_ROOT.glob("BENCH_r0*.json"))
    assert len(paths) >= 2
    parsed = [bc.load(p) for p in paths]
    assert any(rec for rec in parsed), "no BENCH round carries metrics"
    rc = bc.main([*paths, "--baseline", "median", "--scale", "100"])
    assert rc in (0, 2)  # 2 only if the newest round crashed pre-headline
