"""Property-based tests (hypothesis) for the reshard math: random shard
tilings and request regions must always reassemble to the dense oracle —
the correctness core everything else stands on."""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from torchstore_tpu.transport.types import TensorSlice
from torchstore_tpu.utils import (
    Box,
    assemble_tensor,
    get_destination_view,
    intersect_boxes,
)


def tilings(draw, length: int, max_cuts: int = 3):
    """Random partition of [0, length) into contiguous segments."""
    n_cuts = draw(st.integers(0, min(max_cuts, length - 1)))
    cuts = sorted(
        draw(
            st.lists(
                st.integers(1, length - 1),
                min_size=n_cuts,
                max_size=n_cuts,
                unique=True,
            )
        )
    )
    bounds = [0] + cuts + [length]
    return list(zip(bounds[:-1], bounds[1:]))


@st.composite
def sharded_global(draw):
    """A random 2D global array tiled into a random grid of shards."""
    rows = draw(st.integers(2, 24))
    cols = draw(st.integers(2, 24))
    g = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
    row_tiles = tilings(draw, rows)
    col_tiles = tilings(draw, cols)
    shards = []
    for i, (r0, r1) in enumerate(row_tiles):
        for j, (c0, c1) in enumerate(col_tiles):
            ts = TensorSlice(
                offsets=(r0, c0),
                local_shape=(r1 - r0, c1 - c0),
                global_shape=(rows, cols),
                coordinates=(i, j),
                mesh_shape=(len(row_tiles), len(col_tiles)),
            )
            shards.append((ts, g[r0:r1, c0:c1].copy()))
    return g, shards


@st.composite
def region_of(draw, shape):
    r0 = draw(st.integers(0, shape[0] - 1))
    r1 = draw(st.integers(r0 + 1, shape[0]))
    c0 = draw(st.integers(0, shape[1] - 1))
    c1 = draw(st.integers(c0 + 1, shape[1]))
    return Box((r0, c0), (r1 - r0, c1 - c0))


@settings(max_examples=200, deadline=None)
@given(st.data())
def test_any_region_reassembles_from_any_tiling(data):
    g, shards = data.draw(sharded_global())
    want = data.draw(region_of(g.shape))
    # The client planner's core: intersect the wanted region with every
    # stored shard, cut the pieces, reassemble.
    parts = []
    for ts, shard_data in shards:
        inter = intersect_boxes(ts.box, want)
        if inter is None:
            continue
        rel = tuple(
            slice(o - so, o - so + s)
            for o, so, s in zip(inter.offsets, ts.offsets, inter.shape)
        )
        parts.append((shard_data[rel], inter.offsets))
    out, offsets = assemble_tensor(parts)
    assert offsets == want.offsets
    np.testing.assert_array_equal(out, g[want.to_index()])


@settings(max_examples=200, deadline=None)
@given(st.data())
def test_inplace_landing_matches_oracle(data):
    g, shards = data.draw(sharded_global())
    want = data.draw(region_of(g.shape))
    dest = np.zeros(want.shape, np.float32)
    for ts, shard_data in shards:
        inter = intersect_boxes(ts.box, want)
        if inter is None:
            continue
        rel = tuple(
            slice(o - so, o - so + s)
            for o, so, s in zip(inter.offsets, ts.offsets, inter.shape)
        )
        view = get_destination_view(dest, want, inter, require_contiguous=False)
        assert view is not None
        np.copyto(view, shard_data[rel])
    np.testing.assert_array_equal(dest, g[want.to_index()])


def test_store_roundtrip_random_tilings():
    """End-to-end property check against the LIVE store: random tilings put
    as explicit shards, random regions fetched, oracle-compared. Drives the
    whole stack (controller commit tracking, planner, transport, assembly)
    over 25 random layouts."""
    import asyncio

    import torchstore_tpu as ts

    rng = np.random.default_rng(0)

    async def run():
        await ts.initialize(store_name="prop")
        try:
            for case in range(25):
                g, shards = _random_tiling(rng)
                key = f"p/{case}"
                for tslice, data_arr in shards:
                    await ts.put(key, ts.Shard(data_arr, tslice), store_name="prop")
                # Random region.
                r0 = int(rng.integers(0, g.shape[0]))
                r1 = int(rng.integers(r0 + 1, g.shape[0] + 1))
                c0 = int(rng.integers(0, g.shape[1]))
                c1 = int(rng.integers(c0 + 1, g.shape[1] + 1))
                want = TensorSlice(
                    offsets=(r0, c0), local_shape=(r1 - r0, c1 - c0),
                    global_shape=g.shape, coordinates=(), mesh_shape=(),
                )
                out = await ts.get(key, like=want, store_name="prop")
                np.testing.assert_array_equal(out, g[r0:r1, c0:c1])
                full = await ts.get(key, store_name="prop")
                np.testing.assert_array_equal(full, g)
        finally:
            await ts.shutdown("prop")

    asyncio.run(run())


def _random_tiling(rng):
    rows = int(rng.integers(2, 20))
    cols = int(rng.integers(2, 20))
    g = rng.random((rows, cols), dtype=np.float32)

    def cuts(length):
        n = int(rng.integers(0, min(3, length - 1) + 1))
        pts = sorted(set(rng.integers(1, length, size=n).tolist()))
        bounds = [0] + pts + [length]
        return list(zip(bounds[:-1], bounds[1:]))

    row_tiles, col_tiles = cuts(rows), cuts(cols)
    shards = []
    for i, (a, b) in enumerate(row_tiles):
        for j, (c, d) in enumerate(col_tiles):
            tslice = TensorSlice(
                offsets=(a, c), local_shape=(b - a, d - c), global_shape=(rows, cols),
                coordinates=(i, j), mesh_shape=(len(row_tiles), len(col_tiles)),
            )
            shards.append((tslice, g[a:b, c:d].copy()))
    return g, shards


@settings(max_examples=100, deadline=None)
@given(st.data())
def test_intersection_properties(data):
    g, shards = data.draw(sharded_global())
    boxes = [ts.box for ts, _ in shards]
    full = Box((0, 0), g.shape)
    # Shards tile the space: pairwise disjoint, sizes sum to the whole.
    total = 0
    for i, a in enumerate(boxes):
        assert intersect_boxes(a, full) == a  # contained in the global box
        assert intersect_boxes(a, a) == a  # idempotent
        total += a.size
        for b in boxes[i + 1 :]:
            inter = intersect_boxes(a, b)
            assert inter is None  # tiling -> disjoint
            assert intersect_boxes(b, a) is None  # symmetric
    assert total == full.size
