"""SPMD bootstrap tests: env parsing/validation + full multi-process
lifecycle (rendezvous, per-host volume spawn, handle broadcast, cross-rank
put/get, two-phase shutdown) — reference tests/test_spmd.py mechanisms."""

import asyncio
import json
import multiprocessing as mp
import os

import numpy as np
import pytest

from torchstore_tpu.spmd import SPMDEnv
from torchstore_tpu.utils import get_free_port


class TestSPMDEnv:
    def _env(self, **kw):
        base = {
            "RANK": "1",
            "WORLD_SIZE": "4",
            "LOCAL_RANK": "1",
            "LOCAL_WORLD_SIZE": "4",
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": "29500",
        }
        base.update(kw)
        return base

    def test_parse(self, monkeypatch):
        for k, v in self._env().items():
            monkeypatch.setenv(k, v)
        env = SPMDEnv.from_env()
        assert env.rank == 1 and env.world_size == 4
        assert env.num_hosts == 1 and env.host_rank == 0

    def test_multi_host_derivation(self, monkeypatch):
        for k, v in self._env(
            RANK="5", WORLD_SIZE="8", LOCAL_RANK="1", LOCAL_WORLD_SIZE="4"
        ).items():
            monkeypatch.setenv(k, v)
        env = SPMDEnv.from_env()
        assert env.num_hosts == 2 and env.host_rank == 1

    def test_missing_vars(self, monkeypatch):
        monkeypatch.delenv("RANK", raising=False)
        monkeypatch.delenv("MASTER_ADDR", raising=False)
        with pytest.raises(RuntimeError, match="missing"):
            SPMDEnv.from_env()

    def test_rank_out_of_range(self, monkeypatch):
        for k, v in self._env(RANK="4").items():
            monkeypatch.setenv(k, v)
        with pytest.raises(ValueError, match="out of range"):
            SPMDEnv.from_env()

    def test_world_not_divisible(self, monkeypatch):
        for k, v in self._env(WORLD_SIZE="6", LOCAL_WORLD_SIZE="4", RANK="0", LOCAL_RANK="0").items():
            monkeypatch.setenv(k, v)
        with pytest.raises(ValueError, match="divisible"):
            SPMDEnv.from_env()


def _durable_worker(rank: int, world: int, port: int, result_dir: str, phase: str) -> None:
    os.environ.update(
        {
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(world),
            "LOCAL_WORLD_SIZE": str(world),
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        }
    )
    result = {"rank": rank, "ok": False}
    try:
        asyncio.run(_durable_scenario(rank, world, result_dir, phase, result))
    except Exception as exc:  # noqa: BLE001
        import traceback

        result["error"] = f"{exc!r}\n{traceback.format_exc()}"
    with open(os.path.join(result_dir, f"{phase}_rank_{rank}.json"), "w") as f:
        json.dump(result, f)


async def _durable_scenario(rank, world, result_dir, phase, result):
    import torchstore_tpu as ts

    storage = os.path.join(result_dir, "storage")
    if phase == "write":
        await ts.initialize_spmd(store_name="dspmd", storage_dir=storage)
        await ts.put(f"r{rank}", np.full(4, float(rank)), store_name="dspmd")
        await ts.barrier("puts", store_name="dspmd")
        from torchstore_tpu.spmd import _spmd_sessions

        session = _spmd_sessions["dspmd"]
        # Drain ack: non-zero ranks confirm they have no in-flight
        # rendezvous requests before rank 0 (which HOSTS the rendezvous)
        # simulates its crash — otherwise killing the server races their
        # barrier replies.
        if rank != 0:
            await session.client.add("drained", 1)
        else:
            await session.client.wait_counter("drained", world - 1)
        # SIMULATED CRASH: exit without collective shutdown (volumes are
        # children and die with us; data must persist on disk).
        if session.volume_mesh is not None:
            for proc in session.volume_mesh._processes:
                proc.terminate()
        result["ok"] = True
        return
    # phase == "recover": fresh world over the same storage dir.
    await ts.initialize_spmd(store_name="dspmd", storage_dir=storage, recover=True)
    for other in range(world):
        out = await ts.get(f"r{other}", store_name="dspmd")
        assert out[0] == float(other), (other, out)
    await ts.barrier("reads", store_name="dspmd")
    await ts.shutdown("dspmd")
    result["ok"] = True


def test_spmd_durable_recovery(tmp_path):
    world = 2
    for phase in ("write", "recover"):
        port = get_free_port()
        ctx = mp.get_context("spawn")
        procs = [
            ctx.Process(
                target=_durable_worker,
                args=(r, world, port, str(tmp_path), phase),
                daemon=False,
            )
            for r in range(world)
        ]
        for p in procs:
            p.start()
        try:
            for p in procs:
                p.join(timeout=180)
                assert not p.is_alive(), f"{phase} worker hung"
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
        for r in range(world):
            result = json.loads((tmp_path / f"{phase}_rank_{r}.json").read_text())
            assert result["ok"], f"{phase} rank {r}: {result.get('error')}"


async def test_rendezvous_kv():
    from torchstore_tpu.runtime.rendezvous import RendezvousClient, RendezvousServer

    server = RendezvousServer()
    port = await server.start("127.0.0.1", 0)
    a = RendezvousClient("127.0.0.1", port)
    b = RendezvousClient("127.0.0.1", port)
    await a.connect()
    await b.connect()
    try:
        # Blocking get resolves once the other client sets.
        get_task = asyncio.ensure_future(b.get("k"))
        await asyncio.sleep(0.05)
        assert not get_task.done()
        await a.set("k", {"v": 1})
        assert await get_task == {"v": 1}
        assert await a.add("c", 2) == 2
        assert await b.add("c", 3) == 5
        await a.wait_counter("c", 5)
        assert await b.check("k") and not await b.check("nope")
        await asyncio.gather(a.barrier("x", 2), b.barrier("x", 2))
    finally:
        await a.close()
        await b.close()
        await server.stop()


def _spmd_worker(
    rank: int,
    world: int,
    port: int,
    result_dir: str,
    local_world: int = 0,
    secret: "str | None" = None,
) -> None:
    local_world = local_world or world
    env = {
        "RANK": str(rank),
        "LOCAL_RANK": str(rank % local_world),
        "WORLD_SIZE": str(world),
        "LOCAL_WORLD_SIZE": str(local_world),
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(port),
    }
    if local_world != world:
        # Emulated multi-host on one machine: volumes bind 0.0.0.0; the
        # advertised address must still be reachable.
        env["TORCHSTORE_TPU_ADVERTISE_HOST"] = "127.0.0.1"
    if secret:
        env["TORCHSTORE_TPU_AUTH_SECRET"] = secret
    os.environ.update(env)
    result = {"rank": rank, "ok": False}
    try:
        asyncio.run(_spmd_scenario(rank, world, result))
    except Exception as exc:  # noqa: BLE001 - reported to parent
        import traceback

        result["error"] = f"{exc!r}\n{traceback.format_exc()}"
    with open(os.path.join(result_dir, f"rank_{rank}.json"), "w") as f:
        json.dump(result, f)


async def _spmd_scenario(rank: int, world: int, result: dict) -> None:
    import torchstore_tpu as ts

    await ts.initialize_spmd(store_name="spmdtest")
    # Each rank publishes its shard of a global array + a rank tensor.
    g = np.arange(float(world * 4), dtype=np.float32).reshape(world, 4)
    sl = ts.TensorSlice(
        offsets=(rank, 0), local_shape=(1, 4), global_shape=(world, 4),
        coordinates=(rank,), mesh_shape=(world,),
    )
    await ts.put("g", ts.Shard(g[rank : rank + 1], sl), store_name="spmdtest")
    await ts.put(f"r{rank}", np.full(2, float(rank)), store_name="spmdtest")
    await ts.barrier("puts_done", store_name="spmdtest")
    other = (rank + 1) % world
    peer = await ts.get(f"r{other}", store_name="spmdtest")
    assert peer[0] == float(other), peer
    full = await ts.get("g", store_name="spmdtest")
    np.testing.assert_array_equal(full, g)
    await ts.barrier("reads_done", store_name="spmdtest")
    await ts.shutdown("spmdtest")
    result["ok"] = True


def _channel_worker(rank: int, world: int, port: int, result_dir: str) -> None:
    os.environ.update(
        {
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(world),
            "LOCAL_WORLD_SIZE": str(world),
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
        }
    )
    result = {"rank": rank, "ok": False}
    try:
        asyncio.run(_channel_scenario(rank, world, result))
    except Exception as exc:  # noqa: BLE001 - reported to parent
        import traceback

        result["error"] = f"{exc!r}\n{traceback.format_exc()}"
    with open(os.path.join(result_dir, f"rank_{rank}.json"), "w") as f:
        json.dump(result, f)


async def _channel_scenario(rank: int, world: int, result: dict) -> None:
    """Versioned weight channel across SPMD ranks: rank 0 publishes, every
    other rank block-acquires each version (wait_for_change over real RPC,
    no polling) — the RL trainer/generator topology under torchrun."""
    import torchstore_tpu as ts

    await ts.initialize_spmd(store_name="chspmd")
    versions = 3
    if rank == 0:
        pub = ts.WeightPublisher("policy", store_name="chspmd", keep=versions)
        for v in range(versions):
            await pub.publish({"w": np.full(8, float(v), np.float32)})
            await asyncio.sleep(0.05)
    else:
        sub = ts.WeightSubscriber("policy", store_name="chspmd")
        got = []
        while len(got) < 1 or got[-1] < versions - 1:
            sd, v = await sub.acquire(timeout=60.0)
            assert sd["w"][0] == float(v), (v, sd["w"][0])
            got.append(v)
        assert got == sorted(got), got
    await ts.barrier("channel_done", store_name="chspmd")
    await ts.shutdown("chspmd")
    result["ok"] = True


def _device_sync_worker(rank: int, world: int, port: int, result_dir: str) -> None:
    os.environ.update(
        {
            "RANK": str(rank),
            "LOCAL_RANK": str(rank),
            "WORLD_SIZE": str(world),
            "LOCAL_WORLD_SIZE": str(world),
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        }
    )
    result = {"rank": rank, "ok": False}
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        asyncio.run(_device_sync_scenario(rank, world, result))
    except Exception as exc:  # noqa: BLE001 - reported to parent
        import traceback

        result["error"] = f"{exc!r}\n{traceback.format_exc()}"
    with open(os.path.join(result_dir, f"rank_{rank}.json"), "w") as f:
        json.dump(result, f)


async def _device_sync_scenario(rank: int, world: int, result: dict) -> None:
    """Multi-rank SPMD DEVICE-path direct sync (VERDICT r2 item 1): two
    publisher processes each own a disjoint 4-device subset and publish
    their half of the model direct=True; the consumer (rank 0) pulls the
    merged dict over the device path — per-rank transfer servers, zero host
    staging on any source."""
    import jax

    import torchstore_tpu as ts

    from torchstore_tpu.transport import device_transfer as dt

    await ts.initialize_spmd(store_name="devsync")
    w = np.arange(128.0, dtype=np.float32).reshape(16, 8)
    devs = jax.devices()
    if rank > 0:
        r = rank - 1  # publisher rank within the 2-rank source world
        sub = np.array(devs[4 * r : 4 * r + 4], dtype=object)
        mesh = jax.sharding.Mesh(sub.reshape(4), ("x",))
        sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
        local = jax.device_put(jax.numpy.asarray(w[8 * r : 8 * r + 8]), sh)
        sl = ts.TensorSlice(
            offsets=(8 * r, 0), local_shape=(8, 8), global_shape=(16, 8),
            coordinates=(r,), mesh_shape=(2,),
        )
        await ts.put_state_dict(
            "policy", {"w": ts.Shard(local, sl)}, direct=True,
            rank=r, num_ranks=2, store_name="devsync",
        )
        await ts.barrier("published", store_name="devsync")
        # Keep serving until the consumer confirms its pull.
        await ts.barrier("pulled", store_name="devsync")
    else:
        await ts.barrier("published", store_name="devsync")
        # Zero-host-staging holds only where the jax build ships the XLA
        # transfer engine (jax.experimental.transfer). This image's jax
        # (0.4.37) predates it, so device_transfer.is_available() is False
        # in EVERY process and registration deterministically falls back to
        # host staging (root cause of the standing tier-1 failure — not a
        # flake). The merged multi-rank pull below is path-independent and
        # stays asserted either way.
        for r in (0, 1):
            published = await ts.get(f"policy/rank_{r}", store_name="devsync")
            if dt.is_available():
                assert published["handles"] == {}, "host buffers on device path"
                assert published["device"] is not None
            else:
                assert published["handles"], "no handles on fallback path"
        mesh8 = jax.sharding.Mesh(
            np.array(devs, dtype=object).reshape(8), ("x",)
        )
        tgt = jax.sharding.NamedSharding(mesh8, jax.sharding.PartitionSpec("x"))
        out = await ts.get_state_dict(
            "policy",
            user_state_dict={
                "w": jax.ShapeDtypeStruct(
                    (16, 8), jax.numpy.float32, sharding=tgt
                )
            },
            direct=True,
            store_name="devsync",
        )
        assert out["w"].sharding == tgt
        np.testing.assert_array_equal(np.asarray(out["w"]), w)
        await ts.barrier("pulled", store_name="devsync")
    await ts.shutdown("devsync")
    result["ok"] = True


def test_spmd_multi_rank_device_sync(tmp_path):
    world = 3  # rank 0 consumes; ranks 1-2 publish as source ranks 0-1
    port = get_free_port()
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(
            target=_device_sync_worker,
            args=(r, world, port, str(tmp_path)),
            daemon=False,
        )
        for r in range(world)
    ]
    for p in procs:
        p.start()
    try:
        for p in procs:
            p.join(timeout=180)
            assert not p.is_alive(), "device-sync worker hung"
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
    for r in range(world):
        path = tmp_path / f"rank_{r}.json"
        assert path.exists(), f"rank {r} produced no result"
        result = json.loads(path.read_text())
        assert result["ok"], f"rank {r} failed: {result.get('error')}"


def test_spmd_weight_channel(tmp_path):
    world = 3
    port = get_free_port()
    ctx = mp.get_context("spawn")
    procs = [
        ctx.Process(
            target=_channel_worker,
            args=(r, world, port, str(tmp_path)),
            daemon=False,
        )
        for r in range(world)
    ]
    for p in procs:
        p.start()
    try:
        for p in procs:
            p.join(timeout=180)
            assert not p.is_alive(), "channel worker hung"
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
    for r in range(world):
        path = tmp_path / f"rank_{r}.json"
        assert path.exists(), f"rank {r} produced no result"
        result = json.loads(path.read_text())
        assert result["ok"], f"rank {r} failed: {result.get('error')}"


@pytest.mark.parametrize(
    "world,local_world,secret",
    [
        (2, 2, None),
        (4, 4, None),
        (4, 2, None),
        # Multi-host WITH connection auth: every listener (rendezvous,
        # actors, bulk) requires the HMAC challenge end to end.
        (4, 2, "spmd-secret"),
    ],
    ids=["1host-2rank", "1host-4rank", "2hosts-2ranks", "2hosts-auth"],
)
def test_spmd_full_lifecycle(tmp_path, world, local_world, secret):
    port = get_free_port()
    ctx = mp.get_context("spawn")
    # Not daemonic: workers spawn their own volume actor children.
    procs = [
        ctx.Process(
            target=_spmd_worker,
            args=(r, world, port, str(tmp_path), local_world, secret),
            daemon=False,
        )
        for r in range(world)
    ]
    for p in procs:
        p.start()
    try:
        for p in procs:
            p.join(timeout=180)
            assert not p.is_alive(), "spmd worker hung"
    finally:
        for p in procs:
            if p.is_alive():
                p.terminate()
    for r in range(world):
        path = tmp_path / f"rank_{r}.json"
        assert path.exists(), f"rank {r} produced no result"
        result = json.loads(path.read_text())
        assert result["ok"], f"rank {r} failed: {result.get('error')}"
