"""Durable storage + crash recovery tests: FileBackedStore round trips,
memmap write-through on in-place overwrite, volume-kill -> re-initialize ->
rebuild_index recovery (capability beyond the in-memory-only reference)."""

import os

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.storage_utils.file_store import FileBackedStore
from torchstore_tpu.transport.types import Request, TensorSlice


class TestFileBackedStoreUnit:
    def test_tensor_roundtrip_and_reload(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        x = np.random.rand(32, 16).astype(np.float32)
        meta = Request.from_tensor("a/b", x).meta_only()
        store.store([meta], {0: x})
        np.testing.assert_array_equal(store.get_data(Request.meta_request("a/b")), x)
        # Fresh instance over the same dir sees the data (memmap reload).
        store2 = FileBackedStore(str(tmp_path))
        np.testing.assert_array_equal(
            store2.get_data(Request.meta_request("a/b")), x
        )

    def test_sharded_roundtrip_and_reload(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        g = np.arange(32.0, dtype=np.float32).reshape(4, 8)
        for r in range(2):
            sl = TensorSlice(
                offsets=(r * 2, 0), local_shape=(2, 8), global_shape=(4, 8),
                coordinates=(r,), mesh_shape=(2,),
            )
            meta = Request(key="w", tensor_slice=sl)
            store.store([meta], {0: g[r * 2 : r * 2 + 2]})
        store2 = FileBackedStore(str(tmp_path))
        req = Request(
            key="w",
            tensor_slice=TensorSlice(
                offsets=(2, 0), local_shape=(2, 8), global_shape=(4, 8),
                coordinates=(1,), mesh_shape=(2,),
            ),
        )
        np.testing.assert_array_equal(store2.get_data(req), g[2:4])
        assert len(store2.manifest()) == 2

    def test_objects_persist(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        store.store([Request.from_objects("cfg", None).meta_only()], {0: {"lr": 1}})
        store2 = FileBackedStore(str(tmp_path))
        assert store2.get_data(Request(key="cfg", is_object=True)) == {"lr": 1}

    def test_inplace_overwrite_writes_through(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        x = np.zeros((8,), np.float32)
        meta = Request.from_tensor("k", x).meta_only()
        store.store([meta], {0: x})
        existing = store.extract_existing([meta])
        assert isinstance(existing[0], np.memmap)
        existing[0][:] = 7.0  # transport writes into the existing buffer
        store.store([meta], {0: existing[0]})
        store2 = FileBackedStore(str(tmp_path))
        np.testing.assert_array_equal(
            store2.get_data(Request.meta_request("k")), np.full(8, 7.0)
        )

    def test_persist_commits_atomically_no_tmp_left(self, tmp_path):
        """Crash-safe persist (the spill-tier contract): a completed store
        leaves NO temp files behind — data committed via write-temp +
        fsync + rename, meta via its own atomic replace."""
        store = FileBackedStore(str(tmp_path))
        x = np.random.rand(64).astype(np.float32)
        store.store([Request.from_tensor("k", x).meta_only()], {0: x})
        leftovers = [
            os.path.join(dirpath, f)
            for dirpath, _dirs, files in os.walk(str(tmp_path))
            for f in files
            if f.endswith(".tmp")
        ]
        assert leftovers == []
        np.testing.assert_array_equal(
            store.get_data(Request.meta_request("k")), x
        )

    def test_torn_tmp_from_mid_write_death_never_trusted(self, tmp_path):
        """A process killed mid-spill leaves at worst ``*.tmp`` garbage
        (the rename never committed): a reload must neither surface an
        entry from it nor corrupt committed siblings — and must sweep it."""
        store = FileBackedStore(str(tmp_path))
        x = np.random.rand(16).astype(np.float32)
        store.store([Request.from_tensor("good", x).meta_only()], {0: x})
        # Simulate two death points: (a) a torn data temp beside a
        # committed entry; (b) an aborted FIRST persist — dir with only a
        # torn temp, meta never written.
        good_dir = os.path.dirname(
            os.path.join(str(tmp_path), os.listdir(str(tmp_path))[0], "x")
        )
        with open(os.path.join(good_dir, "data.bin.tmp"), "wb") as f:
            f.write(b"\x00garbage\x00" * 3)
        aborted = os.path.join(str(tmp_path), "YWJvcnRlZA")  # "aborted"
        os.makedirs(aborted)
        with open(os.path.join(aborted, "data.bin.tmp"), "wb") as f:
            f.write(b"torn")
        store2 = FileBackedStore(str(tmp_path))
        assert set(store2.kv) == {"good"}
        np.testing.assert_array_equal(
            store2.get_data(Request.meta_request("good")), x
        )
        # The torn temps were swept at load, not left to accumulate.
        assert not os.path.exists(os.path.join(good_dir, "data.bin.tmp"))
        assert not os.path.exists(os.path.join(aborted, "data.bin.tmp"))

    def test_delete_removes_files(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        store.store([Request.from_tensor("k", np.ones(4)).meta_only()], {0: np.ones(4)})
        assert store.delete("k")
        assert not store.delete("k")
        assert len(os.listdir(tmp_path)) == 0
        store2 = FileBackedStore(str(tmp_path))
        with pytest.raises(KeyError):
            store2.get_data(Request.meta_request("k"))

    def test_zero_size_tensor(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        x = np.zeros((0, 128), np.float32)
        store.store([Request.from_tensor("empty", x).meta_only()], {0: x})
        out = store.get_data(Request.meta_request("empty"))
        assert out.shape == (0, 128)
        store2 = FileBackedStore(str(tmp_path))
        assert store2.get_data(Request.meta_request("empty")).shape == (0, 128)

    def test_reset_clears_dir(self, tmp_path):
        store = FileBackedStore(str(tmp_path))
        store.store([Request.from_tensor("k", np.ones(4)).meta_only()], {0: np.ones(4)})
        store.reset()
        assert os.listdir(tmp_path) == []

    def test_layout_change_prunes_superseded_shards(self, tmp_path):
        """Re-publishing a key under a new mesh/global shape must delete the
        old-layout shard files: otherwise crash recovery manifests a mix of
        old and new slices for one key (silent weight corruption)."""
        store = FileBackedStore(str(tmp_path))
        g = np.arange(32.0, dtype=np.float32).reshape(4, 8)
        for r in range(2):  # old layout: 2-way rows
            sl = TensorSlice(
                offsets=(r * 2, 0), local_shape=(2, 8), global_shape=(4, 8),
                coordinates=(r,), mesh_shape=(2,),
            )
            store.store([Request(key="w", tensor_slice=sl)], {0: g[r * 2 : r * 2 + 2]})
        # new layout: 4-way rows; first shard arrives
        sl_new = TensorSlice(
            offsets=(0, 0), local_shape=(1, 8), global_shape=(4, 8),
            coordinates=(0,), mesh_shape=(4,),
        )
        store.store([Request(key="w", tensor_slice=sl_new)], {0: g[:1]})
        manifest = store.manifest()
        assert len(manifest) == 1  # old-layout shards gone
        assert manifest[0]["meta"].tensor_slice.mesh_shape == (4,)
        # and gone from DISK, not just memory
        store2 = FileBackedStore(str(tmp_path))
        assert len(store2.manifest()) == 1

    def test_dtype_change_prunes_old_dtype_shards(self, tmp_path):
        """meta.pkl stores one dtype per sharded key; old-dtype shard files
        must be dropped on a dtype-changing re-publish or recovery maps them
        with the wrong dtype."""
        store = FileBackedStore(str(tmp_path))
        sl0 = TensorSlice(
            offsets=(0,), local_shape=(4,), global_shape=(8,),
            coordinates=(0,), mesh_shape=(2,),
        )
        sl1 = TensorSlice(
            offsets=(4,), local_shape=(4,), global_shape=(8,),
            coordinates=(1,), mesh_shape=(2,),
        )
        store.store([Request(key="w", tensor_slice=sl0)], {0: np.ones(4, np.float32)})
        store.store([Request(key="w", tensor_slice=sl1)], {0: np.ones(4, np.float32)})
        store.store(
            [Request(key="w", tensor_slice=sl0)], {0: np.ones(4, np.float16)}
        )
        store2 = FileBackedStore(str(tmp_path))
        manifest = store2.manifest()
        assert len(manifest) == 1
        assert manifest[0]["meta"].tensor_meta.dtype == "float16"


class TestResolveManifests:
    """Mixed-layout crash recovery: one volume already re-sharded, another
    still holding old-layout shards — the rebuild must keep only the newest
    layout (ADVICE r1: stale-layout invalidation in rebuild_index)."""

    @staticmethod
    def _slice_item(key, coords, mesh, offsets, local, global_, mtime, dtype="float32"):
        from torchstore_tpu.transport.types import TensorMeta

        return {
            "meta": Request(
                key=key,
                tensor_slice=TensorSlice(
                    offsets=offsets, local_shape=local, global_shape=global_,
                    coordinates=coords, mesh_shape=mesh,
                ),
                tensor_meta=TensorMeta(shape=local, dtype=dtype),
            ),
            "mtime": mtime,
        }

    def test_newest_layout_wins(self):
        from torchstore_tpu.controller import resolve_manifests

        old0 = self._slice_item("w", (0,), (2,), (0, 0), (2, 8), (4, 8), 100.0)
        old1 = self._slice_item("w", (1,), (2,), (2, 0), (2, 8), (4, 8), 100.0)
        new0 = self._slice_item("w", (0,), (4,), (0, 0), (1, 8), (4, 8), 200.0)
        survivors, dropped = resolve_manifests(
            [("v0", [new0]), ("v1", [old0, old1])]
        )
        assert dropped == 2
        assert len(survivors) == 1
        assert survivors[0][1].tensor_slice.mesh_shape == (4,)

    def test_single_layout_untouched(self):
        from torchstore_tpu.controller import resolve_manifests

        a = self._slice_item("w", (0,), (2,), (0, 0), (2, 8), (4, 8), 50.0)
        b = self._slice_item("w", (1,), (2,), (2, 0), (2, 8), (4, 8), 60.0)
        survivors, dropped = resolve_manifests([("v0", [a]), ("v1", [b])])
        assert dropped == 0 and len(survivors) == 2

    def test_bare_requests_accepted(self):
        from torchstore_tpu.controller import resolve_manifests

        survivors, dropped = resolve_manifests(
            [("v0", [Request(key="obj", is_object=True)])]
        )
        assert dropped == 0 and survivors[0][1].key == "obj"


async def test_durable_store_survives_volume_crash(tmp_path):
    storage_dir = str(tmp_path / "store")
    await ts.initialize(store_name="dur", storage_dir=storage_dir)
    x = np.random.rand(64, 32).astype(np.float32)
    sl = TensorSlice(
        offsets=(0, 0), local_shape=(32, 32), global_shape=(64, 32),
        coordinates=(0,), mesh_shape=(2,),
    )
    sl2 = TensorSlice(
        offsets=(32, 0), local_shape=(32, 32), global_shape=(64, 32),
        coordinates=(1,), mesh_shape=(2,),
    )
    await ts.put("w", ts.Shard(x[:32], sl), store_name="dur")
    await ts.put("w", ts.Shard(x[32:], sl2), store_name="dur")
    await ts.put("dense", x, store_name="dur")
    await ts.put("cfg", {"step": 9}, store_name="dur")

    # CRASH: kill the volume processes without teardown (data must survive).
    from torchstore_tpu import api
    from torchstore_tpu.runtime import stop_singleton

    handle = api._stores.pop("dur")
    for proc in handle.volume_mesh._processes:
        proc.terminate()
        proc.join(5)
    await stop_singleton("ts_dur_controller")

    # Fresh store over the same directory, with index recovery.
    await ts.initialize(store_name="dur", storage_dir=storage_dir, recover=True)
    try:
        np.testing.assert_array_equal(await ts.get("w", store_name="dur"), x)
        np.testing.assert_array_equal(
            await ts.get("dense", store_name="dur"), x
        )
        assert await ts.get("cfg", store_name="dur") == {"step": 9}
        assert sorted(await ts.keys(store_name="dur")) == ["cfg", "dense", "w"]
    finally:
        await ts.shutdown("dur")


async def test_recover_without_dir_rejected():
    with pytest.raises(ValueError, match="requires storage_dir"):
        await ts.initialize(store_name="bad", recover=True)
    from torchstore_tpu import api

    assert "bad" not in api._stores
