"""Tier-1 guard for the static-analysis suite (torchstore_tpu/analysis/).

Two layers:

1. **Checker self-tests on fixture snippets** — each of the eight rules must
   catch a seeded defect (a synthetic endpoint typo, a swallowed
   CancelledError, an unregistered env var, ...) and stay quiet on the
   matching clean snippet, so a refactor of the suite cannot silently turn
   a rule into a no-op.
2. **The zero-new-findings gate** — the full suite over THIS repo against
   the committed baseline (tslint_baseline.json) must report no new
   findings, and the orphan-task / cancellation-swallow rules must not be
   baselined away (their fixes landed with the checkers that found them).
"""

import asyncio
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from torchstore_tpu.analysis import (  # noqa: E402
    DEFAULT_BASELINE,
    Project,
    load_baseline,
    run_checks,
    save_baseline,
)
from torchstore_tpu.analysis.checkers import (  # noqa: E402
    CHECKERS,
    async_blocking,
    cancellation,
    endpoint_drift,
    env_registry,
    fork_safety,
    history_discipline,
    landing_copy,
    metric_discipline,
    orphan_task,
)


def _project(tmp_path, files: dict) -> Project:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(str(tmp_path))


def _msgs(findings, rule=None):
    return [f.message for f in findings if rule is None or f.rule == rule]


# --------------------------------------------------------------------------
# 1. endpoint-drift
# --------------------------------------------------------------------------

_ACTOR_SRC = """
    class Vol:
        @endpoint
        async def put(self, buffer, metas): ...

        @endpoint
        async def stats(self, include_volumes=False): ...
    """


def test_endpoint_drift_catches_typo_and_arity(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/vol.py": _ACTOR_SRC,
            "torchstore_tpu/caller.py": """
                async def go(ref):
                    await ref.put.call_one(buf, metas)          # ok
                    await ref.putt.call_one(buf, metas)         # typo
                    await ref.put.call_one(buf)                 # missing arg
                    await ref.stats.call_one(include_volumes=True)  # ok kw
                    await ref.stats.call_one(bogus=True)        # unknown kw
                    put = volume.actor.put
                    await put.with_timeout(9).call_one(b, m)    # ok (alias)
                    await put.with_timeout(9).call_one()        # alias, bad arity
                """,
        },
    )
    found = endpoint_drift.check(proj)
    msgs = _msgs(found)
    assert any("unknown endpoint 'putt'" in m for m in msgs), msgs
    assert sum("endpoint 'put'" in m and "matches no endpoint" in m for m in msgs) == 2
    assert any("bogus" in m for m in msgs), msgs
    # exactly the four seeded defects, nothing else
    assert len(found) == 4, [f.render() for f in found]


def test_endpoint_drift_live_coverage_not_vacuous():
    """The real tree must expose a meaningful surface to the checker — a
    scan-scope regression would otherwise pass the gate vacuously."""
    proj = Project(str(REPO_ROOT))
    endpoints = endpoint_drift.collect_endpoints(proj)
    assert len(endpoints) >= 25, sorted(endpoints)
    assert "put" in endpoints and "reserve_prewarm" in endpoints
    assert endpoint_drift.check(proj) == []


# --------------------------------------------------------------------------
# 2. async-blocking
# --------------------------------------------------------------------------


def test_async_blocking_flags_blocking_calls(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                import asyncio, time, subprocess

                async def bad():
                    time.sleep(1)
                    subprocess.run(["true"])
                    open("/tmp/x")
                    fut.result()

                async def good(loop):
                    await asyncio.sleep(1)

                    def thunk():
                        time.sleep(1)  # executor thunk: exempt

                    await loop.run_in_executor(None, thunk)
                """,
        },
    )
    msgs = _msgs(async_blocking.check(proj))
    assert len(msgs) == 4, msgs
    assert all("'bad'" in m for m in msgs), msgs


# --------------------------------------------------------------------------
# 3. cancellation-swallow
# --------------------------------------------------------------------------


def test_cancellation_swallow_rules(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                import asyncio

                async def swallow_base():
                    try:
                        await x()
                    except BaseException:
                        pass  # seeded defect

                async def swallow_bare():
                    try:
                        await x()
                    except:
                        log()  # seeded defect

                async def swallow_cancel():
                    try:
                        await x()
                    except asyncio.CancelledError:
                        return  # seeded defect

                async def ok_reraise():
                    try:
                        await x()
                    except BaseException:
                        cleanup()
                        raise

                async def ok_forward_idiom():
                    try:
                        await x()
                    except asyncio.CancelledError:
                        raise
                    except BaseException as exc:
                        report(exc)

                def sync_is_exempt():
                    try:
                        run()
                    except BaseException:
                        pass
                """,
        },
    )
    found = cancellation.check(proj)
    assert len(found) == 3, [f.render() for f in found]
    assert {"swallow_base", "swallow_bare", "swallow_cancel"} == {
        m.split("async def ")[1].split("'")[1] for m in _msgs(found)
    }


# --------------------------------------------------------------------------
# 4. orphan-task
# --------------------------------------------------------------------------


def test_orphan_task_rules(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                import asyncio

                def fire_and_forget():
                    asyncio.create_task(work())  # seeded defect

                def discard_only(tasks):
                    t = asyncio.ensure_future(work())
                    tasks.add(t)
                    t.add_done_callback(tasks.discard)  # seeded defect

                def logged(tasks):
                    t = asyncio.create_task(work())
                    tasks.add(t)
                    t.add_done_callback(_log_failure)

                class C:
                    def owner_managed(self):
                        self._t = asyncio.create_task(work())

                async def awaited():
                    t = asyncio.create_task(work())
                    await t

                async def gathered():
                    t = asyncio.create_task(work())
                    await asyncio.gather(t)
                """,
        },
    )
    found = orphan_task.check(proj)
    assert len(found) == 2, [f.render() for f in found]
    assert any("fire-and-forget" in m for m in _msgs(found))
    assert any("set discard" in m for m in _msgs(found))


# --------------------------------------------------------------------------
# 5. fork-safety
# --------------------------------------------------------------------------


def test_fork_safety_rules(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/bad.py": """
                import threading
                _registry = {}
                _lock = threading.Lock()
                RULE_TABLE = {"a": 1}   # constant convention: exempt
                _FROZEN = frozenset()   # immutable: exempt
                """,
            "torchstore_tpu/good.py": """
                _registry = {}

                def reinit_after_fork():
                    _registry.clear()
                """,
            "torchstore_tpu/pragma.py": """
                _cache = {}  # tslint: disable=fork-safety
                """,
            "scripts/tool.py": """
                _state = {}  # scripts never run inside forked actors
                """,
        },
    )
    found = fork_safety.check(proj)
    # the raw checker sees the pragma'd file too; suppression is run_checks' job
    assert {f.path for f in found} == {
        "torchstore_tpu/bad.py",
        "torchstore_tpu/pragma.py",
    }
    assert sum(f.path == "torchstore_tpu/bad.py" for f in found) == 2
    result = run_checks(str(tmp_path), rules=["fork-safety"], project=proj)
    assert {f.path for f in result.findings} == {"torchstore_tpu/bad.py"}


# --------------------------------------------------------------------------
# 6. env-registry
# --------------------------------------------------------------------------

_FIXTURE_CONFIG = """
    ENV_REGISTRY = (
        EnvVar("TORCHSTORE_TPU_FOO", "int", 7, "Foo knob."),
        EnvVar("TORCHSTORE_TPU_DEAD", "str", None, "Referenced nowhere."),
    )
    ENV_PREFIXES = ("TORCHSTORE_TPU_DYN_",)
    """


def test_env_registry_rules(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/config.py": _FIXTURE_CONFIG,
            "torchstore_tpu/m.py": """
                import os
                ok = os.environ.get("TORCHSTORE_TPU_FOO", "7")
                unregistered = os.environ.get("TORCHSTORE_TPU_BAR")  # seeded
                dyn = os.environ.get("TORCHSTORE_TPU_DYN_THING")     # prefix ok
                drifted = os.environ.get("TORCHSTORE_TPU_FOO", "9")  # seeded
                """,
        },
    )
    msgs = _msgs(env_registry.check(proj))
    assert any("'TORCHSTORE_TPU_BAR'" in m and "not declared" in m for m in msgs), msgs
    assert any("'TORCHSTORE_TPU_DEAD'" in m and "dead knob" in m for m in msgs), msgs
    assert any("defaults must not fork" in m for m in msgs), msgs
    assert any("docs/API.md is missing" in m for m in msgs), msgs
    assert not any("TORCHSTORE_TPU_DYN_THING" in m for m in msgs), msgs
    assert len(msgs) == 4, msgs


def test_env_registry_bool_default_comparison(tmp_path):
    """bool registry defaults must compare by _env_bool semantics, not
    bool("0") truthiness: True vs "0" is drift, False vs "0" is not."""
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/config.py": """
                ENV_REGISTRY = (
                    EnvVar("TORCHSTORE_TPU_ON", "bool", True, "On knob."),
                    EnvVar("TORCHSTORE_TPU_OFF", "bool", False, "Off knob."),
                )
                """,
            "torchstore_tpu/m.py": """
                import os
                drift = os.environ.get("TORCHSTORE_TPU_ON", "0")   # seeded
                fine = os.environ.get("TORCHSTORE_TPU_OFF", "0")   # equivalent
                also = os.environ.get("TORCHSTORE_TPU_ON", "1")    # equivalent
                """,
        },
    )
    msgs = [
        m for m in _msgs(env_registry.check(proj)) if "defaults must not fork" in m
    ]
    assert len(msgs) == 1 and "TORCHSTORE_TPU_ON" in msgs[0], msgs


def test_env_registry_docs_block_roundtrip(tmp_path):
    entries, prefixes, _ = env_registry.parse_registry(
        textwrap.dedent(_FIXTURE_CONFIG)
    )
    assert [e.name for e in entries] == ["TORCHSTORE_TPU_FOO", "TORCHSTORE_TPU_DEAD"]
    assert prefixes == ["TORCHSTORE_TPU_DYN_"]
    table = env_registry.render_env_table(entries)
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/config.py": _FIXTURE_CONFIG,
            "torchstore_tpu/m.py": """
                import os
                a = os.environ.get("TORCHSTORE_TPU_FOO", "7")
                b = os.environ.get("TORCHSTORE_TPU_DEAD")
                """,
        },
    )
    docs = tmp_path / "docs" / "API.md"
    docs.parent.mkdir()
    docs.write_text(
        f"# API\n\n{env_registry.DOCS_BEGIN}\n{table}\n{env_registry.DOCS_END}\n"
    )
    assert env_registry.check(proj) == []
    # a stale table (entry edited without regen) is a finding
    docs.write_text(
        f"# API\n\n{env_registry.DOCS_BEGIN}\nstale\n{env_registry.DOCS_END}\n"
    )
    msgs = _msgs(env_registry.check(proj))
    assert any("stale" in m for m in msgs), msgs


# --------------------------------------------------------------------------
# 7. metric-discipline
# --------------------------------------------------------------------------


def test_metric_discipline_rules(tmp_path):
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/a.py": """
                from torchstore_tpu.observability import metrics as m
                _C = m.counter("ts_thing_total", "help")
                _BAD = m.gauge("Bad-Name", "not snake case")
                _NOPREFIX = m.counter("thing_total", "missing ts_")

                def use(key):
                    _C.inc(key=key)  # unbounded label: seeded defect
                    _C.inc(op="put")  # allowlisted: ok

                def trace():
                    with span("Bad Span"):  # seeded defect
                        pass
                    with span("rpc/put"):
                        pass
                """,
            "torchstore_tpu/b.py": """
                from torchstore_tpu.observability import metrics as m
                _G = m.gauge("ts_thing_total")
                """,
        },
    )
    msgs = _msgs(metric_discipline.check(proj))
    assert any("conflicting kinds" in m and "ts_thing_total" in m for m in msgs), msgs
    assert any("Bad-Name" in m and "snake_case" in m for m in msgs), msgs
    assert any("'thing_total'" in m and "prefix" in m for m in msgs), msgs
    assert any("label key 'key'" in m for m in msgs), msgs
    assert any("span name 'Bad Span'" in m for m in msgs), msgs
    assert len(msgs) == 5, msgs


def test_metric_docs_table_drift(tmp_path):
    """The generated docs/API.md metrics table is lint-enforced: missing
    markers, a stale table, and an up-to-date table each behave; fixture
    trees WITHOUT docs/API.md (every other test here) skip the rule."""
    src = {
        "torchstore_tpu/a.py": """
            from torchstore_tpu.observability import metrics as m
            _C = m.counter("ts_docs_total", "counted things")
            _G = m.gauge("ts_docs_gauge", "gauged things")
            """,
    }
    # No docs/API.md at all: rule silently skips (fixture-tree contract).
    proj = _project(tmp_path / "nodocs", src)
    assert _msgs(metric_discipline.check(proj)) == []
    # docs/API.md without markers: told to regen.
    proj = _project(
        tmp_path / "nomark", {**src, "docs/API.md": "# api\n"}
    )
    msgs = _msgs(metric_discipline.check(proj))
    assert any("markers" in m for m in msgs), msgs
    # Stale table between markers: drift finding.
    stale = (
        "# api\n\n"
        + metric_discipline.METRIC_DOCS_BEGIN
        + "\n| Metric | Kind | Description |\n|---|---|---|\n"
        + "| `ts_gone_total` | counter | deleted metric |\n"
        + metric_discipline.METRIC_DOCS_END
        + "\n"
    )
    proj = _project(tmp_path / "stale", {**src, "docs/API.md": stale})
    msgs = _msgs(metric_discipline.check(proj))
    assert any("stale" in m for m in msgs), msgs
    # Regenerated table: clean.
    proj = _project(tmp_path / "fresh", src)
    fresh_table = metric_discipline.render_metric_table(
        metric_discipline.collect_instruments(str(tmp_path / "fresh"), proj)
    )
    (tmp_path / "fresh" / "docs").mkdir()
    (tmp_path / "fresh" / "docs" / "API.md").write_text(
        "# api\n\n"
        + metric_discipline.METRIC_DOCS_BEGIN
        + "\n"
        + fresh_table
        + "\n"
        + metric_discipline.METRIC_DOCS_END
        + "\n"
    )
    assert _msgs(metric_discipline.check(proj)) == []
    assert "ts_docs_total" in fresh_table and "counted things" in fresh_table


# --------------------------------------------------------------------------
# history-discipline
# --------------------------------------------------------------------------


def test_history_discipline_rules(tmp_path):
    """Detector series selectors: literal + registered passes (including
    ``:rate`` derivations, label globs, and histogram ``_count`` series);
    a non-literal selector, a glob in the NAME part, and an unregistered
    name are each a finding."""
    proj = _project(
        tmp_path,
        {
            "torchstore_tpu/metrics_def.py": """
                from torchstore_tpu.observability import metrics as m
                _G = m.gauge("ts_landing_inflight", "open landing brackets")
                _C = m.counter("ts_client_ops_total", "client ops")
                _H = m.histogram("ts_op_seconds", "op latency")
                """,
            "torchstore_tpu/dets.py": """
                from torchstore_tpu.observability.detect import Detector

                SELECTOR = "ts_landing_inflight"

                GOOD = (
                    Detector(name="a", series="ts_landing_inflight", kind="sustained"),
                    Detector("b", 'ts_client_ops_total:rate{op="put"}', "ramp"),
                    Detector(name="c", series="ts_op_seconds_count", kind="drift"),
                    Detector(name="d", series='ts_landing_inflight{volume="*"}', kind="ramp"),
                    Detector(name="e", series="ts_landing_inflight*", kind="ramp"),
                )
                BAD_NONLITERAL = Detector(name="f", series=SELECTOR, kind="sustained")
                BAD_GLOB = Detector(name="g", series="ts_*_inflight", kind="sustained")
                BAD_UNREGISTERED = Detector(name="h", series="ts_gone_gauge", kind="drift")
                """,
        },
    )
    msgs = _msgs(history_discipline.check(proj))
    assert any("non-literal" in m for m in msgs), msgs
    assert any("globs the" in m and "ts_*_inflight" in m for m in msgs), msgs
    assert any(
        "does not resolve" in m and "ts_gone_gauge" in m for m in msgs
    ), msgs
    assert len(msgs) == 3, msgs


# --------------------------------------------------------------------------
# Framework: pragmas, baseline, runner
# --------------------------------------------------------------------------


def test_pragma_suppresses_findings(tmp_path):
    _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                import asyncio

                def spawn():
                    asyncio.create_task(work())  # tslint: disable=orphan-task
                """,
        },
    )
    result = run_checks(str(tmp_path), rules=["orphan-task"])
    assert result.findings == []


def test_file_pragma_suppresses_whole_file(tmp_path):
    _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                # tslint: disable-file=orphan-task
                import asyncio

                def spawn():
                    asyncio.create_task(work())
                """,
        },
    )
    result = run_checks(str(tmp_path), rules=["orphan-task"])
    assert result.findings == []


def test_baseline_splits_new_from_grandfathered(tmp_path):
    _project(
        tmp_path,
        {
            "torchstore_tpu/m.py": """
                import asyncio

                def one():
                    asyncio.create_task(work())
                """,
        },
    )
    # grandfather the current state
    result = run_checks(str(tmp_path), rules=["orphan-task"])
    assert len(result.new) == 1
    baseline = tmp_path / "baseline.json"
    save_baseline(str(baseline), result.findings)
    result = run_checks(
        str(tmp_path), rules=["orphan-task"], baseline_path=str(baseline)
    )
    assert result.new == [] and len(result.baselined) == 1
    # a SECOND, identical-message defect in the same file exceeds the count
    (tmp_path / "torchstore_tpu" / "m.py").write_text(
        textwrap.dedent(
            """
            import asyncio

            def one():
                asyncio.create_task(work())

            def two():
                asyncio.create_task(work())
            """
        )
    )
    result = run_checks(
        str(tmp_path), rules=["orphan-task"], baseline_path=str(baseline)
    )
    assert len(result.new) == 1 and len(result.baselined) == 1


def test_landing_copy_rules(tmp_path):
    """landing-copy: bare np.copyto in transport/landing modules is flagged;
    native.py and out-of-scope modules are exempt; the native helpers pass."""
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/transport/somexport.py": """
                import numpy as np
                def land(dst, src):
                    np.copyto(dst, src)  # seeded defect
            """,
            "torchstore_tpu/client.py": """
                import numpy as np
                from torchstore_tpu.native import copy_into
                def land(dst, src):
                    copy_into(dst, src)  # the sanctioned path
            """,
            "torchstore_tpu/native.py": """
                import numpy as np
                def fallback(dst, src):
                    np.copyto(dst, src)  # the fallback IS allowed here
            """,
            "torchstore_tpu/torch_interop.py": """
                import numpy as np
                def convert(dst, src):
                    np.copyto(dst, src)  # out of scope (not a landing module)
            """,
        },
    )
    findings = landing_copy.check(project)
    assert len(findings) == 1
    assert findings[0].path == "torchstore_tpu/transport/somexport.py"
    assert "np.copyto" in findings[0].message


def test_landing_copy_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/transport/x.py": """
                import numpy as np
                def land(dst, src):
                    np.copyto(dst, src)  # tslint: disable=landing-copy
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["landing-copy"])
    assert result.new == []


def test_retry_discipline_flags_bare_sleep_retry_loop(tmp_path):
    """retry-discipline: a constant-delay sleep inside a try-bearing loop is
    the ad-hoc retry idiom RetryPolicy replaced; policy-derived delays,
    pacing loops without exception handling, sleep(0) yields, and closures
    merely DEFINED inside a loop all pass."""
    from torchstore_tpu.analysis.checkers import retry_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/bad.py": """
                import asyncio, time
                async def drain():
                    while True:
                        try:
                            await push()
                            return
                        except ConnectionError:
                            await asyncio.sleep(1.0)  # seeded defect
                def sync_drain():
                    for _ in range(3):
                        try:
                            return push()
                        except OSError:
                            time.sleep(0.5)  # seeded defect
            """,
            "torchstore_tpu/good.py": """
                import asyncio
                async def drain(policy):
                    deadline = policy.start()
                    attempt = 0
                    while policy.should_retry(attempt, deadline):
                        try:
                            await push()
                            return
                        except ConnectionError:
                            await asyncio.sleep(policy.backoff(attempt))
                            attempt += 1
                async def pace(interval):
                    while True:
                        await asyncio.sleep(interval)  # pacing, no except
                async def batched():
                    while True:
                        try:
                            await one()
                        except ValueError:
                            pass
                        await asyncio.sleep(0)  # cooperative yield
                async def definer():
                    while True:
                        try:
                            spawn(lambda: time.sleep(1.0))
                            async def helper():
                                await asyncio.sleep(2.0)  # closure: opaque
                            return helper
                        except RuntimeError:
                            raise
            """,
        },
    )
    findings = retry_discipline.check(project)
    assert sorted((f.path, f.line) for f in findings) == [
        ("torchstore_tpu/bad.py", 9),
        ("torchstore_tpu/bad.py", 15),
    ]


def test_retry_discipline_flags_unregistered_faultpoint(tmp_path):
    from torchstore_tpu.analysis.checkers import retry_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/sites.py": """
                from torchstore_tpu import faults
                async def serve():
                    await faults.afire("volume.put")       # registered
                    faults.fire("volume.typo")             # drift
                    faults.arm("contoller.notify", "raise")  # drift
                    faults.fire(dynamic_name)              # out of scope
            """,
        },
    )
    findings = retry_discipline.check(project)
    assert len(findings) == 2
    assert all("not in faults.REGISTRY" in f.message for f in findings)
    assert {f.line for f in findings} == {5, 6}


def test_unknown_rule_rejected(tmp_path):
    (tmp_path / "torchstore_tpu").mkdir()
    with pytest.raises(ValueError, match="unknown rule"):
        run_checks(str(tmp_path), rules=["no-such-rule"])


# --------------------------------------------------------------------------
# The tier-1 gate: zero NEW findings on this repo
# --------------------------------------------------------------------------


def test_repo_is_clean_against_baseline():
    baseline = REPO_ROOT / DEFAULT_BASELINE
    assert baseline.exists(), "tslint_baseline.json must be committed"
    result = run_checks(str(REPO_ROOT), baseline_path=str(baseline))
    assert result.new == [], "NEW tslint findings:\n" + "\n".join(
        f.render() for f in result.new
    )
    assert set(result.rules) == set(CHECKERS)


def test_orphan_and_cancellation_rules_not_baselined_away():
    """Acceptance: the orphan-task and cancellation-swallow fixes landed
    WITH their checkers enabled — no grandfathered findings for either."""
    grandfathered = load_baseline(str(REPO_ROOT / DEFAULT_BASELINE))
    offenders = [
        key
        for key in grandfathered
        if key[0] in ("orphan-task", "cancellation-swallow")
    ]
    assert offenders == []


def test_cli_json_runs_clean():
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / "scripts" / "tslint.py"), "--json"],
        capture_output=True,
        text=True,
        cwd=str(REPO_ROOT),
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["new"] == 0
    assert sorted(doc["rules"]) == sorted(CHECKERS)


def test_cli_fail_on_new_reports_seeded_defect(tmp_path):
    """--fail-on-new gate mode: a synthetic endpoint typo added to a copy of
    the scan scope fails the run and names the typo."""
    _project(
        tmp_path,
        {
            "torchstore_tpu/vol.py": _ACTOR_SRC,
            "torchstore_tpu/caller.py": """
                async def go(ref):
                    await ref.putt.call_one(1, 2)
                """,
        },
    )
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO_ROOT / "scripts" / "tslint.py"),
            "--fail-on-new",
            "--rules",
            "endpoint-drift",
            "--root",
            str(tmp_path),
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "putt" in proc.stdout


# --------------------------------------------------------------------------
# one-sided-discipline
# --------------------------------------------------------------------------


def test_one_sided_discipline_flags_raw_segment_reads(tmp_path):
    """one-sided-discipline: raw seg.view/strided_view and frombuffer(mmap)
    reads in client/direct modules are flagged; the blessed accessors and
    out-of-scope modules (the transport itself, numpy dtype-views) pass."""
    from torchstore_tpu.analysis.checkers import one_sided

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/client.py": """
                import numpy as np
                def bad(seg, meta, plan):
                    a = seg.strided_view(meta, 0, None)  # seeded defect
                    b = seg.view(meta)  # seeded defect
                    c = np.frombuffer(seg.mmap, dtype=np.uint64)  # seeded
                    return a, b, c
                def fine(arr):
                    return arr.view(np.uint8)  # numpy dtype view: no segment
            """,
            "torchstore_tpu/direct_weight_sync.py": """
                from torchstore_tpu.transport import shared_memory as shm
                def good(seg, meta):
                    return shm.segment_read_view(seg, meta)  # blessed path
            """,
            "torchstore_tpu/transport/shared_memory.py": """
                def stamped_read(seg, meta):
                    return seg.strided_view(meta, 0, None)  # implements it
            """,
        },
    )
    findings = one_sided.check(project)
    assert len(findings) == 3
    assert all(f.path == "torchstore_tpu/client.py" for f in findings)
    assert all("segment_read_view" in f.message for f in findings)


def test_one_sided_discipline_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/direct_weight_sync.py": """
                def writer(seg, meta):
                    # writer side publishes the seqlock itself
                    return seg.view(meta)  # tslint: disable=one-sided-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["one-sided-discipline"])
    assert result.new == []


def test_stream_discipline_flags_raw_watermark_reads(tmp_path):
    """stream-discipline: raw ``["watermarks"]`` subscripts and
    ``.get("watermarks")`` in acquire-side modules are flagged; the
    blessed helpers' home (stream_sync.py) and out-of-scope modules (the
    controller implements the protocol) pass."""
    from torchstore_tpu.analysis.checkers import stream_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/weight_channel.py": """
                async def acquire(client, state, key, version):
                    wm = state["watermarks"][key]  # seeded defect
                    ok = state.get("watermarks")  # seeded defect
                    return wm, ok
            """,
            "torchstore_tpu/client.py": """
                from torchstore_tpu import stream_sync
                def fine(state, keys, version):
                    return stream_sync.inconsistent_keys(state, keys, version)
            """,
            "torchstore_tpu/stream_sync.py": """
                def watermark_of(state, key):
                    return (state.get("watermarks") or {}).get(key)
            """,
            "torchstore_tpu/controller.py": """
                def server_side(rec, key, version):
                    rec["watermarks"][key] = version  # protocol home
            """,
        },
    )
    findings = stream_discipline.check(project)
    assert len(findings) == 2
    assert all(f.path == "torchstore_tpu/weight_channel.py" for f in findings)
    assert all("watermark_of" in f.message for f in findings)


def test_stream_discipline_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/state_dict_utils.py": """
                def debug_dump(state):
                    return dict(state["watermarks"])  # tslint: disable=stream-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["stream-discipline"])
    assert result.new == []


def test_stream_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays
    empty): every acquire-side watermark check routes through
    stream_sync's blessed helpers."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["stream-discipline"])
    assert _msgs(result.findings, "stream-discipline") == []


def test_quant_discipline_flags_raw_scale_access(tmp_path):
    """quant-discipline: raw ``["scales"]`` subscripts / ``.get("scales")``
    in data-plane modules are flagged; the codec's home
    (state_dict_utils.py) and the arena-layout module (landing.py) pass."""
    from torchstore_tpu.analysis.checkers import quant_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/weight_channel.py": """
                def bad(marker, key):
                    s = marker["quant"]["scales"][key]  # seeded defect
                    t = marker.get("scales")  # seeded defect
                    return s, t
            """,
            "torchstore_tpu/transport/bulk.py": """
                def also_bad(blob_meta):
                    return blob_meta["scales"]  # seeded defect
            """,
            "torchstore_tpu/state_dict_utils.py": """
                def codec_home(info):
                    return info["scales"]  # the blessed home
            """,
            "torchstore_tpu/transport/landing.py": """
                def layout_home(layout):
                    return layout["scales"]  # the layout module
            """,
        },
    )
    findings = quant_discipline.check(project)
    by_path = {}
    for f in findings:
        by_path.setdefault(f.path, 0)
        by_path[f.path] += 1
    assert by_path == {
        "torchstore_tpu/weight_channel.py": 2,
        "torchstore_tpu/transport/bulk.py": 1,
    }, by_path


def test_quant_discipline_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/client.py": """
                def debug_dump(info):
                    return dict(info["scales"])  # tslint: disable=quant-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["quant-discipline"])
    assert result.new == []


def test_quant_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays
    empty): scale tables are only ever touched by the codec in
    state_dict_utils and the layout math in transport/landing.py."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["quant-discipline"])
    assert _msgs(result.findings, "quant-discipline") == []


def test_one_sided_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays empty):
    every client/direct segment read goes through the stamped helpers, and
    the one writer-side staging view carries its justified pragma."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["one-sided-discipline"])
    assert _msgs(result.findings, "one-sided-discipline") == []


# --------------------------------------------------------------------------
# shard-discipline (ISSUE 14)
# --------------------------------------------------------------------------


def test_shard_discipline_flags_raw_index_access(tmp_path):
    """shard-discipline: raw ``.index`` / ``._key_gens`` touches in the
    scoped modules (controller.py, client.py) are flagged; the metadata
    package (the state's home) and str/list ``.index(...)`` method calls
    pass."""
    from torchstore_tpu.analysis.checkers import shard_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def peek(self, key):
                        infos = self.index.get(key)  # seeded defect
                        gen = self._key_gens.get(key, 0)  # seeded defect
                        return infos, gen

                    def fine(self, keys):
                        return keys.index("a")  # list.index: a CALL, exempt
            """,
            "torchstore_tpu/client.py": """
                def bad(core):
                    return core.index["k"]  # seeded defect
            """,
            "torchstore_tpu/metadata/index_core.py": """
                class IndexCore:
                    def get(self, key):
                        return self.index.get(key)  # the state's home
            """,
            "torchstore_tpu/storage_volume.py": """
                def unscoped(store):
                    return store.index  # outside the metadata plane
            """,
        },
    )
    findings = shard_discipline.check(project)
    by_path = {}
    for f in findings:
        by_path.setdefault(f.path, 0)
        by_path[f.path] += 1
    assert by_path == {
        "torchstore_tpu/controller.py": 2,
        "torchstore_tpu/client.py": 1,
    }, by_path


def test_shard_discipline_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/controller.py": """
                def debug_dump(core):
                    return dict(core.index)  # tslint: disable=shard-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["shard-discipline"])
    assert result.new == []


def test_shard_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays
    empty): after the metadata-plane refactor, controller.py reaches the
    index only through ``self.idx`` (IndexCore locally, the RemoteIndex
    fan-out when sharded) — the property that makes shards=N safe."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["shard-discipline"])
    assert result.new == [], [str(f) for f in result.new]


# --------------------------------------------------------------------------
# 14. stage-discipline
# --------------------------------------------------------------------------


def test_stage_discipline_flags_uncataloged_and_nonliteral_stages(tmp_path):
    """stage-discipline: an ``observe_stage`` call with a literal stage
    outside STAGE_CATALOG is drift; a non-literal stage defeats the
    static guarantee; catalog entries pass; timeline.py itself (the
    catalog's home) is exempt."""
    from torchstore_tpu.analysis.checkers import stage_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/client.py": """
                from torchstore_tpu.observability import timeline as obs_timeline
                def fine(dur):
                    obs_timeline.observe_stage("get", "landing", dur)
                def drifted(dur):
                    obs_timeline.observe_stage("get", "landing_copy", dur)
                def laundered(stage, dur):
                    obs_timeline.observe_stage("get", stage, dur)
            """,
            "torchstore_tpu/observability/timeline.py": """
                def observe_stage(op, stage, dur_s):
                    _stages.observe(op, stage, dur_s)
            """,
        },
    )
    findings = stage_discipline.check(project)
    assert len(findings) == 2, [str(f) for f in findings]
    assert all(f.path == "torchstore_tpu/client.py" for f in findings)
    drift, nonliteral = sorted(findings, key=lambda f: f.line)
    assert "landing_copy" in drift.message
    assert "non-literal" in nonliteral.message


def test_stage_discipline_keyword_stage_checked(tmp_path):
    from torchstore_tpu.analysis.checkers import stage_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/storage_volume.py": """
                from torchstore_tpu.observability.timeline import observe_stage
                def serve(dur):
                    observe_stage("put", stage="stamp_verfy", dur_s=dur)
            """,
        },
    )
    findings = stage_discipline.check(project)
    assert len(findings) == 1
    assert "stamp_verfy" in findings[0].message


def test_stage_discipline_pragma(tmp_path):
    project = _project(
        tmp_path,
        {
            "torchstore_tpu/client.py": """
                from torchstore_tpu.observability import timeline as obs_timeline
                def experimental(dur):
                    obs_timeline.observe_stage("get", "prototype", dur)  # tslint: disable=stage-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["stage-discipline"])
    assert result.new == []


def test_stage_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays
    empty): every client- and volume-side stage segment records under a
    STAGE_CATALOG name, so the dominant-stage attribution in
    ``ts.slo_report()`` folds both sides into one taxonomy."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["stage-discipline"])
    assert _msgs(result.findings, "stage-discipline") == []


# --------------------------------------------------------------------------
# 15. control-discipline
# --------------------------------------------------------------------------


def test_control_discipline_flags_silent_actuation(tmp_path):
    """control-discipline: an actuator call (``migrate_key``, a
    ``tier_sweep`` endpoint wrapper, a ``_relay_prefer`` re-parent)
    inside ``control/`` with no decision-audit call in the same function
    is flagged; functions routing through ``self._decision(...)`` or
    ``record("decision", ...)`` pass, as do the same primitives outside
    the control package (auto-repair owns its own event discipline)."""
    from torchstore_tpu.analysis.checkers import control_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def silent_move(self, key, src, dst):
                        return await self.host.idx.migrate_key(
                            key, src, dst, drop_src=True
                        )  # seeded defect: no decision event

                    async def silent_demote(self, ref, keys):
                        await ref.tier_sweep.call_one({}, keys)  # seeded defect

                    def silent_reparent(self, host, channel, order):
                        host._relay_prefer[channel] = tuple(order)  # seeded defect

                    async def audited_move(self, snap, action):
                        await self.host.idx.migrate_key(
                            action.subject, action.src, action.dst, drop_src=True
                        )
                        return self._decision(snap, action, "applied")

                    def audited_reparent(self, host, channel, order, recorder):
                        host._relay_prefer[channel] = tuple(order)
                        recorder.record("decision", "control/relay", order=order)
            """,
            "torchstore_tpu/metadata/index_core.py": """
                async def migrate_key(self, key, src, dst, drop_src):
                    return await self._do_migrate(key, src, dst, drop_src)
            """,
            "torchstore_tpu/controller.py": """
                async def auto_repair(idx, key, src, dst):
                    return await idx.migrate_key(key, src, dst, drop_src=False)
            """,
        },
    )
    findings = control_discipline.check(project)
    assert all(f.path == "torchstore_tpu/control/engine.py" for f in findings)
    flagged = sorted(
        (msg.split("'")[1], msg.split("'")[3])
        for msg in _msgs(findings, "control-discipline")
    )
    assert flagged == [
        ("_relay_prefer", "silent_reparent"),
        ("migrate_key", "silent_move"),
        ("tier_sweep", "silent_demote"),
    ], flagged


def test_control_discipline_nested_scope_not_credited(tmp_path):
    """The audit call must live in the SAME function scope as the
    actuation — a ``_decision`` call inside a nested closure does not
    license the enclosing function's silent actuation."""
    from torchstore_tpu.analysis.checkers import control_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/control/engine.py": """
                async def outer(idx, key, src, dst, snap, action):
                    def audit_later():
                        return _decision(snap, action, "applied")
                    await idx.migrate_key(key, src, dst, drop_src=True)
                    return audit_later
            """,
        },
    )
    findings = control_discipline.check(project)
    assert len(findings) == 1, _msgs(findings)
    assert "'outer'" in findings[0].message


def test_control_discipline_pragma(tmp_path):
    from torchstore_tpu.analysis.checkers import control_discipline  # noqa: F401

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/control/engine.py": """
                async def bootstrap_copy(idx, key, src, dst):
                    # Bootstrap pre-seeding, not a policy action.
                    return await idx.migrate_key(key, src, dst, drop_src=False)  # tslint: disable=control-discipline
            """,
        },
    )
    result = run_checks(str(tmp_path), rules=["control-discipline"])
    assert result.new == []


def test_control_discipline_autoscale_scope(tmp_path):
    """ISSUE 18: the rule also covers ``torchstore_tpu/autoscale/`` and
    the fleet actuators (drain marking, retire detach/drop, blob
    demote/archive endpoint wrappers) — a silent scale actuation is
    flagged, an audited one passes, and the same names outside both
    planes stay out of scope (the api-layer spawn executor owns its own
    event discipline)."""
    from torchstore_tpu.analysis.checkers import control_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/autoscale/engine.py": """
                class Engine:
                    async def silent_drain(self, host, vid, dst, key):
                        host.mark_draining(vid)  # seeded defect
                        await host.idx.migrate_key(
                            key, vid, dst, drop_src=True
                        )  # seeded defect: no decision event

                    async def silent_demote(self, ref):
                        await ref.blob_sweep.call_one(8)  # seeded defect

                    async def audited_retire(self, host, vid, snap, action):
                        await host.idx.detach_volume(vid)
                        await host.drop_volume(vid)
                        return self._decision(snap, action, "applied")
            """,
            "torchstore_tpu/api.py": """
                async def spawn_executor(controller, vid, ref, hostname):
                    return await controller.attach_volume.call_one(
                        vid, ref, hostname
                    )
            """,
        },
    )
    findings = control_discipline.check(project)
    assert all(
        f.path == "torchstore_tpu/autoscale/engine.py" for f in findings
    )
    flagged = sorted(
        (msg.split("'")[1], msg.split("'")[3])
        for msg in _msgs(findings, "control-discipline")
    )
    assert flagged == [
        ("blob_sweep", "silent_demote"),
        ("mark_draining", "silent_drain"),
        ("migrate_key", "silent_drain"),
    ], flagged


def test_control_discipline_live_tree_clean():
    """The live tree stays clean under the new rule (baseline stays
    empty): every engine actuator path returns through ``_decision()``,
    the single chokepoint that stamps ``ts_control_decisions_total`` and
    the ``decision`` flight-recorder event."""
    root = str(pathlib.Path(__file__).resolve().parents[1])
    result = run_checks(root, rules=["control-discipline"])
    assert result.new == [], [str(f) for f in result.new]


# --------------------------------------------------------------------------
# 17. bracket-discipline (flow-aware, ISSUE 19)
# --------------------------------------------------------------------------


def test_bracket_discipline_catches_pr7_begin_landing_verbatim(tmp_path):
    """The exact PR 7 review finding, now mechanical: the pre-fix
    ``_begin_landing`` body where ``faults.afire`` can raise after
    ``begin_writes`` + ``_landing_open`` have run, leaking the inflight
    count and the odd stamps forever."""
    from torchstore_tpu.analysis.checkers import bracket_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/storage_volume.py": """
                from torchstore_tpu import faults

                class StorageVolume:
                    async def _begin_landing(self, pairs):
                        cache = self._shm_cache()
                        if cache is not None:
                            cache.begin_writes(pairs)
                        self._landing_open()
                        await faults.afire("shm.landing_stamp")

                    def _end_landing(self, pairs):
                        cache = self._shm_cache()
                        if cache is not None:
                            cache.end_writes(pairs)
                        self._landing_close()
            """,
        },
    )
    findings = bracket_discipline.check(project)
    raise_escapes = [f for f in findings if "raise can escape" in f.message]
    assert raise_escapes, [f.render() for f in findings]
    kinds = {f.message.split(" bracket", 1)[0] for f in raise_escapes}
    # Both the per-entry stamp bracket and the volume-wide inflight
    # counter leak on the raise path.
    assert "stamp-writes" in kinds and "landing-inflight" in kinds, kinds
    # And the NORMAL exit is licensed — _begin_landing's contract is to
    # return with the bracket open for the caller's try/finally.
    assert not any("return path" in f.message for f in findings), [
        f.render() for f in findings
    ]


def test_bracket_discipline_fixed_begin_landing_passes(tmp_path):
    """The shipped PR 7 fix shape (except BaseException: close; raise)
    is clean, with the open inside the guarded region."""
    from torchstore_tpu.analysis.checkers import bracket_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/storage_volume.py": """
                from torchstore_tpu import faults

                class StorageVolume:
                    async def _begin_landing(self, pairs):
                        cache = self._shm_cache()
                        if cache is not None:
                            cache.begin_writes(pairs)
                        try:
                            self._landing_open()
                            await faults.afire("shm.landing_stamp")
                        except BaseException:
                            self._end_landing(pairs)
                            raise

                    def _end_landing(self, pairs):
                        cache = self._shm_cache()
                        if cache is not None:
                            cache.end_writes(pairs)
                        self._landing_close()
            """,
        },
    )
    assert bracket_discipline.check(project) == []


def test_bracket_discipline_caller_must_close_on_all_paths(tmp_path):
    """A CALLER holding the landing bracket (it contains both begin and
    end) must close on every path: the try/finally idiom passes, a bare
    sequence is flagged on the raise path."""
    from torchstore_tpu.analysis.checkers import bracket_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/storage_volume.py": """
                class StorageVolume:
                    async def put_ok(self, pairs, reqs):
                        await self._begin_landing(pairs)
                        try:
                            await self._land(reqs)
                        finally:
                            self._end_landing(pairs)

                    async def put_leaky(self, pairs, reqs):
                        await self._begin_landing(pairs)
                        await self._land(reqs)
                        self._end_landing(pairs)
            """,
        },
    )
    findings = bracket_discipline.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "'put_leaky'" in findings[0].message
    assert "raise can escape" in findings[0].message


def test_bracket_discipline_lease_pairs_only_when_paired(tmp_path):
    """Acquire-only functions transfer lease ownership to their caller and
    are skipped; a function with both acquire and release must not leak
    on the return path."""
    from torchstore_tpu.analysis.checkers import bracket_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/weight_channel.py": """
                class Channel:
                    async def acquire_only(self, client, version):
                        return await client.lease_acquire("o", self.name, version)

                    async def leaky_paired(self, client, version):
                        lease = await client.lease_acquire("o", self.name, version)
                        if await self.fast_path(lease):
                            return lease["payload"]
                        await client.lease_release(lease["lease_id"])
                        return None
            """,
        },
    )
    findings = bracket_discipline.check(project)
    assert findings, "paired acquire/release with an escaping return must flag"
    assert all("'leaky_paired'" in f.message for f in findings), [
        f.render() for f in findings
    ]


def test_bracket_discipline_live_tree_clean():
    """The live tree is clean (baseline stays empty): every bracket open
    reaches its close on all paths, or carries a justified pragma (the
    lease handoff in weight_channel._pinned_lease)."""
    result = run_checks(str(REPO_ROOT), rules=["bracket-discipline"])
    assert result.new == [], [f.render() for f in result.new]


# --------------------------------------------------------------------------
# 18. epoch-discipline (flow-aware, ISSUE 19)
# --------------------------------------------------------------------------


def test_epoch_discipline_catches_missing_bump_on_one_branch(tmp_path):
    """The historical shape: a structural mutation whose epoch bump sits
    behind a condition the mutation does not share — one branch returns
    with clients still routing on the stale placement."""
    from torchstore_tpu.analysis.checkers import epoch_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def notify_delete_batch(self, keys):
                        by_volume = self.core.delete_keys(keys)
                        if self.quiet:
                            return by_volume
                        self._bump_epoch()
                        return by_volume
            """,
        },
    )
    findings = epoch_discipline.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "'delete_keys'" in findings[0].message
    assert "'notify_delete_batch'" in findings[0].message


def test_epoch_discipline_bump_on_every_path_passes(tmp_path):
    """Unconditional bump after the mutation passes; so does a bump routed
    through the coordinator endpoint wrapper, and a mutation whose only
    bump-free paths are explicit raises (the abort is not client-visible)."""
    from torchstore_tpu.analysis.checkers import epoch_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def delete_finish(self, keys):
                        by_volume = self.core.delete_keys(keys)
                        self._bump_epoch()
                        return by_volume

                    async def guarded(self, keys):
                        if self.sharded:
                            raise RuntimeError("route via shards")
                        out = self.core.delete_keys(keys)
                        self._bump_epoch()
                        return out
            """,
            "torchstore_tpu/metadata/shards.py": """
                class ControllerShard:
                    async def on_structural(self):
                        await self.coordinator.bump_placement_epoch.call_one()

                    async def drop(self, vid):
                        self.core.detach_volume(vid)
                        await self.coordinator.bump_placement_epoch.call_one()
            """,
        },
    )
    assert epoch_discipline.check(project) == []


def test_epoch_discipline_out_of_scope_files_exempt(tmp_path):
    """The same call names outside the three structural-state files are
    someone else's protocol (e.g. the autoscale engine calls detach_volume
    through the controller endpoint, which owns the bump)."""
    from torchstore_tpu.analysis.checkers import epoch_discipline

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/autoscale/engine.py": """
                class Engine:
                    async def retire(self, ref, vid):
                        await ref.detach_volume.call_one(vid)
            """,
        },
    )
    assert epoch_discipline.check(project) == []


def test_epoch_discipline_live_tree_clean():
    """The live tree is clean (baseline stays empty): every raw structural
    mutation is post-dominated by a bump, or carries a pragma naming the
    protocol that owns it (conditional-bump gates, the sharded 3-phase
    delete)."""
    result = run_checks(str(REPO_ROOT), rules=["epoch-discipline"])
    assert result.new == [], [f.render() for f in result.new]


# --------------------------------------------------------------------------
# 19. await-atomicity (flow-aware, ISSUE 19)
# --------------------------------------------------------------------------


def test_await_atomicity_catches_await_inside_publish_bracket(tmp_path):
    """An ``await`` injected between ``_publish_open`` and
    ``_publish_close`` parks the metadata seqlock odd for an unbounded
    time — every reader burns its torn-read retries."""
    from torchstore_tpu.analysis.checkers import await_atomicity

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/metadata/stamped.py": """
                import asyncio

                class MetaStampWriter:
                    async def publish_now(self, blob):
                        seq = self._publish_open()
                        self.words[2] = len(blob)
                        await asyncio.sleep(0)
                        self._publish_close(seq)
            """,
        },
    )
    findings = await_atomicity.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "await suspends" in findings[0].message
    assert "'publish_now'" in findings[0].message


def test_await_atomicity_blocking_call_in_bracket_flagged_sync_too(tmp_path):
    """async_blocking's table is reused: a known-blocking call between the
    open and close wedges readers even in a sync writer."""
    from torchstore_tpu.analysis.checkers import await_atomicity

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/metadata/stamped.py": """
                import time

                class MetaStampWriter:
                    def publish_now(self, blob):
                        seq = self._publish_open()
                        time.sleep(0.01)
                        self._publish_close(seq)
            """,
        },
    )
    findings = await_atomicity.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "known-blocking call (sleep)" in findings[0].message


def test_await_atomicity_clean_bracket_and_awaits_outside_pass(tmp_path):
    from torchstore_tpu.analysis.checkers import await_atomicity

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/metadata/stamped.py": """
                import asyncio

                class MetaStampWriter:
                    async def publish_now(self, payload_fn):
                        blob = await asyncio.to_thread(payload_fn)
                        seq = self._publish_open()
                        self.words[2] = len(blob)
                        self._publish_close(seq)
                        await asyncio.sleep(0)
            """,
        },
    )
    assert await_atomicity.check(project) == []


def test_await_atomicity_catches_lock_skipping_dict_mutation(tmp_path):
    """The PR 18 ledger-singleton race shape: one async path mutates a
    shared dict under the module's asyncio.Lock, a second path mutates it
    with no lock held — the lock guards nothing."""
    from torchstore_tpu.analysis.checkers import await_atomicity

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/puller.py": """
                import asyncio

                class Puller:
                    def __init__(self):
                        self._conns = {}
                        self._lock = asyncio.Lock()

                    async def get_conn(self, key):
                        async with self._lock:
                            if key not in self._conns:
                                self._conns[key] = dial(key)
                        return self._conns[key]

                    async def close(self):
                        self._conns.clear()
            """,
        },
    )
    findings = await_atomicity.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "'_conns'" in findings[0].message
    assert "'close'" in findings[0].message


def test_await_atomicity_lock_held_everywhere_passes(tmp_path):
    from torchstore_tpu.analysis.checkers import await_atomicity

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/puller.py": """
                import asyncio

                class Puller:
                    def __init__(self):
                        self._conns = {}
                        self._lock = asyncio.Lock()

                    async def get_conn(self, key):
                        async with self._lock:
                            if key not in self._conns:
                                self._conns[key] = dial(key)
                            return self._conns[key]

                    async def close(self):
                        async with self._lock:
                            self._conns.clear()

                    async def read_only_ok(self, key):
                        return self._conns.get(key)
            """,
        },
    )
    assert await_atomicity.check(project) == []


def test_await_atomicity_live_tree_clean():
    """The live tree is clean (baseline stays empty): the stamp-bracket
    landing path is deliberately NOT in the atomic set (holding across the
    awaited landing copy is the design), and every shared dict mutation
    takes its module's lock."""
    result = run_checks(str(REPO_ROOT), rules=["await-atomicity"])
    assert result.new == [], [f.render() for f in result.new]


# --------------------------------------------------------------------------
# 20. decision-flow (flow-aware, ISSUE 19)
# --------------------------------------------------------------------------


def test_decision_flow_catches_early_return_skipping_audit(tmp_path):
    """The control-discipline blind spot, closed: the function DOES call
    ``_decision`` (same scope — the old rule passes), but an early return
    between the actuation and the audit leaves an unrecorded mutation."""
    from torchstore_tpu.analysis.checkers import control_discipline, decision_flow

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def apply_move(self, snap, action):
                        await self.host.idx.migrate_key(
                            action.subject, action.src, action.dst, drop_src=True
                        )
                        if snap.quiet:
                            return None
                        return self._decision(snap, action, "applied")
            """,
        },
    )
    # Same-scope rule is blind to this by design...
    assert control_discipline.check(project) == []
    # ...the flow-aware rule is not.
    findings = decision_flow.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "'migrate_key'" in findings[0].message
    assert "'apply_move'" in findings[0].message


def test_decision_flow_post_dominating_and_dominating_audits_pass(tmp_path):
    """Both sanctioned idioms pass: act-then-return-_decision on every
    branch (the _apply_* shape), and audit-before-act (the checkpoint
    shape). An exception edge out of the actuator is exempt — _apply's
    wrapper funnels the error through _decision itself."""
    from torchstore_tpu.analysis.checkers import decision_flow

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/autoscale/engine.py": """
                class Engine:
                    async def apply_retire(self, snap, action):
                        await self.ref.detach_volume.call_one(action.vid)
                        if snap.drop:
                            await self.ref.drop_volume.call_one(action.vid)
                            return self._decision(snap, action, "dropped")
                        return self._decision(snap, action, "detached")

                    async def checkpoint(self, snap, action, ref):
                        self._decision(snap, action, "archiving")
                        await ref.blob_archive.call_one(action.vid)
            """,
        },
    )
    assert decision_flow.check(project) == []


def test_decision_flow_relay_reparent_needs_audit_on_path(tmp_path):
    from torchstore_tpu.analysis.checkers import decision_flow

    project = _project(
        tmp_path,
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    def reparent(self, host, channel, order, snap, action):
                        host._relay_prefer[channel] = tuple(order)
                        if not self.verbose:
                            return
                        self._decision(snap, action, "reparented")
            """,
        },
    )
    findings = decision_flow.check(project)
    assert len(findings) == 1, [f.render() for f in findings]
    assert "'_relay_prefer'" in findings[0].message


def test_decision_flow_live_tree_clean():
    """The live tree is clean (baseline stays empty): every engine
    actuator is dominated or post-dominated by its decision event on
    every normal path."""
    result = run_checks(str(REPO_ROOT), rules=["decision-flow"])
    assert result.new == [], [f.render() for f in result.new]


# --------------------------------------------------------------------------
# Fixture completeness: every registered rule has a dirty AND a clean fixture
# --------------------------------------------------------------------------

_ENV_ENTRIES, _ENV_PREFIXES, _ = env_registry.parse_registry(
    textwrap.dedent(_FIXTURE_CONFIG)
)
_ENV_DOCS_OK = (
    "# API\n\n"
    + env_registry.DOCS_BEGIN
    + "\n"
    + env_registry.render_env_table(_ENV_ENTRIES)
    + "\n"
    + env_registry.DOCS_END
    + "\n"
)

_STAGE_TIMELINE_STUB = """
    def observe_stage(op, stage, dur_s):
        _stages.observe(op, stage, dur_s)
    """

# rule -> (dirty fixture files, clean fixture files). The meta-test below
# holds this table to the CHECKERS registry, so registering rule #21 without
# a detectable-defect fixture and a quiet fixture fails tier-1 immediately —
# a rule nobody can demonstrate firing is a no-op waiting to happen.
RULE_FIXTURES = {
    "endpoint-drift": (
        {
            "torchstore_tpu/vol.py": _ACTOR_SRC,
            "torchstore_tpu/caller.py": """
                async def go(ref, buf, metas):
                    await ref.putt.call_one(buf, metas)
                """,
        },
        {
            "torchstore_tpu/vol.py": _ACTOR_SRC,
            "torchstore_tpu/caller.py": """
                async def go(ref, buf, metas):
                    await ref.put.call_one(buf, metas)
                """,
        },
    ),
    "async-blocking": (
        {
            "torchstore_tpu/m.py": """
                import time
                async def f():
                    time.sleep(1)
                """,
        },
        {
            "torchstore_tpu/m.py": """
                import asyncio
                async def f():
                    await asyncio.sleep(1)
                """,
        },
    ),
    "cancellation-swallow": (
        {
            "torchstore_tpu/m.py": """
                async def f(op):
                    try:
                        await op()
                    except BaseException:
                        pass
                """,
        },
        {
            "torchstore_tpu/m.py": """
                async def f(op):
                    try:
                        await op()
                    except BaseException:
                        cleanup()
                        raise
                """,
        },
    ),
    "orphan-task": (
        {
            "torchstore_tpu/m.py": """
                import asyncio
                def spawn():
                    asyncio.create_task(work())
                """,
        },
        {
            "torchstore_tpu/m.py": """
                import asyncio
                async def spawn():
                    t = asyncio.create_task(work())
                    await t
                """,
        },
    ),
    "fork-safety": (
        {
            "torchstore_tpu/m.py": """
                import threading
                _registry = {}
                """,
        },
        {
            "torchstore_tpu/m.py": """
                _registry = {}

                def reinit_after_fork():
                    _registry.clear()
                """,
        },
    ),
    "env-registry": (
        {
            "torchstore_tpu/config.py": _FIXTURE_CONFIG,
            "torchstore_tpu/m.py": """
                import os
                bad = os.environ.get("TORCHSTORE_TPU_BAR")
                """,
        },
        {
            "torchstore_tpu/config.py": _FIXTURE_CONFIG,
            "torchstore_tpu/m.py": """
                import os
                ok = os.environ.get("TORCHSTORE_TPU_FOO", "7")
                dead = os.environ.get("TORCHSTORE_TPU_DEAD")
                """,
            "docs/API.md": _ENV_DOCS_OK,
        },
    ),
    "metric-discipline": (
        {
            "torchstore_tpu/m.py": """
                from torchstore_tpu.observability import metrics as m
                _BAD = m.gauge("Bad-Name", "not snake case")
                """,
        },
        {
            "torchstore_tpu/m.py": """
                from torchstore_tpu.observability import metrics as m
                _C = m.counter("ts_thing_total", "help")
                """,
        },
    ),
    "landing-copy": (
        {
            "torchstore_tpu/transport/somexport.py": """
                import numpy as np
                def land(dst, src):
                    np.copyto(dst, src)
                """,
        },
        {
            "torchstore_tpu/transport/somexport.py": """
                from torchstore_tpu.native import copy_into
                def land(dst, src):
                    copy_into(dst, src)
                """,
        },
    ),
    "retry-discipline": (
        {
            "torchstore_tpu/m.py": """
                import asyncio
                async def drain():
                    while True:
                        try:
                            await push()
                            return
                        except ConnectionError:
                            await asyncio.sleep(1.0)
                """,
        },
        {
            "torchstore_tpu/m.py": """
                import asyncio
                async def drain(policy):
                    attempt = 0
                    while policy.should_retry(attempt):
                        try:
                            await push()
                            return
                        except ConnectionError:
                            await asyncio.sleep(policy.backoff(attempt))
                            attempt += 1
                """,
        },
    ),
    "one-sided-discipline": (
        {
            "torchstore_tpu/client.py": """
                def bad(seg, meta):
                    return seg.view(meta)
                """,
        },
        {
            "torchstore_tpu/client.py": """
                from torchstore_tpu.transport import shared_memory as shm
                def good(seg, meta):
                    return shm.segment_read_view(seg, meta)
                """,
        },
    ),
    "stream-discipline": (
        {
            "torchstore_tpu/weight_channel.py": """
                async def acquire(state, key):
                    return state["watermarks"][key]
                """,
        },
        {
            "torchstore_tpu/weight_channel.py": """
                from torchstore_tpu import stream_sync
                def fine(state, keys, version):
                    return stream_sync.inconsistent_keys(state, keys, version)
                """,
        },
    ),
    "quant-discipline": (
        {
            "torchstore_tpu/weight_channel.py": """
                def bad(marker):
                    return marker.get("scales")
                """,
        },
        {
            "torchstore_tpu/state_dict_utils.py": """
                def codec_home(info):
                    return info["scales"]
                """,
        },
    ),
    "shard-discipline": (
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def peek(self, key):
                        return self.index.get(key)
                """,
        },
        {
            "torchstore_tpu/metadata/index_core.py": """
                class IndexCore:
                    def get(self, key):
                        return self.index.get(key)
                """,
        },
    ),
    "mirror-discipline": (
        {
            "torchstore_tpu/metadata/router.py": """
                from torchstore_tpu.metadata import stamped as stamped_mod
                def attach(desc):
                    return stamped_mod.MetaStampReader(
                        desc["segment"], desc["size"]
                    )
                """,
        },
        {
            "torchstore_tpu/metadata/router.py": """
                from torchstore_tpu.metadata import stamped as stamped_mod
                def attach(desc):
                    return stamped_mod.attach_reader(desc)
                """,
        },
    ),
    "stage-discipline": (
        {
            "torchstore_tpu/client.py": """
                from torchstore_tpu.observability import timeline as obs_timeline
                def drifted(dur):
                    obs_timeline.observe_stage("get", "landing_copy", dur)
                """,
            "torchstore_tpu/observability/timeline.py": _STAGE_TIMELINE_STUB,
        },
        {
            "torchstore_tpu/client.py": """
                from torchstore_tpu.observability import timeline as obs_timeline
                def fine(dur):
                    obs_timeline.observe_stage("get", "landing", dur)
                """,
            "torchstore_tpu/observability/timeline.py": _STAGE_TIMELINE_STUB,
        },
    ),
    "control-discipline": (
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def silent_move(self, key, src, dst):
                        return await self.host.idx.migrate_key(
                            key, src, dst, drop_src=True
                        )
                """,
        },
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def audited_move(self, snap, action):
                        await self.host.idx.migrate_key(
                            action.subject, action.src, action.dst, drop_src=True
                        )
                        return self._decision(snap, action, "applied")
                """,
        },
    ),
    "history-discipline": (
        {
            "torchstore_tpu/dets.py": """
                from torchstore_tpu.observability.detect import Detector
                SELECTOR = "ts_landing_inflight"
                BAD = Detector(name="f", series=SELECTOR, kind="sustained")
                """,
        },
        {
            "torchstore_tpu/metrics_def.py": """
                from torchstore_tpu.observability import metrics as m
                _G = m.gauge("ts_landing_inflight", "open landing brackets")
                """,
            "torchstore_tpu/dets.py": """
                from torchstore_tpu.observability.detect import Detector
                GOOD = Detector(
                    name="a", series="ts_landing_inflight", kind="sustained"
                )
                """,
        },
    ),
    "bracket-discipline": (
        {
            "torchstore_tpu/storage_volume.py": """
                class StorageVolume:
                    async def put_leaky(self, pairs, reqs):
                        await self._begin_landing(pairs)
                        await self._land(reqs)
                        self._end_landing(pairs)
                """,
        },
        {
            "torchstore_tpu/storage_volume.py": """
                class StorageVolume:
                    async def put_ok(self, pairs, reqs):
                        await self._begin_landing(pairs)
                        try:
                            await self._land(reqs)
                        finally:
                            self._end_landing(pairs)
                """,
        },
    ),
    "epoch-discipline": (
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def notify_delete_batch(self, keys):
                        by_volume = self.core.delete_keys(keys)
                        if self.loud:
                            self._bump_epoch()
                        return by_volume
                """,
        },
        {
            "torchstore_tpu/controller.py": """
                class Controller:
                    async def notify_delete_batch(self, keys):
                        by_volume = self.core.delete_keys(keys)
                        self._bump_epoch()
                        return by_volume
                """,
        },
    ),
    "await-atomicity": (
        {
            "torchstore_tpu/metadata/stamped.py": """
                import asyncio
                class MetaStampWriter:
                    async def publish_now(self, blob):
                        seq = self._publish_open()
                        await asyncio.sleep(0)
                        self._publish_close(seq)
                """,
        },
        {
            "torchstore_tpu/metadata/stamped.py": """
                class MetaStampWriter:
                    def publish_now(self, blob):
                        seq = self._publish_open()
                        self.words[2] = len(blob)
                        self._publish_close(seq)
                """,
        },
    ),
    "decision-flow": (
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def apply_move(self, snap, action):
                        await self.host.idx.migrate_key(
                            action.subject, action.src, action.dst, drop_src=True
                        )
                        if snap.quiet:
                            return None
                        return self._decision(snap, action, "applied")
                """,
        },
        {
            "torchstore_tpu/control/engine.py": """
                class Engine:
                    async def apply_move(self, snap, action):
                        await self.host.idx.migrate_key(
                            action.subject, action.src, action.dst, drop_src=True
                        )
                        return self._decision(snap, action, "applied")
                """,
        },
    ),
}


def test_rule_fixtures_cover_every_registered_rule():
    """Registering a rule without fixtures is itself a tier-1 failure."""
    assert set(RULE_FIXTURES) == set(CHECKERS), (
        "every rule in CHECKERS needs a (dirty, clean) entry in RULE_FIXTURES: "
        f"missing={sorted(set(CHECKERS) - set(RULE_FIXTURES))} "
        f"stale={sorted(set(RULE_FIXTURES) - set(CHECKERS))}"
    )
    assert len(CHECKERS) == 21, sorted(CHECKERS)


@pytest.mark.parametrize("rule", sorted(CHECKERS))
def test_rule_dirty_fixture_detects(rule, tmp_path):
    dirty, _clean = RULE_FIXTURES[rule]
    findings = CHECKERS[rule](_project(tmp_path, dirty))
    assert findings, f"{rule}: dirty fixture produced no finding"
    assert all(f.rule == rule for f in findings), [f.render() for f in findings]


@pytest.mark.parametrize("rule", sorted(CHECKERS))
def test_rule_clean_fixture_is_quiet(rule, tmp_path):
    _dirty, clean = RULE_FIXTURES[rule]
    findings = CHECKERS[rule](_project(tmp_path, clean))
    assert findings == [], [f.render() for f in findings]


# --------------------------------------------------------------------------
# Runtime budget, per-rule timing, SARIF (ISSUE 19 satellites)
# --------------------------------------------------------------------------


def test_full_gate_budget_timing_and_sarif(tmp_path):
    """One full 21-rule gate over the live tree, in a fresh interpreter the
    way CI runs it: must finish well under the 30 s budget (parallel
    checkers + the parse cache), expose per-rule wall time in the JSON
    report, and emit a SARIF 2.1.0 log whose rule table matches the
    registry — with zero results, because the tree is clean."""
    import time

    sarif_path = tmp_path / "gate.sarif"
    t0 = time.monotonic()
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO_ROOT / "scripts" / "tslint.py"),
            "--fail-on-new",
            "--json",
            "--sarif",
            str(sarif_path),
        ],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 30.0, f"tslint gate took {elapsed:.1f}s (budget: 30s)"

    doc = json.loads(proc.stdout)
    assert len(doc["rules"]) == 21, doc["rules"]
    assert doc["new"] == 0
    assert set(doc["rule_seconds"]) == set(doc["rules"])
    assert all(v >= 0.0 for v in doc["rule_seconds"].values())

    sarif = json.loads(sarif_path.read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    rules = run["tool"]["driver"]["rules"]
    assert sorted(r["id"] for r in rules) == sorted(doc["rules"])
    assert all(r["shortDescription"]["text"] for r in rules)
    assert all(r["help"]["text"] for r in rules)
    assert run["results"] == []


def test_sarif_fingerprints_and_baseline_states(tmp_path):
    """SARIF results carry the repo's line-independent finding identity:
    the fingerprint survives the finding moving to another line, and a
    baselined finding is emitted as note/unchanged rather than error/new."""
    from torchstore_tpu.analysis.sarif import to_sarif

    src = """
        import asyncio

        def spawn():
            asyncio.create_task(work())
        """
    _project(tmp_path, {"torchstore_tpu/m.py": src})
    result = run_checks(str(tmp_path), rules=["orphan-task"])
    doc = to_sarif(result, CHECKERS)
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "orphan-task"
    assert res["level"] == "error" and res["baselineState"] == "new"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "torchstore_tpu/m.py"
    fp = res["partialFingerprints"]["tslintIdentity/v1"]

    # Shift the defect down three lines: identity (and fingerprint) stable.
    (tmp_path / "torchstore_tpu" / "m.py").write_text(
        "\n\n\n" + textwrap.dedent(src)
    )
    shifted = to_sarif(run_checks(str(tmp_path), rules=["orphan-task"]), CHECKERS)
    (res2,) = shifted["runs"][0]["results"]
    assert res2["partialFingerprints"]["tslintIdentity/v1"] == fp
    assert res2["locations"][0]["physicalLocation"]["region"]["startLine"] != loc[
        "region"
    ]["startLine"]

    # Grandfathered: same result, downgraded presentation.
    baseline = tmp_path / "baseline.json"
    save_baseline(str(baseline), result.findings)
    gated = run_checks(
        str(tmp_path), rules=["orphan-task"], baseline_path=str(baseline)
    )
    doc3 = to_sarif(gated, CHECKERS)
    (res3,) = doc3["runs"][0]["results"]
    assert res3["level"] == "note" and res3["baselineState"] == "unchanged"
    assert res3["partialFingerprints"]["tslintIdentity/v1"] == fp
