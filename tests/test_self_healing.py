"""Self-healing fleet (ISSUE 6): deterministic faultpoints, the controller
health supervisor (quarantine -> probation -> reinstatement, auto
re-replication), and the unified RetryPolicy retry/failover paths."""

import asyncio
import os
import time

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import faults
from torchstore_tpu.config import RetryPolicy
from torchstore_tpu.strategy import LocalRankStrategy


# --------------------------------------------------------------------------
# RetryPolicy (config.py) — the one retry vocabulary
# --------------------------------------------------------------------------


def test_retry_policy_exponential_schedule():
    p = RetryPolicy(
        base_s=0.1, max_s=1.0, multiplier=2.0, jitter=0.0, deadline_s=5.0
    )
    assert [p.backoff(i) for i in range(5)] == [0.1, 0.2, 0.4, 0.8, 1.0]
    assert p.max_attempts is None  # deadline-limited, not attempt-limited


def test_retry_policy_jitter_bounds():
    p = RetryPolicy(base_s=1.0, max_s=1.0, multiplier=1.0, jitter=0.25)
    for _ in range(50):
        d = p.backoff(0)
        assert 0.75 <= d <= 1.25


def test_retry_policy_explicit_delays():
    p = RetryPolicy.from_delays(("1", 5, 15.0))
    assert p.max_attempts == 3
    assert p.delays == (1.0, 5.0, 15.0)
    # Past-the-end attempts reuse the last delay; should_retry caps them.
    assert p.backoff(10) == pytest.approx(15.0, rel=0.11)
    d = p.start()
    assert p.should_retry(2, d) and not p.should_retry(3, d)
    with pytest.raises(ValueError):
        RetryPolicy.from_delays(())


def test_retry_policy_deadline_budget():
    p = RetryPolicy(deadline_s=0.05, jitter=0.0)
    d = p.start()
    assert p.should_retry(0, d)
    time.sleep(0.06)
    assert not p.should_retry(0, d)


def test_retry_policy_rides_store_config():
    import pickle

    from torchstore_tpu.config import StoreConfig

    cfg = StoreConfig(retry=RetryPolicy(base_s=0.01, deadline_s=1.0))
    clone = pickle.loads(pickle.dumps(cfg))
    assert clone.retry.base_s == 0.01 and clone.retry.deadline_s == 1.0


# --------------------------------------------------------------------------
# faults.py — process-local framework
# --------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _disarm_local_faults():
    yield
    faults.disarm()


def test_disarmed_faultpoint_is_a_noop():
    assert faults.fire("volume.put") is None
    assert faults.armed() == []


def test_arm_fire_count_and_self_disarm():
    faults.arm("volume.put", "raise", count=2)
    for _ in range(2):
        with pytest.raises(faults.FaultInjectedError):
            faults.fire("volume.put")
    assert faults.fire("volume.put") is None  # budget consumed: self-disarmed
    assert faults.armed() == []


def test_arm_validates_names_and_actions():
    with pytest.raises(ValueError, match="unknown faultpoint"):
        faults.arm("volume.typo", "raise")
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.arm("volume.put", "explode")
    with pytest.raises(ValueError, match="count"):
        faults.arm("volume.put", "raise", count=0)
    with pytest.raises(ValueError, match="prob"):
        faults.arm("volume.put", "raise", prob=1.5)


def test_drop_frame_returns_sentinel():
    faults.arm("bulk.send_frame", "drop-frame", count=1)
    assert faults.fire("bulk.send_frame") == "drop-frame"
    assert faults.fire("bulk.send_frame") is None


async def test_async_fire_delay_action():
    faults.arm("controller.notify", "delay", count=1, delay_ms=30)
    t0 = time.monotonic()
    assert await faults.afire("controller.notify") is None
    assert time.monotonic() - t0 >= 0.025
    assert await faults.afire("controller.notify") is None  # disarmed


def test_parse_spec_roundtrip():
    specs = faults.parse_spec(
        "volume.put=raise:count=2; actor.ping=wedge ;"
        "bulk.recv_frame=drop-frame:prob=0.5:delay_ms=10"
    )
    assert specs == [
        {"name": "volume.put", "action": "raise", "count": 2},
        {"name": "actor.ping", "action": "wedge"},
        {
            "name": "bulk.recv_frame",
            "action": "drop-frame",
            "prob": 0.5,
            "delay_ms": 10.0,
        },
    ]
    with pytest.raises(ValueError):
        faults.parse_spec("volume.put")  # no action
    with pytest.raises(ValueError):
        faults.parse_spec("volume.put=raise:bogus=1")


def test_env_arming_after_fork_reinit(monkeypatch):
    monkeypatch.setenv(
        faults.ENV_FAULTPOINTS, "controller.locate=raise:count=1"
    )
    faults.reinit_after_fork()
    try:
        assert [s["name"] for s in faults.armed()] == ["controller.locate"]
    finally:
        monkeypatch.delenv(faults.ENV_FAULTPOINTS)
        faults.reinit_after_fork()
    assert faults.armed() == []


def test_registry_covers_documented_sites():
    # The tslint retry-discipline checker cross-references call sites
    # against this registry; the registry itself must cover every site
    # family the docstring promises.
    for name in (
        "controller.notify",
        "controller.locate",
        "volume.put",
        "volume.get",
        "volume.handshake",
        "shm.handshake",
        "actor.ping",
        "bulk.send_frame",
        "bulk.recv_frame",
        "rendezvous.dispatch",
    ):
        assert name in faults.REGISTRY


# --------------------------------------------------------------------------
# fleet integration: inject_fault RPC, retry/failover, supervisor
# --------------------------------------------------------------------------


async def test_inject_fault_reaches_forked_volume_and_put_retries():
    """Arm volume.put=raise inside an already-running volume process via the
    control RPC; the non-replicated put absorbs the injected failure through
    the unified retry (transport demotion) instead of surfacing it."""
    await ts.initialize(store_name="sh_put")
    try:
        await ts.put("k", np.ones(4, np.float32), store_name="sh_put")
        armed = await ts.inject_fault(
            "volume.put", "raise", count=1, store_name="sh_put"
        )
        assert any(t.startswith("volume:") for t in armed)
        listed = await ts.client("sh_put")._volume_refs[
            next(iter(ts.client("sh_put")._volume_refs))
        ].actor.list_faults.call_one()
        assert listed and listed[0]["name"] == "volume.put"
        await ts.put("k", np.full(4, 2.0, np.float32), store_name="sh_put")
        np.testing.assert_array_equal(
            await ts.get("k", store_name="sh_put"),
            np.full(4, 2.0, np.float32),
        )
        from torchstore_tpu.observability import metrics as obs_metrics

        snap = obs_metrics.metrics_snapshot()
        retries = snap.get("ts_client_put_retries_total", {}).get("series", [])
        assert sum(s["value"] for s in retries) >= 1
    finally:
        await ts.shutdown("sh_put")


async def test_get_fails_over_through_injected_fault():
    """volume.get=raise on every volume: the first fetch attempt surfaces
    the injected fault internally; the RetryPolicy-driven failover retries
    and the caller never sees an error."""
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="sh_get",
    )
    try:
        want = np.arange(16.0, dtype=np.float32)
        await ts.put("k", want, store_name="sh_get")
        await ts.inject_fault(
            "volume.get", "raise", count=1, scope="volumes",
            store_name="sh_get",
        )
        np.testing.assert_array_equal(
            await ts.get("k", store_name="sh_get"), want
        )
        assert await ts.clear_faults(store_name="sh_get") >= 0
    finally:
        await ts.shutdown("sh_get")


async def _wait_for(predicate, timeout=20.0, interval=0.15, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = await predicate()
        if result:
            return result
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


async def _kill_volume(store_name: str, volume_id: str) -> None:
    from torchstore_tpu import api

    client = ts.client(store_name)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap[volume_id]["ref"]
    handle = api._stores[store_name]
    for mesh in [handle.volume_mesh, *(handle.repair_meshes or [])]:
        if mesh is None:
            continue
        for idx, ref in enumerate(mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = mesh._processes[idx]
                proc.kill()
                proc.join(5)
                return
    raise AssertionError(f"no process found for volume {volume_id!r}")


@pytest.fixture
def fast_health(monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "2")


async def test_supervisor_quarantines_dead_volume_and_auto_repairs(
    fast_health,
):
    """Kill one of three volumes: the supervisor quarantines it with NO
    manual repair call, locate stops returning it, the replicated key is
    re-replicated onto the remaining healthy volume, and gets keep
    working throughout."""
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="sh_sup",
    )
    try:
        want = np.arange(64.0, dtype=np.float32)
        await ts.put("k", want, store_name="sh_sup")
        client = ts.client("sh_sup")
        located = await client.controller.locate_volumes.call_one(["k"])
        victim = sorted(located["k"])[0]
        await _kill_volume("sh_sup", victim)

        async def quarantined():
            vh = await ts.volume_health("sh_sup")
            return vh[victim]["state"] == "quarantined"

        await _wait_for(quarantined, what=f"quarantine of {victim}")

        # Auto-repair restores 2 healthy copies without ts.repair().
        async def rereplicated():
            loc = await client.controller.locate_volumes.call_one(["k"])
            vids = set(loc["k"])
            return victim not in vids and len(vids) == 2

        await _wait_for(rereplicated, what="auto re-replication")
        np.testing.assert_array_equal(
            await ts.get("k", store_name="sh_sup"), want
        )
        # The supervisor's verdict rides stats() for fleet dashboards.
        stats = await client.controller.stats.call_one()
        assert stats["volume_health"][victim]["state"] == "quarantined"
    finally:
        await ts.shutdown("sh_sup")


async def test_supervisor_probation_then_reinstatement(fast_health):
    """A volume whose pings fail transiently (injected, self-disarming) is
    quarantined, then reinstated through probation once it answers again —
    and new puts route around it only while it is quarantined."""
    await ts.initialize(num_storage_volumes=2, store_name="sh_prob")
    try:
        client = ts.client("sh_prob")
        await client._ensure_setup()
        victim = sorted(client._volume_refs)[0]
        # 5 failing pings: 2 misses quarantine it (threshold 2), the rest
        # keep it down ~3 sweeps, then pings succeed again on their own.
        await ts.inject_fault(
            "actor.ping", "raise", count=5, scope=victim,
            store_name="sh_prob",
        )

        async def state_is(state):
            async def check():
                vh = await ts.volume_health("sh_prob")
                return vh[victim]["state"] == state

            return check

        await _wait_for(
            await state_is("quarantined"), what="quarantine"
        )
        await _wait_for(
            await state_is("ok"), what="reinstatement through probation"
        )
        vh = await ts.volume_health("sh_prob")
        assert vh[victim] == {"state": "ok", "misses": 0, "oks": 0} or (
            vh[victim]["state"] == "ok"
        )
    finally:
        await ts.shutdown("sh_prob")


async def test_puts_route_around_quarantined_volume(fast_health):
    """While a volume is quarantined, non-replicated puts select a healthy
    volume instead (placement-epoch bump -> health refresh -> avoid set)."""
    await ts.initialize(num_storage_volumes=2, store_name="sh_route")
    try:
        client = ts.client("sh_route")
        await client._ensure_setup()
        victim = sorted(client._volume_refs)[0]
        await _kill_volume("sh_route", victim)

        async def quarantined():
            vh = await ts.volume_health("sh_route")
            return vh[victim]["state"] == "quarantined"

        await _wait_for(quarantined, what="quarantine")
        # Sync the client's health view, then land a burst of puts: every
        # one must succeed and index on the surviving volume.
        await client.placement_epoch()
        if client._volumes_stale:
            await client._refresh_health()
        for i in range(4):
            await ts.put(
                f"r{i}", np.full(8, float(i), np.float32),
                store_name="sh_route",
            )
        located = await client.controller.locate_volumes.call_one(
            [f"r{i}" for i in range(4)]
        )
        for i in range(4):
            assert victim not in located[f"r{i}"]
            np.testing.assert_array_equal(
                await ts.get(f"r{i}", store_name="sh_route"),
                np.full(8, float(i), np.float32),
            )
    finally:
        await ts.shutdown("sh_route")


async def test_supervisor_disabled_by_interval_zero(monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0")
    await ts.initialize(store_name="sh_off")
    try:
        await ts.put("k", np.ones(2, np.float32), store_name="sh_off")
        vh = await ts.volume_health("sh_off")
        assert all(h["state"] == "ok" for h in vh.values())
    finally:
        await ts.shutdown("sh_off")
