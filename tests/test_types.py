import pickle

import numpy as np
import pytest

from torchstore_tpu.transport.types import Request, TensorSlice
from torchstore_tpu.utils import Box


def make_slice(**kw):
    defaults = dict(
        offsets=(0, 0),
        local_shape=(2, 4),
        global_shape=(4, 4),
        coordinates=(0,),
        mesh_shape=(2,),
    )
    defaults.update(kw)
    return TensorSlice(**defaults)


class TestTensorSlice:
    def test_box(self):
        ts = make_slice(offsets=(2, 0))
        assert ts.box == Box((2, 0), (2, 4))
        assert ts.nelements == 8

    def test_full(self):
        assert make_slice(local_shape=(4, 4)).is_full()
        assert not make_slice().is_full()

    def test_numpy_ints_normalized(self):
        ts = make_slice(offsets=(np.int64(1), np.int64(0)))
        assert ts.offsets == (1, 0) and type(ts.offsets[0]) is int

    def test_rank_mismatch(self):
        with pytest.raises(ValueError):
            make_slice(offsets=(0,))

    def test_with_box(self):
        ts = make_slice()
        sub = ts.with_box(Box((1, 1), (1, 2)))
        assert sub.offsets == (1, 1) and sub.local_shape == (1, 2)
        assert sub.global_shape == ts.global_shape


class TestRequest:
    def test_from_tensor(self):
        r = Request.from_tensor("k", np.ones((2, 2)))
        assert r.nbytes == 32 and not r.is_object

    def test_from_objects(self):
        r = Request.from_objects("k", {"a": 1})
        assert r.is_object and r.objects == {"a": 1}

    def test_slice_shape_validation(self):
        with pytest.raises(ValueError, match="local_shape"):
            Request.from_tensor_slice("k", make_slice(), np.ones((3, 3)))

    def test_meta_only_strips_data(self):
        r = Request.from_tensor_slice("k", make_slice(), np.ones((2, 4)))
        m = r.meta_only()
        assert m.tensor_val is None and m.tensor_slice == r.tensor_slice
        o = Request.from_objects("k", {"big": "payload"}).meta_only()
        assert o.objects is None and o.is_object

    def test_pickle_strips_destination_view(self):
        r = Request.from_tensor("k", np.ones(4))
        r.destination_view = np.zeros(4)
        r2 = pickle.loads(pickle.dumps(r))
        assert r2.destination_view is None
        np.testing.assert_array_equal(r2.tensor_val, r.tensor_val)
