"""HostStrategy mapping, MoE expert-parallel store round trip, and failure
behavior (volume death, failed-put consistency) — the strategy x fault axes
of the reference suite (tests/utils.py strategy params, fault injection).

Fault injection here rides the deterministic faultpoint framework
(``torchstore_tpu/faults.py`` + ``ts.inject_fault``): faults are armed
INSIDE the already-forked volume processes over the control RPC, replacing
the old idiom of monkeypatching client-side helpers (which could never
reach a forked volume's put path) — only whole-process kills (SIGKILL/
SIGSTOP) remain as raw OS operations, since dying is the one fault a
process cannot inject into itself and keep serving the injection RPC."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import HostStrategy
from torchstore_tpu.runtime import Actor, ActorDiedError, endpoint, spawn_actors


# HostStrategy on one physical host needs per-volume hostname envs; spawn
# through the runtime directly to emulate two hosts.
async def test_host_strategy_two_emulated_hosts():
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.runtime import get_or_spawn_singleton, stop_singleton
    from torchstore_tpu.storage_volume import StorageVolume

    strategy = HostStrategy()
    mesh = await spawn_actors(
        2,
        StorageVolume,
        "hostvols",
        strategy,
        env_fn=lambda r: {"TORCHSTORE_TPU_HOSTNAME": f"host{r}"},
    )
    controller = await get_or_spawn_singleton("hosts_ctrl", Controller)
    try:
        info = await controller.init.call_one(strategy, mesh.refs)
        assert sorted(info["volume_ids"]) == ["host0", "host1"]
        from torchstore_tpu.client import LocalClient

        import os

        os.environ["TORCHSTORE_TPU_HOSTNAME"] = "host1"
        try:
            client = LocalClient(controller)
            await client.put("k", np.arange(4.0))
            np.testing.assert_array_equal(await client.get("k"), np.arange(4.0))
            # The data landed on host1's volume.
            located = await controller.locate_volumes.call_one(["k"])
            assert list(located["k"].keys()) == ["host1"]
        finally:
            del os.environ["TORCHSTORE_TPU_HOSTNAME"]
    finally:
        await stop_singleton("hosts_ctrl")
        await mesh.stop()


async def test_host_strategy_duplicate_ids_rejected():
    # Two volumes on one real host under HostStrategy -> duplicate volume
    # ids; initialize must fail loudly AND clean up its spawned processes.
    with pytest.raises(Exception, match="duplicate volume id"):
        await ts.initialize(
            num_storage_volumes=2, strategy=HostStrategy(), store_name="dup"
        )
    from torchstore_tpu import api

    assert "dup" not in api._stores  # no half-initialized record


async def test_moe_expert_parallel_roundtrip():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from torchstore_tpu import parallel
    from torchstore_tpu.models.llama import Llama, LlamaConfig

    await ts.initialize(store_name="moe")
    try:
        cfg = LlamaConfig.tiny_moe()
        model = Llama(cfg)
        mesh = parallel.make_mesh({"dp": 2, "ep": 4})
        boxed = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
        params = parallel.unbox(parallel.shard_params(boxed, mesh))
        # Expert kernels are sharded over ep.
        w_gate = params["params"]["layer_0"]["mlp"]["gate_proj"]
        from jax.sharding import PartitionSpec as P

        assert w_gate.sharding.spec[0] == "ep"
        await ts.put_state_dict("moe/v0", {"params": params}, store_name="moe")
        # Pull onto a tp-only mesh (cross-mesh expert reshard; tp=4 so the
        # 4-expert axis stays divisible).
        mesh2 = parallel.make_mesh({"tp": 4})
        like = parallel.unbox(parallel.shard_params(boxed, mesh2))
        out = await ts.get_state_dict(
            "moe/v0", user_state_dict={"params": like}, store_name="moe"
        )
        ref = parallel.unbox(boxed)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(out["params"])[0],
            jax.tree_util.tree_flatten_with_path(ref)[0],
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    finally:
        await ts.shutdown("moe")


async def test_volume_death_surfaces_cleanly():
    await ts.initialize(store_name="death")
    try:
        await ts.put("k", np.ones(4), store_name="death")
        # Kill the volume process out from under the store.
        from torchstore_tpu import api

        handle = api._stores["death"]
        for proc in handle.volume_mesh._processes:
            proc.terminate()
            proc.join(5)
        with pytest.raises((ActorDiedError, ConnectionError, OSError)):
            await ts.get("k", store_name="death")
    finally:
        from torchstore_tpu import api

        api._stores.pop("death", None)
        from torchstore_tpu.runtime import stop_singleton

        await stop_singleton("ts_death_controller")


async def test_wedged_volume_times_out_with_diagnosis():
    """A SIGSTOP'd (alive-but-stuck) volume must not hang clients forever:
    the configured rpc_timeout fires and the error carries the controller's
    health diagnosis (VERDICT r1 item 4 — the supervision role Monarch
    plays for the reference)."""
    import os
    import signal

    from torchstore_tpu.config import StoreConfig
    from torchstore_tpu.runtime import ActorTimeoutError

    await ts.initialize(
        store_name="wedge", config=StoreConfig(rpc_timeout=2.0)
    )
    procs = []
    try:
        await ts.put("k", np.ones(4), store_name="wedge")
        from torchstore_tpu import api

        handle = api._stores["wedge"]
        procs = list(handle.volume_mesh._processes)
        for proc in procs:
            os.kill(proc.pid, signal.SIGSTOP)
        t0 = __import__("time").monotonic()
        with pytest.raises(ActorDiedError) as exc_info:
            await ts.get("k", store_name="wedge")
        elapsed = __import__("time").monotonic() - t0
        assert elapsed < 30, f"timeout took {elapsed:.1f}s (must be bounded)"
        assert "diagnosis" in str(exc_info.value)
        assert "wedged" in str(exc_info.value)  # not misreported as dead
        # The underlying cause is a timeout, not a dead connection.
        assert isinstance(exc_info.value.__cause__, ActorTimeoutError)
    finally:
        for proc in procs:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        await ts.shutdown("wedge")


async def test_killed_volume_mid_use_diagnosed_dead():
    """Kill -9 the volume between put and get: the client error must name
    the volume and include the controller's 'dead' diagnosis."""
    await ts.initialize(store_name="diag")
    try:
        await ts.put("k", np.ones(4), store_name="diag")
        from torchstore_tpu import api

        handle = api._stores["diag"]
        for proc in handle.volume_mesh._processes:
            proc.kill()
            proc.join(5)
        with pytest.raises(ActorDiedError) as exc_info:
            await ts.get("k", store_name="diag")
        msg = str(exc_info.value)
        assert "diagnosis" in msg and "dead" in msg
    finally:
        from torchstore_tpu import api

        api._stores.pop("diag", None)
        from torchstore_tpu.runtime import stop_singleton

        await stop_singleton("ts_diag_controller")


async def test_failed_put_leaves_store_consistent():
    await ts.initialize(store_name="consist")
    try:
        await ts.put("k", np.ones(4), store_name="consist")
        # Type-confusion put fails server-side AFTER transport shipped data.
        with pytest.raises(ValueError, match="already stored"):
            await ts.put("k", {"obj": 1}, store_name="consist")
        # Store still serves the original value; controller index intact.
        np.testing.assert_array_equal(
            await ts.get("k", store_name="consist"), np.ones(4)
        )
        assert await ts.keys(store_name="consist") == ["k"]
    finally:
        await ts.shutdown("consist")


async def test_dcn_bind_and_advertise_env():
    # Cross-host wiring on one machine: volumes bind 0.0.0.0 and must
    # advertise a reachable address; the full data path still works.
    import os

    os.environ["TORCHSTORE_TPU_BIND_HOST"] = "0.0.0.0"
    os.environ["TORCHSTORE_TPU_ADVERTISE_HOST"] = "127.0.0.1"
    try:
        await ts.initialize(store_name="dcn")
        try:
            client = ts.client("dcn")
            await client._ensure_setup()
            ref = next(iter(client._volume_refs.values()))
            assert ref.actor.host == "127.0.0.1"  # advertised, not 0.0.0.0
            x = np.random.rand(1024, 256).astype(np.float32)  # 1 MB
            await ts.put("w", x, store_name="dcn")
            np.testing.assert_array_equal(await ts.get("w", store_name="dcn"), x)
        finally:
            await ts.shutdown("dcn")
    finally:
        del os.environ["TORCHSTORE_TPU_BIND_HOST"]
        del os.environ["TORCHSTORE_TPU_ADVERTISE_HOST"]


async def test_replicated_put_detaches_faulted_replica():
    """Regression for the replicated-put detach path, driven by an ARMED
    faultpoint inside the replica's own process (not a client-side patch):
    a replica whose put keeps failing is detached in the same notify that
    indexes the landed copies — readers only ever see volumes holding the
    new bytes — and the next clean put restores full replication."""
    from torchstore_tpu.strategy import LocalRankStrategy

    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="fp_detach",
    )
    try:
        client = ts.client("fp_detach")
        await client._ensure_setup()
        targets = [v.volume_id for v in client._put_volumes()]
        assert len(targets) == 2
        # Every put to the SECOND target fails until disarmed.
        await ts.inject_fault(
            "volume.put", "raise", count=1000, scope=targets[1],
            store_name="fp_detach",
        )
        await ts.put("k", np.arange(8.0, dtype=np.float32), store_name="fp_detach")
        located = await client.controller.locate_volumes.call_one(["k"])
        assert set(located["k"]) == {targets[0]}  # faulted replica detached
        np.testing.assert_array_equal(
            await ts.get("k", store_name="fp_detach"),
            np.arange(8.0, dtype=np.float32),
        )
        # Disarm; the next put re-replicates onto both targets again.
        await ts.clear_faults(store_name="fp_detach")
        await ts.put("k", np.arange(8.0, dtype=np.float32) + 1, store_name="fp_detach")
        located = await client.controller.locate_volumes.call_one(["k"])
        assert set(located["k"]) == set(targets)
    finally:
        await ts.shutdown("fp_detach")


async def test_injected_volume_death_diagnosed_dead():
    """The 'die' action (os._exit mid-operation, armed over the control
    RPC) reproduces a real volume crash across the process boundary: the
    client error carries the controller's 'dead' diagnosis."""
    await ts.initialize(store_name="fp_die")
    try:
        await ts.put("k", np.ones(4), store_name="fp_die")
        await ts.inject_fault("volume.get", "die", store_name="fp_die")
        with pytest.raises(ActorDiedError) as exc_info:
            await ts.get("k", store_name="fp_die")
        msg = str(exc_info.value)
        assert "diagnosis" in msg and "dead" in msg
    finally:
        from torchstore_tpu import api

        api._stores.pop("fp_die", None)
        from torchstore_tpu.runtime import stop_singleton

        await stop_singleton("ts_fp_die_controller")


async def test_partial_commit_counts_as_exists_but_not_readable():
    # Fault-injection analog of the reference's ranks_to_skip_put helper:
    # one missing shard keeps the key readable=False, exists=True.
    await ts.initialize(store_name="skip")
    try:
        sl = ts.TensorSlice(
            offsets=(0, 0), local_shape=(2, 4), global_shape=(4, 4),
            coordinates=(0,), mesh_shape=(2,),
        )
        await ts.put("w", ts.Shard(np.ones((2, 4), np.float32), sl), store_name="skip")
        assert await ts.exists("w", store_name="skip")
        with pytest.raises(KeyError, match="partially committed"):
            await ts.get("w", store_name="skip")
    finally:
        await ts.shutdown("skip")
