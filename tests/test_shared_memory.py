"""SHM transport tests: segment round-trips, descriptor-reuse handshake,
staged-get ownership transfer, cache invalidation, lease/retire/free pool
bookkeeping (reference tests/test_shared_memory.py; end-to-end zero-copy
semantics live in test_zero_copy.py)."""

import os

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.config import StoreConfig
from torchstore_tpu.transport import shared_memory as shm
from torchstore_tpu.transport.buffers import TransportContext
from torchstore_tpu.transport.shared_memory import (
    ShmClientCache,
    ShmDescriptor,
    ShmSegment,
    ShmServerCache,
    SharedMemoryTransportBuffer,
)
from torchstore_tpu.transport.types import Request, TensorMeta

pytestmark = pytest.mark.skipif(
    not shm.is_available(), reason="/dev/shm not available"
)


class TestSegment:
    def test_create_view_attach_roundtrip(self):
        seg = ShmSegment.create(64)
        meta = TensorMeta(shape=(4, 4), dtype="float32")
        seg.view(meta)[:] = np.arange(16.0).reshape(4, 4)
        other = ShmSegment.attach(seg.name, seg.size)
        np.testing.assert_array_equal(
            other.view(meta), np.arange(16.0).reshape(4, 4)
        )
        seg.unlink()
        assert not os.path.exists(os.path.join(shm.SHM_DIR, seg.name))

    def test_unlink_idempotent(self):
        seg = ShmSegment.create(8)
        seg.unlink()
        seg.unlink()

    def test_attach_missing_raises(self):
        with pytest.raises(FileNotFoundError):
            ShmSegment.attach("ts_shm_never_existed", 8)


class TestServerCache:
    def test_put_replaces_and_pools(self):
        cache = ShmServerCache()
        a = ShmSegment.create(16)
        b = ShmSegment.create(16)
        meta = TensorMeta(shape=(4,), dtype="float32")
        cache.put("k", None, a, meta)
        cache.put("k", None, b, meta)
        # Replaced (unleased) segments are recycled, not unlinked: the next
        # put of this size reuses the warm segment.
        assert os.path.exists(os.path.join(shm.SHM_DIR, a.name))
        cache.delete_key("k")
        assert not os.path.exists(os.path.join(shm.SHM_DIR, b.name))
        # take_free transfers ownership to the caller (the put adopting it)
        assert cache.take_free(16) is a
        assert cache.take_free(16) is None
        a.unlink()
        cache.clear()

    def test_retired_until_released(self):
        cache = ShmServerCache()
        a = ShmSegment.create(16)
        b = ShmSegment.create(16)
        meta = TensorMeta(shape=(4,), dtype="float32")
        cache.put("k", None, a, meta)
        cache.grant(a.name)  # an outstanding zero-copy view lease
        cache.put("k", None, b, meta)
        # Leased segment is retired (still linked, never recycled) until the
        # client reports the view released.
        assert a.name in cache.retired
        assert cache.take_free(16) is None
        assert os.path.exists(os.path.join(shm.SHM_DIR, a.name))
        cache.apply_releases({"client": "c1", "batches": [(1, {a.name: 1})]})
        # Retransmission of the same batch must be a no-op (exactly-once).
        cache.apply_releases({"client": "c1", "batches": [(1, {a.name: 1})]})
        assert a.name not in cache.retired
        assert cache.take_free(16) is a
        cache.clear()

    def test_shard_coords_tracked_separately(self):
        cache = ShmServerCache()
        meta = TensorMeta(shape=(4,), dtype="float32")
        s0, s1 = ShmSegment.create(16), ShmSegment.create(16)
        cache.put("k", (0,), s0, meta)
        cache.put("k", (1,), s1, meta)
        assert cache.lookup("k", (0,)).seg is s0
        assert len(cache.segments_for("k")) == 2
        cache.clear()
        assert not os.path.exists(os.path.join(shm.SHM_DIR, s0.name))


class TestPoolWarmer:
    async def test_warms_in_idle_window(self):
        import asyncio
        import time as _time

        cache = ShmServerCache()
        cache.last_activity = _time.monotonic() - 5.0  # store is idle
        cache.schedule_warm([4096, 4096])
        for _ in range(50):
            await asyncio.sleep(0.05)
            if len(cache.free_by_size.get(4096, ())) == 2:
                break
        assert len(cache.free_by_size.get(4096, ())) == 2
        a = cache.take_free(4096)
        assert a is not None and a.size == 4096
        a.unlink()
        cache.clear()

    async def test_warms_under_load(self):
        """With MAP_POPULATE, warming is one batched kernel call on an
        executor thread — it completes regardless of store activity (the
        old trap-per-page prefault deferred under load; that slow path
        survives only on platforms without MAP_POPULATE)."""
        import asyncio
        import time as _time

        import pytest

        if not ShmSegment._POPULATE:
            pytest.skip("platform lacks MAP_POPULATE")
        cache = ShmServerCache()
        cache.last_activity = _time.monotonic()  # live traffic
        cache.schedule_warm([4096])
        for _ in range(50):
            await asyncio.sleep(0.05)
            if cache.free_by_size.get(4096):
                break
        assert cache.free_by_size.get(4096)  # warmed despite activity
        cache.clear()

    def test_no_loop_is_noop(self):
        cache = ShmServerCache()
        cache.schedule_warm([4096])  # no running loop: silently skipped
        assert cache.take_free(4096) is None


class TestBufferUnit:
    def test_pickle_strips_client_state(self):
        import pickle

        buf = SharedMemoryTransportBuffer(StoreConfig())
        buf._client_segments[0] = "not-picklable-marker"
        buf.descriptors[0] = ShmDescriptor("n", 8, TensorMeta((2,), "float32"))
        b2 = pickle.loads(pickle.dumps(buf))
        # config travels (the volume side reads pool-cap overrides from it);
        # only live client-process state is stripped.
        assert b2._client_segments == {} and b2.config is not None
        assert b2.descriptors[0].segment_name == "n"

    def test_handshake_offers_pooled_never_live(self):
        """Puts must never be offered the LIVE entry segment (a writer
        would race concurrent reads of it); pooled segments of the right
        size are offered instead (warm rotation)."""
        ctx = TransportContext()
        cache = ctx.get_cache(ShmServerCache)
        seg = ShmSegment.create(16)
        meta = TensorMeta((4,), "float32")
        cache.put("k", None, seg, meta)
        buf = SharedMemoryTransportBuffer()
        req = Request.from_tensor("k", np.zeros(4, np.float32)).meta_only()
        # Live segment, empty pool -> nothing offered (client allocates).
        assert buf.recv_handshake(ctx, [req], {}, "put") == {}
        pooled = ShmSegment.create(16)
        cache._add_free(pooled)
        offered = buf.recv_handshake(ctx, [req], {}, "put")
        assert offered[0].segment_name == pooled.name != seg.name
        assert pooled.name in cache.reserved  # held until the put RPC
        # Size-mismatched request -> no offer.
        req2 = Request.from_tensor("k", np.zeros(8, np.float32)).meta_only()
        assert buf.recv_handshake(ctx, [req2], {}, "put") == {}
        cache.clear()


@pytest.fixture
async def store():
    await ts.initialize(
        store_name="shm",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    yield "shm"
    await ts.shutdown("shm")


async def test_forced_shm_roundtrip(store):
    x = np.random.rand(128, 64).astype(np.float32)
    await ts.put("w", x, store_name=store)
    np.testing.assert_array_equal(await ts.get("w", store_name=store), x)


async def test_overwrite_reuses_segment(store):
    x = np.zeros((64, 64), np.float32)
    await ts.put("w", x, store_name=store)
    # Overwrite with same shape/dtype: handshake must offer the old segment.
    y = np.random.rand(64, 64).astype(np.float32)
    await ts.put("w", y, store_name=store)
    np.testing.assert_array_equal(await ts.get("w", store_name=store), y)


async def test_objects_ride_shm_buffer(store):
    await ts.put("obj", {"a": [1, 2]}, store_name=store)
    assert await ts.get("obj", store_name=store) == {"a": [1, 2]}


async def test_slice_get_staged_segment_cleaned(store):
    import asyncio as _asyncio

    x = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    await ts.put("w", x, store_name=store)
    want = ts.TensorSlice(
        offsets=(2, 0), local_shape=(3, 8), global_shape=(8, 8),
        coordinates=(), mesh_shape=(),
    )
    # Steady-state leak check: the volume's background pool warming also
    # creates ts_shm_ segments on its own executor-thread schedule (a
    # single before/after diff races it — the warm create lands whenever
    # the thread runs, not when the get returns). A REAL staged-segment
    # leak grows /dev/shm by one segment PER GET; pool warming reaches a
    # steady census after the first serve. So: warm once, settle, then
    # assert repeated slice gets leave the census flat (one in-flight
    # warm segment of slack).
    out = await ts.get("w", like=want, store_name=store)
    np.testing.assert_array_equal(out, x[2:5])
    await _asyncio.sleep(0.3)
    before = sum(
        1 for n in os.listdir(shm.SHM_DIR) if n.startswith("ts_shm_")
    )
    reps = 4
    for _ in range(reps):
        out = await ts.get("w", like=want, store_name=store)
        np.testing.assert_array_equal(out, x[2:5])
    await _asyncio.sleep(0.3)
    after = sum(
        1 for n in os.listdir(shm.SHM_DIR) if n.startswith("ts_shm_")
    )
    assert after - before < reps, (
        f"staged segments leaked: {before} -> {after} over {reps} gets"
    )


async def test_delete_unlinks_segments(store):
    await ts.put("w", np.ones((32, 32), np.float32), store_name=store)
    # Find volume-owned segments for this store.
    await ts.get("w", store_name=store)
    await ts.delete("w", store_name=store)
    with pytest.raises(KeyError):
        await ts.get("w", store_name=store)


async def test_large_tensor_shm(store):
    x = np.random.rand(1024, 1024).astype(np.float32)  # 4 MB
    await ts.put("big", x, store_name=store)
    out = await ts.get("big", store_name=store)
    np.testing.assert_array_equal(out, x)


async def test_shm_no_segment_leak_after_shutdown():
    before = {n for n in os.listdir(shm.SHM_DIR) if n.startswith("ts_shm_")}
    await ts.initialize(
        store_name="shmleak",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    await ts.put("a", np.ones((16, 16), np.float32), store_name="shmleak")
    await ts.put("b", np.ones((8,), np.float32), store_name="shmleak")
    await ts.get("a", store_name="shmleak")
    await ts.shutdown("shmleak")
    after = {n for n in os.listdir(shm.SHM_DIR) if n.startswith("ts_shm_")}
    assert after <= before, f"leaked: {after - before}"


def test_reap_orphaned_segments():
    # A segment named with a genuinely dead pid gets reaped; a live-pid
    # segment stays. Use a real exited child's pid (no magic numbers —
    # pid_max can exceed any constant).
    import multiprocessing as mp
    import uuid as _uuid

    proc = mp.get_context("spawn").Process(target=int)
    proc.start()
    proc.join()
    dead_pid = proc.pid
    dead = ShmSegment.create(
        8, name=f"ts_shm_{dead_pid}_{_uuid.uuid4().hex[:8]}"
    )
    alive = ShmSegment.create(8)  # our own pid
    try:
        reaped = shm.reap_orphaned_segments()
        assert reaped >= 1
        assert not os.path.exists(os.path.join(shm.SHM_DIR, dead.name))
        assert os.path.exists(os.path.join(shm.SHM_DIR, alive.name))
    finally:
        dead.unlink()
        alive.unlink()


async def test_adopted_segment_survives_client_death(store):
    # The put's client-created segment is renamed to the VOLUME's pid on
    # adoption, so the reaper can never unlink live volume storage after
    # the creating client exits.
    x = np.random.rand(16, 16).astype(np.float32)
    await ts.put("adopt", x, store_name=store)
    # Reap with this client still alive: nothing of ours may vanish, and a
    # subsequent get served from volume-owned segments must work.
    shm.reap_orphaned_segments()
    np.testing.assert_array_equal(await ts.get("adopt", store_name=store), x)
    # Overwrite still reuses (descriptor now carries the volume-pid name).
    y = np.random.rand(16, 16).astype(np.float32)
    await ts.put("adopt", y, store_name=store)
    np.testing.assert_array_equal(await ts.get("adopt", store_name=store), y)
