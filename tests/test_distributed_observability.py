"""The distributed observability plane (PR 2): trace-context propagation
across real actor processes, fleet metrics aggregation, the live HTTP
exporter, and the hot-key/slow-op profiler."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchstore_tpu.observability import aggregate
from torchstore_tpu.observability import context as obs_context
from torchstore_tpu.observability import http_exporter
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import profile as obs_profile
from torchstore_tpu.observability import tracing


# --------------------------------------------------------------------------
# trace context (in-process semantics)
# --------------------------------------------------------------------------


class TestTraceContext:
    def test_no_context_by_default(self):
        assert obs_context.current() is None

    def test_ensure_root_creates_and_restores(self):
        with obs_context.ensure_root():
            ctx = obs_context.current()
            assert ctx is not None and ctx["trace_id"]
            # Nested ensure_root joins, never re-roots.
            with obs_context.ensure_root():
                assert obs_context.current()["trace_id"] == ctx["trace_id"]
        assert obs_context.current() is None

    def test_activate_adopts_rpc_carried_context(self):
        with obs_context.activate({"trace_id": "t1", "parent_span_id": "s9"}):
            assert obs_context.current() == {
                "trace_id": "t1",
                "parent_span_id": "s9",
            }
        assert obs_context.current() is None
        with obs_context.activate(None):  # untraced callers cost nothing
            assert obs_context.current() is None

    def test_spans_chain_parent_ids(self, tmp_path):
        collector = tracing.collector()
        old = collector.path
        collector.path = str(tmp_path / "trace.json")
        try:
            with obs_context.ensure_root():
                with tracing.span("outer"):
                    with tracing.span("inner"):
                        pass
            collector.flush()
        finally:
            collector.path = old
        events = tracing.load_trace_events(str(tmp_path / "trace.json"))
        by_name = {e["name"]: e for e in events if e.get("ph") == "X"}
        outer, inner = by_name["outer"], by_name["inner"]
        assert outer["args"]["trace_id"] == inner["args"]["trace_id"]
        assert inner["args"]["parent_id"] == outer["args"]["span_id"]
        assert "parent_id" not in outer["args"]  # root span has no parent


# --------------------------------------------------------------------------
# multi-process stitching through a real store
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_one_trace_id_spans_client_controller_volume(
    tmp_path, monkeypatch
):
    """THE acceptance path: a single put's trace id must appear in spans
    from the client process AND the controller/volume actor processes, and
    the merged file must be one loadable Chrome trace with labeled
    process tracks."""
    import torchstore_tpu as ts

    base = str(tmp_path / "trace.json")
    # Children inherit the env var at spawn; the main process's collector
    # predates it, so point it at the same base directly.
    monkeypatch.setenv("TORCHSTORE_TPU_TRACE", base)
    collector = tracing.collector()
    old_path = collector.path
    collector.path = base
    try:
        await ts.initialize(store_name="obs_stitch")
        try:
            arr = np.arange(1024, dtype=np.float32)
            await ts.put("stitch/k", arr, store_name="obs_stitch")
            out = await ts.get("stitch/k", store_name="obs_stitch")
            np.testing.assert_array_equal(np.asarray(out), arr)
            del out
        finally:
            await ts.shutdown("obs_stitch")
        result = ts.collect_trace(str(tmp_path / "merged.json"))
    finally:
        collector.flush()
        collector.path = old_path
    assert result is not None
    # Client + at least one actor process contributed files.
    assert len(result["files"]) >= 2, result
    events = json.load(open(result["path"]))  # loads as-is: one valid array
    spans = [e for e in events if e.get("ph") == "X"]
    put_spans = [e for e in spans if e["name"] == "put_batch"]
    assert put_spans, {e["name"] for e in spans}
    trace_id = put_spans[-1]["args"]["trace_id"]
    pids_in_trace = {
        e["pid"]
        for e in spans
        if (e.get("args") or {}).get("trace_id") == trace_id
    }
    assert len(pids_in_trace) >= 2, (
        f"trace {trace_id} confined to one process; events: "
        f"{[(e['name'], e['pid']) for e in spans]}"
    )
    # Server-side rpc spans adopted the client's trace id.
    stitched_names = {
        e["name"]
        for e in spans
        if (e.get("args") or {}).get("trace_id") == trace_id
    }
    assert any(n.startswith("rpc/") for n in stitched_names), stitched_names
    # Labeled process tracks for every contributing file.
    meta_names = [
        e["args"]["name"] for e in events if e.get("ph") == "M"
    ]
    assert len(meta_names) == len(result["files"])
    assert any("volume" in n for n in meta_names), meta_names


# --------------------------------------------------------------------------
# fleet snapshot
# --------------------------------------------------------------------------


class TestMergeSnapshots:
    def _counter_snap(self, value, labels=None, help=""):
        return {
            "kind": "counter",
            "help": help,
            "series": [{"labels": labels or {}, "value": value}],
        }

    def test_labels_injected_per_process(self):
        merged, conflicts = aggregate.merge_snapshots(
            [
                ({"process": "controller"}, {"ts_x_total": self._counter_snap(1)}),
                (
                    {"process": "volume", "volume_id": "7"},
                    {"ts_x_total": self._counter_snap(2)},
                ),
            ]
        )
        assert conflicts == []
        series = merged["ts_x_total"]["series"]
        assert {"process": "controller"} in [s["labels"] for s in series]
        assert {"process": "volume", "volume_id": "7"} in [
            s["labels"] for s in series
        ]

    def test_label_collision_preserved_under_exported_prefix(self):
        merged, _ = aggregate.merge_snapshots(
            [
                (
                    {"process": "volume", "volume_id": "0"},
                    {
                        "ts_x_total": self._counter_snap(
                            5, labels={"process": "impostor", "op": "put"}
                        )
                    },
                )
            ]
        )
        labels = merged["ts_x_total"]["series"][0]["labels"]
        assert labels["process"] == "volume"  # scraper identity wins
        assert labels["exported_process"] == "impostor"  # original kept
        assert labels["op"] == "put"

    def test_kind_conflict_dropped_and_reported(self):
        merged, conflicts = aggregate.merge_snapshots(
            [
                ({"process": "a"}, {"ts_x": self._counter_snap(1)}),
                (
                    {"process": "b"},
                    {
                        "ts_x": {
                            "kind": "gauge",
                            "help": "",
                            "series": [{"labels": {}, "value": 2}],
                        }
                    },
                ),
            ]
        )
        assert merged["ts_x"]["kind"] == "counter"
        assert len(merged["ts_x"]["series"]) == 1  # gauge contribution dropped
        assert conflicts and "ts_x" in conflicts[0]

    def test_fleet_doc_renders_prometheus(self):
        doc = aggregate.fleet_doc(
            [({"process": "controller"}, {"ts_x_total": self._counter_snap(3)})],
            errors={"1": "dead: ConnectionRefusedError"},
        )
        assert doc["errors"] == {"1": "dead: ConnectionRefusedError"}
        text = aggregate.render_prometheus(doc["metrics"])
        assert 'ts_x_total{process="controller"} 3' in text
        json.dumps(doc)  # the whole envelope is JSON-serializable


@pytest.mark.anyio
async def test_fleet_snapshot_covers_controller_and_every_volume():
    import torchstore_tpu as ts
    from torchstore_tpu.observability import profile

    # The hot-key tracker is process-global and rolling: earlier tests in
    # the same process may have recorded bigger keys that would evict this
    # test's tiny one from the top-K — reset for a deterministic envelope.
    profile.reset_hot_keys()
    await ts.initialize(store_name="obs_fleet", num_storage_volumes=2)
    try:
        arr = np.ones(512, np.float32)
        await ts.put("fleet/k", arr, store_name="obs_fleet")
        out = await ts.get("fleet/k", store_name="obs_fleet")
        del out
        doc = await ts.fleet_snapshot(store_name="obs_fleet")
        assert doc["errors"] == {}
        procs = doc["processes"]
        assert {"process": "client"} in procs
        assert {"process": "controller"} in procs
        vol_ids = {
            p["volume_id"] for p in procs if p.get("process") == "volume"
        }
        assert len(vol_ids) == 2, procs
        merged = doc["metrics"]
        # Controller-process series are labeled as such.
        ctl = [
            s
            for s in merged["ts_controller_puts_total"]["series"]
            if s["labels"].get("process") == "controller"
        ]
        assert ctl and ctl[0]["value"] >= 1
        # Every series in the document carries a process label.
        for name, snap in merged.items():
            for series in snap["series"]:
                assert "process" in series["labels"], (name, series)
        # The client's hot keys made it into the envelope.
        assert any(
            h["key"] == "fleet/k" for h in doc["hot_keys"]["client"]
        )
        json.dumps(doc)
        # Prometheus rendering of the same scrape.
        text = await ts.fleet_snapshot(
            store_name="obs_fleet", render="prometheus"
        )
        assert 'process="controller"' in text
        assert 'process="volume"' in text
    finally:
        await ts.shutdown("obs_fleet")


@pytest.mark.anyio
async def test_fleet_snapshot_tolerates_dead_volume():
    """A volume that can't be scraped lands in ``errors`` — the rest of the
    fleet document still assembles (heartbeat tolerance)."""
    import torchstore_tpu as ts
    from torchstore_tpu.runtime import ActorDiedError

    await ts.initialize(store_name="obs_dead", num_storage_volumes=2)
    try:
        await ts.put("dead/k", np.ones(64, np.float32), store_name="obs_dead")
        handle = ts.api._stores["obs_dead"]
        victim = handle.volume_mesh._processes[0]
        victim.terminate()
        victim.join(10.0)
        doc = await ts.fleet_snapshot(store_name="obs_dead")
        assert len(doc["errors"]) == 1, doc["errors"]
        # The survivor and the controller still report.
        assert {"process": "controller"} in doc["processes"]
        assert any(p.get("process") == "volume" for p in doc["processes"])
    finally:
        try:
            await ts.shutdown("obs_dead")
        except (ActorDiedError, Exception):
            pass


# --------------------------------------------------------------------------
# HTTP exporter
# --------------------------------------------------------------------------


class TestHTTPExporter:
    def test_serves_metrics_healthz_and_shuts_down(self):
        obs_metrics.counter("ts_http_probe_total", "probe").inc(7)
        exp = http_exporter.start_http_exporter(0, host="127.0.0.1")
        try:
            assert exp.port > 0
            base = f"http://127.0.0.1:{exp.port}"
            body = urllib.request.urlopen(f"{base}/metrics", timeout=10).read()
            text = body.decode()
            assert "# TYPE ts_http_probe_total counter" in text
            assert "ts_http_probe_total 7" in text
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz", timeout=10).read()
            )
            assert health["status"] == "ok"
            assert health["pid"] > 0
            doc = json.loads(
                urllib.request.urlopen(f"{base}/metrics.json", timeout=10).read()
            )
            assert "ts_http_probe_total" in doc["metrics"]
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/nope", timeout=10)
            # The bound port is discoverable through the registry (how
            # fleet_snapshot finds ephemeral-fallback siblings).
            gauge = obs_metrics.get_registry().get("ts_metrics_http_port")
            assert gauge.value() == exp.port
        finally:
            exp.close()
        # Clean shutdown: the port no longer answers.
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/healthz", timeout=2
            )

    def test_maybe_start_is_env_gated_and_falls_back(self, monkeypatch):
        monkeypatch.delenv(http_exporter.ENV_METRICS_PORT, raising=False)
        assert http_exporter.maybe_start_http_exporter() is None
        # Occupy a port, then ask maybe_start for exactly it: the exporter
        # must fall back to an ephemeral port instead of dying (volume
        # actors inherit the same env var as their spawner).
        blocker = http_exporter.start_http_exporter(0, host="127.0.0.1")
        try:
            monkeypatch.setenv(
                http_exporter.ENV_METRICS_PORT, str(blocker.port)
            )
            monkeypatch.setenv(http_exporter.ENV_METRICS_HOST, "127.0.0.1")
            exp = http_exporter.maybe_start_http_exporter()
            try:
                assert exp is not None
                assert exp.port != blocker.port
                health = urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/healthz", timeout=10
                ).read()
                assert json.loads(health)["status"] == "ok"
                # Idempotent: a second call returns the running exporter.
                assert http_exporter.maybe_start_http_exporter() is exp
            finally:
                http_exporter.stop_http_exporter()
        finally:
            blocker.close()


# --------------------------------------------------------------------------
# hot-key / slow-op profiler
# --------------------------------------------------------------------------


class TestProfiler:
    def test_hot_keys_top_k(self):
        tracker = obs_profile.HotKeyTracker()
        tracker.record("big", 1000)
        tracker.record("big", 1000)
        tracker.record("chatty", 1)
        for _ in range(5):
            tracker.record("chatty", 1)
        top_bytes = tracker.top(1, by="bytes")
        assert top_bytes[0]["key"] == "big"
        assert top_bytes[0] == {"key": "big", "ops": 2, "bytes": 2000}
        top_ops = tracker.top(1, by="ops")
        assert top_ops[0]["key"] == "chatty"

    def test_hot_keys_bounded_eviction_keeps_hottest(self):
        tracker = obs_profile.HotKeyTracker()
        tracker.MAX_KEYS = 8
        tracker.record("whale", 10**9)
        for i in range(50):
            tracker.record(f"minnow/{i}", 1)
        assert len(tracker._keys) <= tracker.MAX_KEYS
        assert any(h["key"] == "whale" for h in tracker.top(3))

    def test_slow_op_threshold_logs_counts_and_annotates(
        self, monkeypatch, tmp_path, caplog
    ):
        monkeypatch.setenv(obs_profile.ENV_SLOW_OP_MS, "10")
        collector = tracing.collector()
        old = collector.path
        collector.path = str(tmp_path / "trace.json")
        slow_counter = obs_metrics.get_registry().counter("ts_slow_ops_total")
        before = slow_counter.value(op="probe")
        try:
            with caplog.at_level("WARNING"):
                # 5 ms: under threshold — nothing happens.
                obs_profile.record_op("probe", "k/fast", 10, 0.0, 0.005)
                assert slow_counter.value(op="probe") == before
                # 50 ms: over threshold.
                obs_profile.record_op("probe", "k/slow", 10, 0.0, 0.050)
            collector.flush()
        finally:
            collector.path = old
        assert slow_counter.value(op="probe") == before + 1
        assert any("slow op" in r.getMessage() for r in caplog.records)
        events = tracing.load_trace_events(str(tmp_path / "trace.json"))
        slow = [e for e in events if e["name"] == "slow_op/probe"]
        assert slow and slow[0]["args"]["key"] == "k/slow"
        assert slow[0]["args"]["slow"] is True

    def test_disabled_threshold_is_noop(self, monkeypatch):
        monkeypatch.delenv(obs_profile.ENV_SLOW_OP_MS, raising=False)
        assert obs_profile.slow_op_threshold_s() is None
        monkeypatch.setenv(obs_profile.ENV_SLOW_OP_MS, "junk")
        assert obs_profile.slow_op_threshold_s() is None

    @pytest.mark.anyio
    async def test_volume_stats_carry_hot_keys(self):
        import torchstore_tpu as ts

        await ts.initialize(store_name="obs_hot")
        try:
            arr = np.ones(2048, np.float32)
            for _ in range(3):
                await ts.put("hot/banger", arr, store_name="obs_hot")
            await ts.put("hot/once", np.ones(4, np.float32), store_name="obs_hot")
            stats = await ts.client(
                "obs_hot"
            ).controller.stats.call_one(include_volumes=True)
            (vstats,) = stats["volumes"].values()
            hot = vstats["hot_keys"]
            assert hot[0]["key"] == "hot/banger"
            assert hot[0]["bytes"] >= 3 * arr.nbytes
        finally:
            await ts.shutdown("obs_hot")
