"""Elastic repair: dead volumes are replaced with fresh actors, keys with
surviving replicas are re-replicated onto the replacement, unrecoverable
keys are reported lost and dropped (reads fail loudly, never hang)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.client import Shard
from torchstore_tpu.strategy import LocalRankStrategy
from torchstore_tpu.transport.types import TensorSlice

from tests.test_replication import _kill_volume


@pytest.fixture
async def store():
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(replication=2),
        store_name="rep",
    )
    yield "rep"
    await ts.shutdown("rep")


async def test_repair_restores_replication(store):
    src = np.random.rand(64).astype(np.float32)
    await ts.put("w", src, store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["w"])
    victim = sorted(located["w"])[0]
    await _kill_volume(store, victim)

    report = await ts.repair(store_name=store)
    assert report["replaced"] == [victim]
    assert report["rereplicated"] == 1
    assert report["lost"] == []
    # The key is back on TWO volumes, including the replacement.
    located = await client.controller.locate_volumes.call_one(["w"])
    assert len(located["w"]) == 2 and victim in located["w"]
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)
    # And the store survives a SECOND death of the other original replica:
    # the repaired copy carries the data forward.
    other = next(v for v in located["w"] if v != victim)
    await _kill_volume(store, other)
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, src)


async def test_repair_reports_lost_keys():
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=1),
        store_name="rep1",
    )
    try:
        await ts.put("only", np.ones(4), store_name="rep1")
        client = ts.client("rep1")
        located = await client.controller.locate_volumes.call_one(["only"])
        (vid,) = located["only"]
        await _kill_volume("rep1", vid)
        report = await ts.repair(store_name="rep1")
        assert report["replaced"] == [vid]
        assert report["lost"] == ["only"]
        # The lost key reads as missing (loud), not a hang/dead-ref error.
        with pytest.raises(KeyError):
            await ts.get("only", store_name="rep1")
        # The replacement serves new writes under the old volume id.
        await ts.put("fresh", np.full(2, 7.0), store_name="rep1")
        out = await ts.get("fresh", store_name="rep1")
        np.testing.assert_array_equal(out, np.full(2, 7.0))
    finally:
        await ts.shutdown("rep1")


async def test_repair_rereplicates_shards(store):
    full = np.arange(24.0, dtype=np.float32).reshape(3, 8)
    for row in range(3):
        sl = TensorSlice(
            offsets=(row, 0),
            local_shape=(1, 8),
            global_shape=(3, 8),
            coordinates=(row,),
            mesh_shape=(3,),
        )
        await ts.put("sh", Shard(full[row : row + 1], sl), store_name=store)
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["sh"])
    victim = sorted(located["sh"])[0]
    await _kill_volume(store, victim)
    report = await ts.repair(store_name=store)
    assert report["replaced"] == [victim] and report["lost"] == []
    out = await ts.get("sh", store_name=store)
    np.testing.assert_array_equal(out, full)
    located = await client.controller.locate_volumes.call_one(["sh"])
    assert victim in located["sh"]


async def test_stale_client_self_heals_after_repair(store):
    """A client that never heard about the repair holds the dead volume's
    old ActorRef: its fetch fails, the health check reports the volume ok
    (the controller pings the REPLACEMENT), and the client must conclude
    its ref is stale, refresh the volume map, and succeed on retry."""
    from torchstore_tpu.client import LocalClient

    src = np.random.rand(32).astype(np.float32)
    await ts.put("w", src, store_name=store)
    owner = ts.client(store)
    # Second, independent client with its own cached refs.
    stale = LocalClient(owner.controller, owner._config)
    np.testing.assert_array_equal(await stale.get("w"), src)
    located = await owner.controller.locate_volumes.call_one(["w"])
    for vid in sorted(located["w"]):
        await _kill_volume(store, vid)
    report = await ts.repair(store_name=store)
    assert report["lost"] == ["w"]  # both replicas died
    # Re-publish under a fresh key on the repaired fleet.
    await ts.put("w2", src, store_name=store)
    # The stale client still points old refs at the replaced volumes; a
    # single get must self-heal (diagnosis -> refresh -> retry) and serve.
    out = await stale.get("w2")
    np.testing.assert_array_equal(out, src)


async def test_repair_noop_when_healthy(store):
    await ts.put("k", np.ones(2), store_name=store)
    report = await ts.repair(store_name=store)
    assert report == {
        "replaced": [],
        "rereplicated": 0,
        "lost": [],
        "failed": [],
        "wedged": [],
    }


async def test_repair_survives_double_volume_death(store):
    """Both replicas of a key die: repair must still complete (replacing
    every dead volume, repairing what survivors hold) and report the key
    lost — never abort mid-way."""
    await ts.put("k", np.ones(4), store_name=store)  # on 2 of 3 volumes
    client = ts.client(store)
    located = await client.controller.locate_volumes.call_one(["k"])
    both = sorted(located["k"])
    for vid in both:
        await _kill_volume(store, vid)
    report = await ts.repair(store_name=store)
    assert sorted(report["replaced"]) == both
    assert report["lost"] == ["k"]
    assert report["failed"] == []
    with pytest.raises(KeyError):
        await ts.get("k", store_name=store)
    # The replaced fleet is fully writable again.
    await ts.put("k2", np.full(2, 3.0), store_name=store)
    out = await ts.get("k2", store_name=store)
    np.testing.assert_array_equal(out, np.full(2, 3.0))


async def test_detach_is_shard_granular():
    """A degraded put's detach removes only the FAILED shard's coords from
    the replica — sibling ranks' shards on the same volume survive (unit
    test on the controller; the race needs multi-rank orchestration)."""
    from torchstore_tpu.controller import Controller
    from torchstore_tpu.transport.types import Request, TensorMeta

    c = Controller()
    meta = TensorMeta(shape=(1, 4), dtype="float32")

    def shard_meta(coord):
        sl = TensorSlice(
            offsets=(coord, 0), local_shape=(1, 4), global_shape=(2, 4),
            coordinates=(coord,), mesh_shape=(2,),
        )
        req = Request.from_tensor_slice("k", sl)
        req.tensor_meta = meta
        return req.meta_only()

    # Two ranks' shards both indexed on volume "1".
    await c.notify_put_batch([shard_meta(0)], "1")
    await c.notify_put_batch([shard_meta(1)], "1")
    assert await c.contains("k") == "committed"
    # Rank 0's degraded re-put: lands on "0", detaches ONLY coord (0,)
    # from "1".
    await c.notify_put_batch([shard_meta(0)], ["0"], detach_volume_ids=["1"])
    located = await c.locate_volumes(["k"])
    assert set(located["k"]) == {"0", "1"}
    assert list(located["k"]["1"].tensor_slices) == [(1,)]
    assert list(located["k"]["0"].tensor_slices) == [(0,)]


async def test_repair_requires_owner():
    with pytest.raises(RuntimeError, match="initialized"):
        await ts.repair(store_name="never-made")


async def test_wedged_volume_reported_not_replaced(store):
    import os
    import signal

    from torchstore_tpu import api

    await ts.put("k", np.ones(4), store_name=store)
    client = ts.client(store)
    vmap = await client.controller.get_volume_map.call_one()
    target = vmap["0"]["ref"]
    handle = api._stores[store]
    proc = next(
        p
        for r, p in zip(handle.volume_mesh.refs, handle.volume_mesh._processes)
        if (r.host, r.port, r.name) == (target.host, target.port, target.name)
    )
    os.kill(proc.pid, signal.SIGSTOP)
    try:
        report = await ts.repair(store_name=store)
        # Wedged (alive-but-stuck) volumes may recover: reported, kept.
        assert report["wedged"] == ["0"]
        assert report["replaced"] == []
    finally:
        os.kill(proc.pid, signal.SIGCONT)
    out = await ts.get("k", store_name=store)
    np.testing.assert_array_equal(out, np.ones(4))


async def test_kill_repair_soak(store):
    """Elasticity soak: three consecutive kill -> repair cycles on a
    replicated working set; data survives every cycle and the fleet ends
    fully healthy."""
    working_set = {
        f"w{i}": np.random.rand(32).astype(np.float32) for i in range(4)
    }
    for key, arr in working_set.items():
        await ts.put(key, arr, store_name=store)
    client = ts.client(store)
    for cycle in range(3):
        vmap = await client.controller.get_volume_map.call_one()
        victim = sorted(vmap)[cycle % len(vmap)]
        await _kill_volume(store, victim)
        report = await ts.repair(store_name=store)
        assert report["replaced"] == [victim], (cycle, report)
        assert report["lost"] == [] and report["failed"] == [], (cycle, report)
        for key, arr in working_set.items():
            out = await ts.get(key, store_name=store)
            np.testing.assert_array_equal(out, arr)
    statuses = await client.controller.check_volumes.call_one()
    assert all(s == "ok" for s in statuses.values()), statuses
