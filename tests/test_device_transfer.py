"""Device-path (ICI rung) weight sync tests: the jax.experimental.transfer
engine wrapper, sharding descriptors, and direct state-dict sync riding the
device path end to end on the virtual 8-device CPU mesh (VERDICT r1 item 3;
reference analog: one-sided RDMA device reads, monarch_rdma.py:158-219)."""

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.transport import device_transfer as dt

jax = pytest.importorskip("jax")

pytestmark = pytest.mark.skipif(
    not dt.is_available(), reason="jax.experimental.transfer not in this build"
)


def _mesh(n=8):
    devs = np.array(jax.devices()[:n], dtype=object)
    return jax.sharding.Mesh(devs.reshape(n), ("x",))


class TestShardingDescriptor:
    def test_named_roundtrip(self):
        mesh = _mesh()
        sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
        desc = dt.ShardingDescriptor.of(sh)
        rebuilt = desc.build()
        assert rebuilt == sh

    def test_single_device_roundtrip(self):
        sh = jax.sharding.SingleDeviceSharding(jax.devices()[2])
        rebuilt = dt.ShardingDescriptor.of(sh).build()
        assert rebuilt == sh

    def test_2d_mesh_with_tuple_spec(self):
        devs = np.array(jax.devices()[:8], dtype=object).reshape(2, 4)
        mesh = jax.sharding.Mesh(devs, ("a", "b"))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(("a", "b"), None)
        )
        rebuilt = dt.ShardingDescriptor.of(sh).build()
        assert rebuilt == sh


class TestEngine:
    def test_stage_and_pull_roundtrip(self):
        engine = dt.DeviceTransferEngine.get()
        addr = engine.ensure_server()
        mesh = _mesh()
        sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
        x = jax.device_put(jax.numpy.arange(64.0), sh)
        uid = engine.stage([x])
        out = engine.pull(addr, uid, [dt.DeviceSpec.of(x)])
        np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(x))

    def test_each_stage_serves_one_pull(self):
        engine = dt.DeviceTransferEngine.get()
        addr = engine.ensure_server()
        x = jax.numpy.arange(16.0)
        uids = [engine.stage([x * k]) for k in (1, 2)]
        spec = [dt.DeviceSpec.of(x)]
        out2 = engine.pull(addr, uids[1], spec)
        out1 = engine.pull(addr, uids[0], spec)
        assert np.asarray(out1[0])[1] == 1.0
        assert np.asarray(out2[0])[1] == 2.0


@pytest.fixture
async def store():
    await ts.initialize(store_name="ici")
    yield "ici"
    await ts.shutdown("ici")


async def test_direct_sync_rides_device_path(store):
    """All-jax direct put/get: handles advertise the device path, the pull
    lands device arrays, and refresh semantics (current weights per pull)
    hold — all with zero host staging buffers."""
    mesh = _mesh()
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    sd = {
        "w": jax.device_put(jax.numpy.arange(64.0), sh),
        "b": jax.numpy.ones((8,), jax.numpy.float32),
    }
    await ts.put_state_dict("m", sd, direct=True, store_name=store)
    target = {
        "w": jax.ShapeDtypeStruct((64,), jax.numpy.float32, sharding=sh),
        "b": np.zeros(8, np.float32),  # mixed target kinds: host landing
    }
    out = await ts.get_state_dict(
        "m", user_state_dict=target, direct=True, store_name=store
    )
    assert dt.is_available()
    assert hasattr(out["w"], "sharding")  # device array, not host copy
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(64.0))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.ones(8))

    # Refresh: a second direct put of NEW values must be what the next
    # pull sees (staging happens per pull, so weights are always current).
    sd2 = {"w": jax.device_put(sd["w"] * 2, sh), "b": sd["b"] * 3}
    await ts.put_state_dict("m", sd2, direct=True, store_name=store)
    out2 = await ts.get_state_dict(
        "m", user_state_dict=target, direct=True, store_name=store
    )
    np.testing.assert_array_equal(np.asarray(out2["w"]), np.arange(64.0) * 2)
    np.testing.assert_array_equal(np.asarray(out2["b"]), np.full(8, 3.0))


async def test_device_path_reshards_to_target(store):
    """Dest asks for a different sharding than the source published: the
    pull lands source-layout arrays and reshards locally over the mesh."""
    mesh = _mesh()
    src_sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    sd = {"w": jax.device_put(jax.numpy.arange(64.0).reshape(8, 8), src_sh)}
    await ts.put_state_dict("r", sd, direct=True, store_name=store)
    devs2 = np.array(jax.devices()[:8], dtype=object).reshape(4, 2)
    mesh2 = jax.sharding.Mesh(devs2, ("p", "q"))
    tgt_sh = jax.sharding.NamedSharding(
        mesh2, jax.sharding.PartitionSpec(None, "p")
    )
    target = {"w": jax.ShapeDtypeStruct((8, 8), jax.numpy.float32, sharding=tgt_sh)}
    out = await ts.get_state_dict(
        "r", user_state_dict=target, direct=True, store_name=store
    )
    assert out["w"].sharding == tgt_sh
    np.testing.assert_array_equal(
        np.asarray(out["w"]), np.arange(64.0).reshape(8, 8)
    )


async def test_multi_rank_device_path_in_process(store):
    """Two SPMD source ranks, each owning a DISJOINT 4-device subset,
    publish their halves of a global tensor direct=True (Shard-wrapped jax
    arrays); the consumer pulls the MERGED dict over the device path —
    no host staging buffers exist on either source (VERDICT r2 item 1)."""
    devs = jax.devices()
    w = np.arange(128.0, dtype=np.float32).reshape(16, 8)
    for r in (0, 1):
        sub = np.array(devs[4 * r : 4 * r + 4], dtype=object)
        mesh = jax.sharding.Mesh(sub.reshape(4), ("x",))
        sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
        local = jax.device_put(jax.numpy.asarray(w[8 * r : 8 * r + 8]), sh)
        sl = ts.TensorSlice(
            offsets=(8 * r, 0), local_shape=(8, 8), global_shape=(16, 8),
            coordinates=(r,), mesh_shape=(2,),
        )
        await ts.put_state_dict(
            "mr", {"w": ts.Shard(local, sl)}, direct=True,
            rank=r, num_ranks=2, store_name=store,
        )
    # Both ranks rode the device path: no host handles at all.
    for r in (0, 1):
        published = await ts.get(f"mr/rank_{r}", store_name=store)
        assert published["handles"] == {}
        assert published["device"] is not None
        assert published["device"]["source_rank"] == r
    mesh8 = _mesh()
    tgt = jax.sharding.NamedSharding(mesh8, jax.sharding.PartitionSpec("x"))
    out = await ts.get_state_dict(
        "mr",
        user_state_dict={
            "w": jax.ShapeDtypeStruct((16, 8), jax.numpy.float32, sharding=tgt)
        },
        direct=True,
        store_name=store,
    )
    assert out["w"].sharding == tgt
    np.testing.assert_array_equal(np.asarray(out["w"]), w)
    # Refresh semantics across ranks: republished values are what the next
    # pull sees (per-pull staging on every rank).
    for r in (0, 1):
        sub = np.array(devs[4 * r : 4 * r + 4], dtype=object)
        mesh = jax.sharding.Mesh(sub.reshape(4), ("x",))
        sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
        local = jax.device_put(jax.numpy.asarray(w[8 * r : 8 * r + 8] * 3), sh)
        sl = ts.TensorSlice(
            offsets=(8 * r, 0), local_shape=(8, 8), global_shape=(16, 8),
            coordinates=(r,), mesh_shape=(2,),
        )
        await ts.put_state_dict(
            "mr", {"w": ts.Shard(local, sl)}, direct=True,
            rank=r, num_ranks=2, store_name=store,
        )
    out2 = await ts.get_state_dict(
        "mr",
        user_state_dict={
            "w": jax.ShapeDtypeStruct((16, 8), jax.numpy.float32, sharding=tgt)
        },
        direct=True,
        store_name=store,
    )
    np.testing.assert_array_equal(np.asarray(out2["w"]), w * 3)


async def test_multi_rank_device_pull_to_host_target(store):
    """A numpy consumer of a multi-rank device publish: parts land into the
    destination array region-wise (consumer-local copies only)."""
    devs = jax.devices()
    w = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    for r in (0, 1):
        sh = jax.sharding.SingleDeviceSharding(devs[4 * r])
        local = jax.device_put(jax.numpy.asarray(w[4 * r : 4 * r + 4]), sh)
        sl = ts.TensorSlice(
            offsets=(4 * r, 0), local_shape=(4, 8), global_shape=(8, 8),
            coordinates=(r,), mesh_shape=(2,),
        )
        await ts.put_state_dict(
            "mrh", {"w": ts.Shard(local, sl)}, direct=True,
            rank=r, num_ranks=2, store_name=store,
        )
    target = np.zeros((8, 8), np.float32)
    out = await ts.get_state_dict(
        "mrh", user_state_dict={"w": target}, direct=True, store_name=store
    )
    assert out["w"] is target  # in-place landing
    np.testing.assert_array_equal(target, w)


async def test_device_id_mismatch_falls_back_to_host_staging(store):
    """A dest whose jax world lacks the source's device ids degrades to the
    source-side host-staging control op (_STAGE_HOST) and still gets
    correct, CURRENT bytes over TCP."""
    import dataclasses

    mesh = _mesh()
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    sd = {"w": jax.device_put(jax.numpy.arange(64.0), sh)}
    await ts.put_state_dict("fbk", sd, direct=True, store_name=store)
    # Tamper the published descriptor so its device ids are unknown here —
    # exactly what a dest in a different jax world would observe.
    published = await ts.get("fbk/rank_0", store_name=store)
    for entry in published["device"]["entries"]:
        bogus = dataclasses.replace(
            entry.spec.sharding,
            device_ids=tuple(i + 1000 for i in entry.spec.sharding.device_ids),
        )
        entry.spec = dataclasses.replace(entry.spec, sharding=bogus)
    from torchstore_tpu.direct_weight_sync import DirectWeightSyncDest

    dest = DirectWeightSyncDest()
    try:
        out = await dest.pull_device(
            [published["device"]], {"w": np.zeros(64, np.float32)}
        )
        np.testing.assert_array_equal(out["w"], np.arange(64.0))
    finally:
        await dest.close()


async def test_concurrent_fallback_pulls_share_one_staging(store):
    """N cross-world dests pulling one source concurrently (the RL fan-out
    shape) must not trip each other's tear detection: fallback staging is
    cached per content generation and never bumps the seqlock, so both
    pulls see one stable generation, share ONE D2H materialization, and
    deliver exact dicts with zero retries (VERDICT r3 weak #5)."""
    import asyncio
    import dataclasses

    from torchstore_tpu.direct_weight_sync import (
        DirectWeightSyncDest,
        DirectWeightSyncSource,
    )

    source = DirectWeightSyncSource()
    w = np.arange(256.0, dtype=np.float32).reshape(16, 16)
    mesh = _mesh()
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    await source.register({"w": jax.device_put(jax.numpy.asarray(w), sh)})
    assert source.device_info is not None
    # Tamper the published device ids — each dest now degrades to the
    # source-side host-staging control op.
    info = dict(source.device_info)
    info["entries"] = [
        dataclasses.replace(
            e,
            spec=dataclasses.replace(
                e.spec,
                sharding=dataclasses.replace(
                    e.spec.sharding,
                    device_ids=tuple(
                        i + 1000 for i in e.spec.sharding.device_ids
                    ),
                ),
            ),
        )
        for e in source.device_info["entries"]
    ]

    materializations = {"n": 0}
    real_mat = source._materialize_host_handles

    def counting_mat():
        materializations["n"] += 1
        return real_mat()

    source._materialize_host_handles = counting_mat
    dests = [DirectWeightSyncDest() for _ in range(2)]
    pull_once_calls = {"n": 0}
    try:
        for d in dests:
            real_pull_once = d._pull_once

            async def counted(handles, sd, _real=real_pull_once):
                pull_once_calls["n"] += 1
                return await _real(handles, sd)

            d._pull_once = counted
        gen_before = source._read_gen_locked()
        outs = await asyncio.gather(
            *(
                d.pull_device([info], {"w": np.zeros((16, 16), np.float32)})
                for d in dests
            )
        )
        for out in outs:
            np.testing.assert_array_equal(out["w"], w)
        # One shared staging, one data attempt per dest, no gen movement.
        assert materializations["n"] == 1
        assert pull_once_calls["n"] == len(dests)
        assert source._read_gen_locked() == gen_before

        # A publish invalidates the staging cache: the next fallback pull
        # re-materializes and serves the NEW content.
        source.update_sources(
            {"w": jax.device_put(jax.numpy.asarray(w * 2), sh)}
        )
        await source.refresh()
        out2 = await dests[0].pull_device(
            [info], {"w": np.zeros((16, 16), np.float32)}
        )
        np.testing.assert_array_equal(out2["w"], w * 2)
        assert materializations["n"] == 2
    finally:
        for d in dests:
            await d.close()
        await source.close()


async def test_device_refresh_rejects_resharded_republish(store):
    """A republish whose value keeps the part COUNT but changes placement
    must fail loudly at stage time — staging it against the stale published
    entries would land shards at wrong offsets (silent corruption)."""
    sh0 = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    sd = {"w": jax.device_put(jax.numpy.arange(32.0), sh0)}
    await ts.put_state_dict("rr", sd, direct=True, store_name=store)
    # Same shape/count, different device placement.
    sh1 = jax.sharding.SingleDeviceSharding(jax.devices()[3])
    sd2 = {"w": jax.device_put(jax.numpy.arange(32.0) * 2, sh1)}
    await ts.put_state_dict("rr", sd2, direct=True, store_name=store)
    target = {"w": jax.ShapeDtypeStruct((32,), jax.numpy.float32, sharding=sh0)}
    with pytest.raises(Exception, match="re-register|no device-mode|stage"):
        await ts.get_state_dict(
            "rr", user_state_dict=target, direct=True, store_name=store
        )


async def test_numpy_dict_still_uses_host_path(store):
    """Plain-numpy direct sync keeps the host (SHM/TCP) path."""
    sd = {"w": np.random.rand(128).astype(np.float32)}
    await ts.put_state_dict("h", sd, direct=True, store_name=store)
    user = {"w": np.zeros(128, np.float32)}
    out = await ts.get_state_dict(
        "h", user_state_dict=user, direct=True, store_name=store
    )
    np.testing.assert_array_equal(out["w"], sd["w"])


async def test_ici_disabled_falls_back(store, monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_ICI_ENABLED", "0")
    from torchstore_tpu import config as config_mod

    monkeypatch.setattr(config_mod, "_default_config", None)
    mesh = _mesh()
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("x"))
    sd = {"w": jax.device_put(jax.numpy.arange(32.0), sh)}
    await ts.put_state_dict("fb", sd, direct=True, store_name=store)
    target = {"w": jax.ShapeDtypeStruct((32,), jax.numpy.float32, sharding=sh)}
    out = await ts.get_state_dict(
        "fb", user_state_dict=target, direct=True, store_name=store
    )
    np.testing.assert_array_equal(np.asarray(out["w"]), np.arange(32.0))
