"""Resharding matrix on a virtual 8-device CPU mesh — jax NamedSharding in,
different NamedSharding out, oracle = the dense global array (the reference
used torch DCP as oracle; here `np.asarray(global)` plays that role).
Mirrors reference tests/test_resharding_basic.py + parts of _ext.py."""

import numpy as np
import pytest

import torchstore_tpu as ts

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def make_mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


def sharded(value, mesh, spec):
    return jax.device_put(value, NamedSharding(mesh, spec))


GLOBAL = np.arange(16 * 32, dtype=np.float32).reshape(16, 32)


@pytest.fixture
async def store():
    await ts.initialize(store_name="rs")
    yield "rs"
    await ts.shutdown("rs")


CASES = [
    # (src mesh shape, src names, src spec, dst mesh shape, dst names, dst spec)
    ((8,), ("x",), P("x"), (4,), ("x",), P("x")),          # 1D shrink
    ((4,), ("x",), P("x"), (8,), ("x",), P("x")),          # 1D grow
    ((2, 4), ("x", "y"), P("x", "y"), (4, 2), ("x", "y"), P("x", "y")),  # 2D<->2D
    ((8,), ("x",), P("x"), (2, 4), ("a", "b"), P("a", "b")),  # 1D -> 2D
    ((2, 4), ("x", "y"), P("x", "y"), (8,), ("x",), P("x")),  # 2D -> 1D
    ((8,), ("x",), P("x"), (8,), ("x",), P(None, "x")),    # dim0 -> dim1
    ((2, 4), ("x", "y"), P("y", "x"), (2, 4), ("x", "y"), P("x", "y")),  # swap axes
    ((2, 4), ("dp", "tp"), P(None, "tp"), (2, 4), ("dp", "tp"), P("tp", None)),
    # FSDP-style [Replicate, Shard(0)] -> Shard(1)
    ((2, 4), ("dp", "fsdp"), P("fsdp", None), (8,), ("tp",), P(None, "tp")),
]


@pytest.mark.parametrize("case", CASES, ids=[f"case{i}" for i in range(len(CASES))])
async def test_reshard_matrix(store, case):
    sshape, snames, sspec, dshape, dnames, dspec = case
    src = sharded(GLOBAL, make_mesh(sshape, snames), sspec)
    await ts.put("w", src, store_name=store)
    like = sharded(np.zeros_like(GLOBAL), make_mesh(dshape, dnames), dspec)
    out = await ts.get("w", like=like, store_name=store)
    assert out.sharding == like.sharding
    np.testing.assert_array_equal(np.asarray(out), GLOBAL)
    await ts.delete("w", store_name=store)


async def test_replicate_only_dp(store):
    mesh = make_mesh((8,), ("dp",))
    src = sharded(GLOBAL, mesh, P())  # fully replicated -> demoted to TENSOR
    await ts.put("w", src, store_name=store)
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, GLOBAL)


async def test_partial_replication_hsdp(store):
    # [Replicate on dp, Shard on fsdp] — each coord stores its shard;
    # replicas across dp produce duplicate regions, deduped on fetch.
    mesh = make_mesh((2, 4), ("dp", "fsdp"))
    src = sharded(GLOBAL, mesh, P("fsdp"))
    await ts.put("w", src, store_name=store)
    like = sharded(np.zeros_like(GLOBAL), make_mesh((8,), ("x",)), P("x"))
    out = await ts.get("w", like=like, store_name=store)
    np.testing.assert_array_equal(np.asarray(out), GLOBAL)


async def test_sharded_to_full_fetch(store):
    mesh = make_mesh((2, 4), ("x", "y"))
    await ts.put("w", sharded(GLOBAL, mesh, P("x", "y")), store_name=store)
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, GLOBAL)


async def test_full_to_sharded_fetch(store):
    # Stored as a plain tensor, fetched under a sharding (slice extraction
    # from full tensors server-side).
    await ts.put("w", GLOBAL, store_name=store)
    like = sharded(np.zeros_like(GLOBAL), make_mesh((4, 2), ("x", "y")), P("x", "y"))
    out = await ts.get("w", like=like, store_name=store)
    assert out.sharding == like.sharding
    np.testing.assert_array_equal(np.asarray(out), GLOBAL)


async def test_uneven_shards(store):
    # jax's NamedSharding requires divisible dims; the store itself supports
    # uneven slices via explicit Shard puts (rows 0-3, 4-6, 7-9).
    g = np.arange(10 * 6, dtype=np.float32).reshape(10, 6)
    bounds = [(0, 4), (4, 7), (7, 10)]
    for i, (lo, hi) in enumerate(bounds):
        sl = ts.TensorSlice(
            offsets=(lo, 0), local_shape=(hi - lo, 6), global_shape=(10, 6),
            coordinates=(i,), mesh_shape=(3,),
        )
        await ts.put("u", ts.Shard(g[lo:hi], sl), store_name=store)
    out = await ts.get("u", store_name=store)
    np.testing.assert_array_equal(out, g)


async def test_reshard_to_replicated_like(store):
    # Sharded source fetched with a fully-replicated target sharding: the
    # single fetched part must fan out to every addressable device.
    mesh = make_mesh((2, 4), ("x", "y"))
    await ts.put("w", sharded(GLOBAL, mesh, P("x", "y")), store_name=store)
    like = sharded(np.zeros_like(GLOBAL), make_mesh((8,), ("d",)), P())
    out = await ts.get("w", like=like, store_name=store)
    assert out.sharding == like.sharding
    np.testing.assert_array_equal(np.asarray(out), GLOBAL)


async def test_republish_with_different_layout(store):
    # Re-publishing a key under a new mesh layout must invalidate stale
    # shards from the old layout.
    old = sharded(GLOBAL, make_mesh((8,), ("x",)), P("x"))
    await ts.put("w", old, store_name=store)
    new_vals = GLOBAL * 10.0
    new = sharded(new_vals, make_mesh((2, 2), ("a", "b")), P("a", "b"))
    await ts.put("w", new, store_name=store)
    out = await ts.get("w", store_name=store)
    np.testing.assert_array_equal(out, new_vals)


async def test_shard_put_without_data_rejected(store):
    sl = ts.TensorSlice(
        offsets=(0, 0), local_shape=(4, 32), global_shape=(16, 32),
        coordinates=(0,), mesh_shape=(4,),
    )
    with pytest.raises(ValueError, match="no tensor data"):
        await ts.put("bad", ts.Shard(None, sl), store_name=store)


async def test_3d_tensor_2d_mesh(store):
    g = np.arange(8 * 4 * 6, dtype=np.float32).reshape(8, 4, 6)
    mesh = make_mesh((2, 2), ("x", "y"))
    await ts.put("t3", sharded(g, mesh, P("x", None, "y")), store_name=store)
    like = sharded(np.zeros_like(g), make_mesh((4,), ("z",)), P(None, "z", None))
    out = await ts.get("t3", like=like, store_name=store)
    np.testing.assert_array_equal(np.asarray(out), g)
