"""Resource-leak soak: repeated put/get/delete churn must not grow fds,
/dev/shm segments, or the client connection pool."""

import os

import numpy as np

import torchstore_tpu as ts


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _shm_count() -> int:
    return sum(1 for n in os.listdir("/dev/shm") if n.startswith("ts_shm_"))


async def test_churn_leaves_no_residue():
    await ts.initialize(store_name="soak")
    try:
        x = np.random.rand(256, 256).astype(np.float32)
        # Warm: caches, connections, segments reach steady state. Segment
        # rotation (put -> retire -> release -> pool) is ~3 deep per key,
        # so give each of the two keys enough iterations to converge.
        for i in range(10):
            await ts.put(f"k{i % 2}", x, store_name="soak")
            await ts.get(f"k{i % 2}", store_name="soak")
        fds0, shm0 = _fd_count(), _shm_count()
        for i in range(50):
            key = f"k{i % 2}"
            await ts.put(key, x, store_name="soak")
            out = await ts.get(key, store_name="soak")
            assert out[0, 0] == x[0, 0]
            if i % 10 == 9:
                await ts.delete(key, store_name="soak")
        fds1, shm1 = _fd_count(), _shm_count()
        assert fds1 <= fds0 + 4, (fds0, fds1)
        assert shm1 <= shm0 + 2, (shm0, shm1)
        from torchstore_tpu.runtime.actors import _conn_pools

        assert len(_conn_pools) <= 4, len(_conn_pools)
    finally:
        await ts.shutdown("soak")


async def test_many_loops_prune_connection_pool():
    # Each asyncio.run creates a loop; pooled connections of dead loops must
    # be pruned, not accumulate (this test itself runs in a fresh loop after
    # many prior tests — pool stays bounded).
    import asyncio

    from torchstore_tpu.runtime.actors import _conn_pools

    await ts.initialize(store_name="loops")
    try:
        await ts.put("k", np.ones(4), store_name="loops")

        def one_shot():
            async def go():
                out = await ts.get("k", store_name="loops")
                assert out[0] == 1.0

            asyncio.run(go())

        import threading

        for _ in range(8):
            t = threading.Thread(target=one_shot)
            t.start()
            t.join()
        # Trigger pruning from the current loop.
        await ts.get("k", store_name="loops")
        stale = [
            k for k, (pool_loop, _) in _conn_pools.items()
            if pool_loop.is_closed()
        ]
        assert not stale, stale
    finally:
        await ts.shutdown("loops")
