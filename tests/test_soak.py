"""Resource-leak soak: repeated put/get/delete churn must not grow fds,
/dev/shm segments, or the client connection pool."""

import os

import numpy as np

import torchstore_tpu as ts


def _fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def _shm_count() -> int:
    return sum(1 for n in os.listdir("/dev/shm") if n.startswith("ts_shm_"))


async def test_churn_leaves_no_residue():
    await ts.initialize(store_name="soak")
    try:
        x = np.random.rand(256, 256).astype(np.float32)
        # Warm: caches, connections, segments reach steady state. Segment
        # rotation (put -> retire -> release -> pool) is ~3 deep per key,
        # so give each of the two keys enough iterations to converge.
        for i in range(10):
            await ts.put(f"k{i % 2}", x, store_name="soak")
            await ts.get(f"k{i % 2}", store_name="soak")
        fds0, shm0 = _fd_count(), _shm_count()
        for i in range(50):
            key = f"k{i % 2}"
            await ts.put(key, x, store_name="soak")
            out = await ts.get(key, store_name="soak")
            assert out[0, 0] == x[0, 0]
            if i % 10 == 9:
                await ts.delete(key, store_name="soak")
        fds1, shm1 = _fd_count(), _shm_count()
        assert fds1 <= fds0 + 4, (fds0, fds1)
        assert shm1 <= shm0 + 2, (shm0, shm1)
        from torchstore_tpu.runtime.actors import _conn_pools

        assert len(_conn_pools) <= 4, len(_conn_pools)
    finally:
        await ts.shutdown("soak")


async def test_reclaim_churn_converges_under_wedge_cycles():
    """Stress the conditional-reclaim machinery: repeatedly wedge a
    replica (SIGSTOP) through overwrites and recover it. Invariants after
    every cycle: acknowledged values stay readable (never the overwritten
    one), and the reclaim queue fully drains — no key is ever lost to a
    reclaim racing a put, no stale bytes are served."""
    import asyncio
    import os
    import signal

    from torchstore_tpu import api
    from torchstore_tpu.config import StoreConfig
    from torchstore_tpu.strategy import LocalRankStrategy

    # Short reclaim backoff (inherited by the controller process) so the
    # drain converges within test time; production keeps (1, 5, 15, 60).
    os.environ["TORCHSTORE_TPU_RECLAIM_DELAYS"] = "0.5,1,2,4,8"
    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(replication=2),
        store_name="rsoak",
        config=StoreConfig(rpc_timeout=2.0),
    )
    stopped: list[int] = []
    try:
        client = ts.client("rsoak")
        vmap = await client.controller.get_volume_map.call_one()
        target = vmap["1"]["ref"]
        handle = api._stores["rsoak"]
        proc = None
        for idx, ref in enumerate(handle.volume_mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host, target.port, target.name,
            ):
                proc = handle.volume_mesh._processes[idx]
        assert proc is not None

        keys = [f"w{i}" for i in range(3)]
        version = 0.0
        for key in keys:
            version += 1.0
            await ts.put(key, np.full(64, version, np.float32), store_name="rsoak")
        for cycle in range(3):
            os.kill(proc.pid, signal.SIGSTOP)
            stopped.append(proc.pid)
            version += 1.0
            for key in keys:  # degraded overwrites -> detach + reclaim
                await ts.put(
                    key, np.full(64, version, np.float32), store_name="rsoak"
                )
            os.kill(proc.pid, signal.SIGCONT)
            stopped.clear()
            # Every read returns the acknowledged (latest) value.
            for key in keys:
                out = await ts.get(key, store_name="rsoak")
                assert out[0] == version, (cycle, key, out[0], version)
        # The reclaim machinery drains completely.
        deadline = asyncio.get_event_loop().time() + 30
        while True:
            stats = await client.controller.stats.call_one()
            if not stats.get("pending_reclaims"):
                break
            assert asyncio.get_event_loop().time() < deadline, stats
            await asyncio.sleep(0.5)
        # And a final overwrite + read cycle works at full redundancy.
        for key in keys:
            await ts.put(key, np.full(64, 99.0, np.float32), store_name="rsoak")
            out = await ts.get(key, store_name="rsoak")
            assert out[0] == 99.0
        located = await client.controller.locate_volumes.call_one(keys)
        for key in keys:
            assert len(located[key]) == 2, located  # redundancy restored
    finally:
        os.environ.pop("TORCHSTORE_TPU_RECLAIM_DELAYS", None)
        for pid in stopped:
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass
        await ts.shutdown("rsoak")


async def test_many_loops_prune_connection_pool():
    # Each asyncio.run creates a loop; pooled connections of dead loops must
    # be pruned, not accumulate (this test itself runs in a fresh loop after
    # many prior tests — pool stays bounded).
    import asyncio

    from torchstore_tpu.runtime.actors import _conn_pools

    await ts.initialize(store_name="loops")
    try:
        await ts.put("k", np.ones(4), store_name="loops")

        def one_shot():
            async def go():
                out = await ts.get("k", store_name="loops")
                assert out[0] == 1.0

            asyncio.run(go())

        import threading

        for _ in range(8):
            t = threading.Thread(target=one_shot)
            t.start()
            t.join()
        # Trigger pruning from the current loop.
        await ts.get("k", store_name="loops")
        stale = [
            k for k, (pool_loop, _) in _conn_pools.items()
            if pool_loop.is_closed()
        ]
        assert not stale, stale
    finally:
        await ts.shutdown("loops")
