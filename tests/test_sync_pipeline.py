"""Steady-state sync pipeline tests (ISSUE 5): small-key arena packing edge
cases, overlapped landing-copy pool, the iteration-stable transfer-plan
cache (hit metric + placement-epoch invalidation + loud failure on shape
change), the bulk packed frame, and arena segment lifecycle (refcounts,
lease release returning the arena to the pool)."""

import asyncio
import gc

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.transport import landing
from torchstore_tpu.transport.shared_memory import (
    ShmSegment,
    ShmServerCache,
)
from torchstore_tpu.transport.types import TensorMeta


# --------------------------------------------------------------------------
# layout + landing pool units (no fleet)
# --------------------------------------------------------------------------


class TestArenaLayout:
    def test_offsets_aligned_and_total(self):
        offsets, total = landing.compute_arena_layout([100, 64, 0, 1])
        assert offsets == [0, 128, 192, 192]  # 0-byte member holds no span
        assert total == 256
        assert all(off % landing.ARENA_ALIGN == 0 for off in offsets)

    def test_empty_and_single(self):
        assert landing.compute_arena_layout([]) == ([], 1)
        offsets, total = landing.compute_arena_layout([10])
        assert offsets == [0] and total == 64

    def test_manifest_matches_transport_layout(self):
        """The provisioning manifest and the transport must agree on the
        arena segment size, or a prewarmed pool never serves the first
        put's handshake."""
        from torchstore_tpu.provision.manifest import StateDictManifest

        sd = {str(i): np.zeros(1000, np.float32) for i in range(5)}
        manifest = StateDictManifest.from_state_dict(sd)
        sizes = manifest.segment_sizes(arena_max_bytes=256 << 10)
        _, total = landing.compute_arena_layout([4000] * 5)
        assert sizes == {total: 1}

    def test_manifest_respects_threshold(self):
        from torchstore_tpu.provision.manifest import StateDictManifest

        sd = {"small": np.zeros(10, np.float32), "big": np.zeros(100000, np.float32)}
        manifest = StateDictManifest.from_state_dict(sd)
        sizes = manifest.segment_sizes(arena_max_bytes=1024)
        # one lone small key: plain exact-size segment, no arena
        assert sizes == {40: 1, 400000: 1}


class TestLandingPool:
    def test_task_planning_groups_small_pairs(self):
        pairs = [
            (np.zeros(16, np.uint8), np.ones(16, np.uint8)) for _ in range(100)
        ]
        tasks = landing._plan_tasks(pairs, threads=4, copy=landing.copy_into)
        assert 1 <= len(tasks) <= 8  # grouped, not one future per pair
        assert sum(len(group) for _, group in tasks) == 100

    def test_task_planning_chunks_large_pairs(self, monkeypatch):
        monkeypatch.setattr(landing, "CHUNK_BYTES", 1 << 10)
        dst = np.zeros(5000, np.uint8)
        src = np.arange(5000, dtype=np.uint8)
        tasks = landing._plan_tasks([(dst, src)], threads=4, copy=landing.copy_into)
        assert len(tasks) == 5  # 5000 B / 1 KB chunks

    @pytest.mark.anyio
    async def test_land_async_correctness(self, monkeypatch):
        monkeypatch.setattr(landing, "CHUNK_BYTES", 1 << 12)
        big_src = np.random.randint(0, 255, size=50_000).astype(np.uint8)
        big_dst = np.zeros_like(big_src)
        smalls = [
            (np.zeros(100, np.float32), np.random.rand(100).astype(np.float32))
            for _ in range(32)
        ]
        await landing.land_async([(big_dst, big_src), *smalls], stage="get")
        np.testing.assert_array_equal(big_dst, big_src)
        for dst, src in smalls:
            np.testing.assert_array_equal(dst, src)

    @pytest.mark.anyio
    async def test_land_async_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="shape mismatch"):
            await landing.land_async(
                [(np.zeros(4), np.zeros(5))], stage="put"
            )

    def test_land_sync_correctness(self):
        pairs = [
            (np.zeros(64, np.int32), np.arange(64, dtype=np.int32))
            for _ in range(8)
        ]
        landing.land_sync(pairs, stage="inline")
        for dst, src in pairs:
            np.testing.assert_array_equal(dst, src)


# --------------------------------------------------------------------------
# arena segment lifecycle (server cache, no fleet)
# --------------------------------------------------------------------------


class TestArenaRefcounts:
    def _meta(self, n=4):
        return TensorMeta(shape=(n,), dtype="uint8")

    def test_shared_segment_survives_partial_replacement(self):
        cache = ShmServerCache()
        arena = ShmSegment.create(64)
        cache.put("k1", None, arena, self._meta())
        cache.put("k2", None, arena, self._meta())
        assert cache.seg_refs[arena.name] == 2
        solo = ShmSegment.create(64)
        cache.put("k1", None, solo, self._meta())
        # one member replaced: arena still backs k2, nothing pooled yet
        assert cache.seg_refs[arena.name] == 1
        assert cache.free_bytes == 0
        arena2 = ShmSegment.create(64)
        cache.put("k2", None, arena2, self._meta())
        # last member replaced: arena (unleased) returns to the free pool
        assert arena.name not in cache.seg_refs
        assert cache.free_bytes == 64
        cache.clear()

    def test_leased_arena_retires_then_pools_on_release(self):
        cache = ShmServerCache()
        arena = ShmSegment.create(64)
        cache.put("k1", None, arena, self._meta())
        cache.put("k2", None, arena, self._meta())
        cache.grant(arena.name)  # a zero-copy reader holds a lease
        repl = ShmSegment.create(64)
        cache.put("k1", None, repl, self._meta())
        cache.put("k2", None, ShmSegment.create(64), self._meta())
        assert arena.name in cache.retired  # leased: retired, not pooled
        cache.apply_releases(
            {"client": "c1", "batches": [(1, {arena.name: 1})]}
        )
        assert arena.name not in cache.retired
        assert cache.free_bytes == 64  # lease released -> back to the pool
        cache.clear()

    def test_delete_key_respects_shared_refs(self):
        cache = ShmServerCache()
        arena = ShmSegment.create(64)
        cache.put("k1", None, arena, self._meta())
        cache.put("k2", None, arena, self._meta())
        cache.delete_key("k1")
        assert cache.seg_refs[arena.name] == 1  # k2 still backed
        cache.delete_key("k2")
        assert arena.name not in cache.seg_refs  # last ref: unlinked
        cache.clear()


# --------------------------------------------------------------------------
# arena round trips through a real fleet
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_arena_roundtrip_edge_cases():
    """One fleet, every packing edge case: mixed dtypes, 0-byte tensors,
    keys below/at/above the threshold boundary, subset zero-copy pulls,
    and the arena returning to the pool after lease release."""
    limit = 256 << 10  # default TORCHSTORE_TPU_ARENA_MAX_BYTES
    await ts.initialize(
        store_name="arena",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        packed_before = landing.ARENA_KEYS.total()
        sd = {
            "f32": np.random.rand(24 * 1024).astype(np.float32),  # 96 KB
            "i8": np.random.randint(-100, 100, 70000).astype(np.int8),
            "f64": np.random.rand(4096),  # 32 KB
            "zero": np.zeros((0, 3), np.float32),  # 0-byte member
            "at_boundary": np.random.rand(limit // 8),  # == limit bytes
            "above": np.random.rand((limit // 8) + 1),  # limit+8: NOT packed
        }
        await ts.put_state_dict("e/sd", sd, store_name="arena")
        packed_delta = landing.ARENA_KEYS.total() - packed_before
        # f32, i8, f64, zero, at_boundary pack; 'above' gets its own segment
        assert packed_delta == 5, packed_delta
        out = await ts.get_state_dict("e/sd", store_name="arena")
        for key, arr in sd.items():
            np.testing.assert_array_equal(out[key], arr), key
            assert out[key].dtype == arr.dtype
        # Subset pull: single-key gets serve the arena without re-staging.
        # Cold/RPC path: a read-only zero-copy subview. Warm one-sided path
        # (PR 7 — a plan was recorded by the get_state_dict above): an owned
        # stamped COPY — zero RPCs beats zero copies at this size, and a
        # copy is strictly safer to hand out.
        one = await ts.get("e/sd/f64", store_name="arena")
        np.testing.assert_array_equal(one, sd["f64"])
        if not one.flags.owndata:
            assert not one.flags.writeable  # snapshot view, not a copy
        # Overwrite loop: the previous iteration's arena rotates through
        # retirement (views held) back into the warm pool once released.
        del out, one
        gc.collect()
        for it in range(3):
            for arr in sd.values():
                if arr.size:
                    arr.flat[0] = it + 1
            await ts.put_state_dict("e/sd", sd, store_name="arena")
            out = await ts.get_state_dict("e/sd", store_name="arena")
            np.testing.assert_array_equal(out["f32"], sd["f32"])
            del out
            gc.collect()
        stats = await ts.client("arena").controller.stats.call_one(
            include_volumes=True
        )
        (vstats,) = stats["volumes"].values()
        # The rotation recycles arenas instead of leaking them: pooled or
        # retired-awaiting-release, and at most double-buffered live.
        shm = vstats["shm"]
        assert shm["arena_segments"] >= 1
        assert shm["pool_segments"] + shm["retired_segments"] >= 1
    finally:
        await ts.shutdown("arena")


@pytest.mark.anyio
async def test_single_small_key_and_empty_dict():
    """Degenerate batches: a lone small key (no arena to amortize) and an
    empty state dict (marker-only push) round-trip unchanged."""
    await ts.initialize(
        store_name="arena1",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        lone = {"only": np.random.rand(40 * 1024 // 8)}  # 40 KB, inline path
        await ts.put_state_dict("lone/sd", lone, store_name="arena1")
        out = await ts.get_state_dict("lone/sd", store_name="arena1")
        np.testing.assert_array_equal(out["only"], lone["only"])

        big_lone = {"only": np.random.rand(1 << 17)}  # 1 MB, handshake path
        await ts.put_state_dict("bl/sd", big_lone, store_name="arena1")
        out = await ts.get_state_dict("bl/sd", store_name="arena1")
        np.testing.assert_array_equal(out["only"], big_lone["only"])

        await ts.put_state_dict("empty/sd", {}, store_name="arena1")
        out = await ts.get_state_dict("empty/sd", store_name="arena1")
        assert out == {}
    finally:
        await ts.shutdown("arena1")


# --------------------------------------------------------------------------
# bulk packed frame
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_bulk_packed_frame_roundtrip():
    await ts.initialize(
        store_name="bulkpack",
        strategy=ts.SingletonStrategy(default_transport_type="bulk"),
    )
    try:
        packed_before = landing.ARENA_KEYS.value(transport="bulk")
        sd = {
            "params": {
                str(i): np.random.rand(2048).astype(np.float32)  # 8 KB each
                for i in range(40)
            },
            "big": np.random.rand(1 << 17),  # 1 MB: its own frame
        }
        await ts.put_state_dict("bp/sd", sd, store_name="bulkpack")
        assert landing.ARENA_KEYS.value(transport="bulk") - packed_before >= 40
        out = await ts.get_state_dict("bp/sd", store_name="bulkpack")
        for i in range(40):
            np.testing.assert_array_equal(
                out["params"][str(i)], sd["params"][str(i)]
            )
        np.testing.assert_array_equal(out["big"], sd["big"])
        # Overwrite via the packed path lands in place (invariant 6).
        sd["params"]["0"][0] = 42.0
        await ts.put_state_dict("bp/sd", sd, store_name="bulkpack")
        out = await ts.get_state_dict("bp/sd", store_name="bulkpack")
        assert out["params"]["0"][0] == 42.0
    finally:
        await ts.shutdown("bulkpack")


# --------------------------------------------------------------------------
# plan cache
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_plan_cache_hits_and_epoch_invalidation():
    """Acceptance: the second iteration of a repeated signature hits the
    plan cache (counter moves) and skips re-locate (controller locate
    counter still); a placement-epoch bump (delete) invalidates it."""
    from torchstore_tpu.client import _PLAN_HITS, _PLAN_INVALIDATIONS

    await ts.initialize(
        store_name="plans",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        sd = {str(i): np.random.rand(8192).astype(np.float32) for i in range(8)}
        user = {str(i): np.zeros(8192, np.float32) for i in range(8)}

        async def locates() -> int:
            stats = await ts.client("plans").controller.stats.call_one()
            return stats["locates"]

        hits0 = _PLAN_HITS.total()
        # Iteration 1: builds + stores plans.
        await ts.put_state_dict("p/sd", sd, store_name="plans")
        out = await ts.get_state_dict(
            "p/sd", user_state_dict=user, store_name="plans"
        )
        np.testing.assert_array_equal(out["0"], sd["0"])
        locates_warm = await locates()
        # Iteration 2: same signature — put AND get plans hit.
        sd["0"][0] = 7.0
        await ts.put_state_dict("p/sd", sd, store_name="plans")
        out = await ts.get_state_dict(
            "p/sd", user_state_dict=user, store_name="plans"
        )
        assert out["0"][0] == 7.0
        assert _PLAN_HITS.total() - hits0 >= 2
        assert _PLAN_HITS.value(op="put") >= 1
        assert _PLAN_HITS.value(op="get") >= 1
        # skipped re-locate: the cached-plan get issued no locate RPC
        assert await locates() == locates_warm

        # Epoch bump: a structural change (delete) invalidates every plan.
        inv0 = _PLAN_INVALIDATIONS.total()
        await ts.put("unrelated", np.ones(4), store_name="plans")
        await ts.delete("unrelated", store_name="plans")
        sd["0"][0] = 9.0
        await ts.put_state_dict("p/sd", sd, store_name="plans")
        out = await ts.get_state_dict(
            "p/sd", user_state_dict=user, store_name="plans"
        )
        assert out["0"][0] == 9.0
        assert _PLAN_INVALIDATIONS.total() > inv0
    finally:
        await ts.shutdown("plans")


@pytest.mark.anyio
async def test_plan_cache_shape_change_fails_loudly():
    """Re-publishing a key under a new shape must never land wrong bytes
    through a stale plan: the publisher's signature change bumps the
    placement epoch, and an old-shape in-place target fails loudly (the
    fast_copy no-broadcast rule) instead of filling with garbage."""
    await ts.initialize(
        store_name="shapes",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        sd = {"w": np.random.rand(4096).astype(np.float32)}
        user = {"w": np.zeros(4096, np.float32)}
        await ts.put_state_dict("s/sd", sd, store_name="shapes")
        await ts.get_state_dict("s/sd", user_state_dict=user, store_name="shapes")
        # warm the plans
        await ts.put_state_dict("s/sd", sd, store_name="shapes")
        await ts.get_state_dict("s/sd", user_state_dict=user, store_name="shapes")
        # republish under a DIFFERENT shape
        sd2 = {"w": np.random.rand(128).astype(np.float32)}
        await ts.put_state_dict("s/sd", sd2, store_name="shapes")
        with pytest.raises((ValueError, KeyError)):
            await ts.get_state_dict(
                "s/sd", user_state_dict=user, store_name="shapes"
            )
        # the right-shaped target works
        out = await ts.get_state_dict(
            "s/sd",
            user_state_dict={"w": np.zeros(128, np.float32)},
            store_name="shapes",
        )
        np.testing.assert_array_equal(out["w"], sd2["w"])
    finally:
        await ts.shutdown("shapes")


@pytest.mark.anyio
async def test_plan_cache_key_drop_republish_invalidates():
    """A republish that only DROPS keys deletes nothing, so the index alone
    cannot see the restructure — the publisher-side signature bump must
    invalidate consumer get plans even across a publisher restart (no
    memory of the previous push)."""
    await ts.initialize(
        store_name="drops",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        sd = {
            "head": np.random.rand(1024).astype(np.float32),
            "body": np.random.rand(1024).astype(np.float32),
        }
        await ts.put_state_dict("d/sd", sd, store_name="drops")
        out = await ts.get_state_dict("d/sd", store_name="drops")
        out2 = await ts.get_state_dict("d/sd", store_name="drops")  # plan hit
        assert set(out2) == {"head", "body"}
        del out, out2
        # Simulate a publisher restart: no memory of the previous signature.
        ts.client("drops").plan_cache.last_put_sig.clear()
        await ts.put_state_dict(
            "d/sd", {"body": sd["body"]}, store_name="drops"
        )
        out = await ts.get_state_dict("d/sd", store_name="drops")
        # The cached two-key plan must NOT serve: the new push has one key.
        assert set(out) == {"body"}
    finally:
        await ts.shutdown("drops")


@pytest.mark.anyio
async def test_plan_cache_disabled_by_config():
    await ts.initialize(
        store_name="noplan",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
        config=ts.StoreConfig(plan_cache=False),
    )
    try:
        assert ts.client("noplan").plan_cache is None
        sd = {"w": np.random.rand(1024).astype(np.float32)}
        for _ in range(2):
            await ts.put_state_dict("n/sd", sd, store_name="noplan")
            out = await ts.get_state_dict("n/sd", store_name="noplan")
            np.testing.assert_array_equal(out["w"], sd["w"])
    finally:
        await ts.shutdown("noplan")
