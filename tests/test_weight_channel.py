"""Blocking waits + versioned weight channel.

The reference's consumers poll get_state_dict in try/except loops
(reference example/torchstore_rl.py); this build replaces the poll with
controller-pushed wakeups (`ts.wait_for`, `wait_for_change`) and packages
the RL publish/consume pattern as WeightPublisher/WeightSubscriber with
bounded-memory version GC."""

import asyncio

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.client import Shard
from torchstore_tpu.transport.types import TensorSlice


@pytest.fixture
async def store():
    await ts.initialize(store_name="wc")
    yield "wc"
    await ts.shutdown("wc")


class TestWaitFor:
    async def test_returns_when_key_lands(self, store):
        async def delayed_put():
            await asyncio.sleep(0.15)
            await ts.put("late", np.ones(4), store_name=store)

        task = asyncio.create_task(delayed_put())
        await ts.wait_for("late", timeout=10.0, store_name=store)
        assert await ts.exists("late", store_name=store)
        await task

    async def test_already_committed_returns_immediately(self, store):
        await ts.put("now", np.ones(2), store_name=store)
        await asyncio.wait_for(
            ts.wait_for("now", timeout=5.0, store_name=store), timeout=1.0
        )

    async def test_timeout_names_missing_keys(self, store):
        with pytest.raises(TimeoutError, match="never-written"):
            await ts.wait_for("never-written", timeout=0.2, store_name=store)

    async def test_partial_commit_keeps_blocking(self, store):
        # One of two mesh coordinates landed: the key is partial and
        # wait_for must NOT wake for it.
        sl = TensorSlice(
            offsets=(0,),
            local_shape=(2,),
            global_shape=(4,),
            coordinates=(0,),
            mesh_shape=(2,),
        )
        await ts.put("part", Shard(np.ones(2, np.float32), sl), store_name=store)
        with pytest.raises(TimeoutError):
            await ts.wait_for("part", timeout=0.3, store_name=store)
        # Landing the second shard completes the commit and wakes the wait.
        sl2 = TensorSlice(
            offsets=(2,),
            local_shape=(2,),
            global_shape=(4,),
            coordinates=(1,),
            mesh_shape=(2,),
        )

        async def finish():
            await asyncio.sleep(0.1)
            await ts.put(
                "part", Shard(np.ones(2, np.float32), sl2), store_name=store
            )

        task = asyncio.create_task(finish())
        await ts.wait_for("part", timeout=10.0, store_name=store)
        await task

    async def test_multiple_keys(self, store):
        async def puts():
            await asyncio.sleep(0.05)
            await ts.put("k1", np.ones(1), store_name=store)
            await asyncio.sleep(0.05)
            await ts.put("k2", np.ones(1), store_name=store)

        task = asyncio.create_task(puts())
        await ts.wait_for(["k1", "k2"], timeout=10.0, store_name=store)
        await task


class TestWaitFaults:
    async def test_controller_death_fails_wait_loudly(self):
        """A client blocked in wait_for must surface the controller's death
        as an error, never hang (the supervision property VERDICT r1 item 4
        demanded of every RPC applies to long-blocking waits too)."""
        from torchstore_tpu.runtime import ActorDiedError
        from torchstore_tpu.runtime import actors as actors_mod

        await ts.initialize(store_name="wcdie")
        try:
            waiter = asyncio.create_task(
                ts.wait_for("never", timeout=None, store_name="wcdie")
            )
            await asyncio.sleep(0.3)
            assert not waiter.done()
            mesh = actors_mod._singletons["ts_wcdie_controller"]
            for proc in mesh._processes:
                proc.kill()
                proc.join(5)
            with pytest.raises((ActorDiedError, ConnectionError, OSError)) as exc:
                await asyncio.wait_for(waiter, timeout=10.0)
            # TimeoutError is an OSError subclass on 3.11+: a hung waiter
            # would satisfy the raises tuple via asyncio.wait_for's own
            # timeout — the exact regression this test exists to catch.
            assert not isinstance(exc.value, TimeoutError)
        finally:
            # ts.shutdown tolerates the dead controller and also reaps the
            # volume process + the published store-handle env var.
            await ts.shutdown("wcdie")


class TestWeightChannel:
    async def test_publish_acquire_sequence(self, store):
        pub = ts.WeightPublisher("policy", store_name=store)
        sub = ts.WeightSubscriber("policy", store_name=store)
        v0 = await pub.publish({"w": np.full(8, 0.0, np.float32)})
        assert v0 == 0
        sd, v = await sub.acquire(timeout=10.0)
        assert v == 0
        np.testing.assert_array_equal(sd["w"], np.zeros(8, np.float32))
        # Next acquire blocks until a NEWER version publishes.
        async def later():
            await asyncio.sleep(0.1)
            await pub.publish({"w": np.full(8, 1.0, np.float32)})

        task = asyncio.create_task(later())
        sd, v = await sub.acquire(timeout=10.0)
        assert v == 1
        np.testing.assert_array_equal(sd["w"], np.ones(8, np.float32))
        await task

    async def test_acquire_timeout_when_no_new_version(self, store):
        pub = ts.WeightPublisher("p2", store_name=store)
        sub = ts.WeightSubscriber("p2", store_name=store)
        await pub.publish({"w": np.ones(2)})
        await sub.acquire(timeout=5.0)
        with pytest.raises(TimeoutError):
            await sub.acquire(timeout=0.25)

    async def test_gc_keeps_last_n_versions(self, store):
        pub = ts.WeightPublisher("p3", store_name=store, keep=2)
        for i in range(4):
            await pub.publish({"w": np.full(4, float(i))})
        keys = await ts.keys("p3", store_name=store)
        assert not any(k.startswith("p3/v0/") for k in keys)
        assert not any(k.startswith("p3/v1/") for k in keys)
        assert any(k.startswith("p3/v2/") for k in keys)
        assert any(k.startswith("p3/v3/") for k in keys)

    async def test_publisher_resumes_numbering(self, store):
        pub = ts.WeightPublisher("p4", store_name=store)
        await pub.publish({"w": np.ones(2)})
        await pub.publish({"w": np.ones(2)})
        # A restarted publisher (fresh object) continues after LATEST.
        pub2 = ts.WeightPublisher("p4", store_name=store)
        v = await pub2.publish({"w": np.ones(2)})
        assert v == 2

    async def test_subscriber_skips_to_newest(self, store):
        pub = ts.WeightPublisher("p5", store_name=store)
        for i in range(3):
            await pub.publish({"w": np.full(2, float(i))})
        sub = ts.WeightSubscriber("p5", store_name=store)
        sd, v = await sub.acquire(timeout=5.0)
        assert v == 2  # latest, not v0
        np.testing.assert_array_equal(sd["w"], np.full(2, 2.0))

    async def test_inplace_acquire(self, store):
        pub = ts.WeightPublisher("p6", store_name=store)
        src = {"w": np.arange(16, dtype=np.float32).reshape(4, 4)}
        await pub.publish(src)
        sub = ts.WeightSubscriber("p6", store_name=store)
        user = {"w": np.zeros((4, 4), np.float32)}
        sd, v = await sub.acquire(user_state_dict=user, timeout=5.0)
        np.testing.assert_array_equal(user["w"], src["w"])

    async def test_concurrent_producer_consumer_loop(self, store):
        """The RL steady state: trainer publishes steps, generator acquires
        every version in order (keep large enough to never lag out)."""
        pub = ts.WeightPublisher("loop", store_name=store, keep=8)
        sub = ts.WeightSubscriber("loop", store_name=store)
        seen: list[int] = []

        async def producer():
            for i in range(5):
                await pub.publish({"w": np.full(4, float(i))})
                await asyncio.sleep(0.02)

        async def consumer():
            while len(seen) == 0 or seen[-1] < 4:
                sd, v = await sub.acquire(timeout=10.0)
                assert sd["w"][0] == float(v)
                seen.append(v)

        await asyncio.gather(producer(), consumer())
        assert seen[-1] == 4
        assert seen == sorted(seen)  # versions arrive in order

    async def test_gc_reclaims_orphans(self, store):
        # Versions orphaned by a crash-between-pointer-and-GC or a smaller
        # restart keep are swept by the NEXT publish, not leaked forever.
        pub = ts.WeightPublisher("p8", store_name=store, keep=8)
        for i in range(4):
            await pub.publish({"w": np.full(2, float(i))})  # v0..v3 all kept
        pub2 = ts.WeightPublisher("p8", store_name=store, keep=1)
        v = await pub2.publish({"w": np.ones(2)})  # v4; cutoff = 3
        assert v == 4
        keys = await ts.keys("p8", store_name=store)
        versions = {k.split("/")[1] for k in keys if k.split("/")[1].startswith("v")}
        assert versions == {"v4"}

    async def test_direct_channel_stable_key(self, store):
        # direct=True publishes under ONE stable key with refresh semantics:
        # no per-version staging registrations to leak, versions still
        # order the wakeups.
        pub = ts.WeightPublisher("pd", store_name=store)
        sub = ts.WeightSubscriber("pd", store_name=store)
        src = {"w": np.full(16, 1.0, np.float32)}
        assert await pub.publish(src, direct=True) == 0
        user = {"w": np.zeros(16, np.float32)}
        sd, v = await sub.acquire(user_state_dict=user, direct=True, timeout=5.0)
        assert v == 0
        np.testing.assert_array_equal(user["w"], np.full(16, 1.0))
        src["w"][:] = 2.0  # trainer mutates in place; publish = refresh
        assert await pub.publish(src, direct=True) == 1
        sd, v = await sub.acquire(user_state_dict=user, direct=True, timeout=5.0)
        assert v == 1
        np.testing.assert_array_equal(user["w"], np.full(16, 2.0))
        # Single stable data key, no version keys accumulating.
        keys = await ts.keys("pd", store_name=store)
        assert not any(k.split("/")[1].startswith("v") for k in keys)

    async def test_acquire_survives_concurrent_channel_delete(self, store):
        pub = ts.WeightPublisher("p9", store_name=store)
        sub = ts.WeightSubscriber("p9", store_name=store)
        await pub.publish({"w": np.ones(2)})
        await sub.acquire(timeout=5.0)

        async def delete_then_republish():
            await asyncio.sleep(0.05)
            await pub.close(delete=True)
            await asyncio.sleep(0.1)
            pub2 = ts.WeightPublisher("p9", store_name=store)
            await pub2.publish({"w": np.full(2, 7.0)})

        task = asyncio.create_task(delete_then_republish())
        # The delete bumps the pointer generation; acquire must ride through
        # the missing-pointer window and return the republished version.
        sd, v = await sub.acquire(timeout=10.0)
        np.testing.assert_array_equal(sd["w"], np.full(2, 7.0))
        await task

    async def test_close_deletes_channel(self, store):
        pub = ts.WeightPublisher("p7", store_name=store)
        await pub.publish({"w": np.ones(2)})
        await pub.close(delete=True)
        assert await ts.keys("p7", store_name=store) == []


class TestAtMostOnceDelivery:
    async def test_duplicate_wakeup_not_redelivered(self, store):
        """A wake whose publish was already returned (pointer read in a
        later RPC than the gen — a publish landing in between makes the
        next wake see the same version) must NOT deliver twice (ADVICE r2)."""
        pub = ts.WeightPublisher("dup", store_name=store)
        sub = ts.WeightSubscriber("dup", store_name=store)
        await pub.publish({"w": np.zeros(4, np.float32)})
        _, v0 = await sub.acquire(timeout=10.0)
        assert v0 == 0
        # Emulate the race: roll the subscriber's gen back one step, as if
        # it had woken for a publish whose successor it already returned.
        sub._last_gen -= 1
        with pytest.raises(TimeoutError):
            await sub.acquire(timeout=0.4)
        # A real new publish still arrives.
        await pub.publish({"w": np.ones(4, np.float32)})
        sd, v1 = await sub.acquire(timeout=10.0)
        assert v1 == 1 and sd["w"][0] == 1.0

    async def test_recreated_channel_redelivers_same_version_number(self, store):
        """Delete + recreate restarts numbering; the fresh epoch means the
        recreated channel's versions deliver even when the NUMBERS repeat."""
        pub = ts.WeightPublisher("rc", store_name=store)
        sub = ts.WeightSubscriber("rc", store_name=store)
        await pub.publish({"w": np.full(2, 1.0, np.float32)})
        await pub.publish({"w": np.full(2, 2.0, np.float32)})
        sd, v = await sub.acquire(timeout=10.0)
        assert v == 1 and sd["w"][0] == 2.0
        await pub.close(delete=True)
        pub2 = ts.WeightPublisher("rc", store_name=store)
        await pub2.publish({"w": np.full(2, 5.0, np.float32)})
        await pub2.publish({"w": np.full(2, 6.0, np.float32)})
        sd2, v2 = await sub.acquire(timeout=10.0)
        assert v2 == 1 and sd2["w"][0] == 6.0  # same number, new channel


class TestGenRestartResilience:
    async def test_stale_large_gen_wakes_immediately(self, store):
        """A subscriber holding a pre-restart gen LARGER than the
        controller's current gen must wake immediately and resync, not
        block through every later publish (ADVICE r2: _key_gens is
        in-memory and restarts from scratch)."""
        await ts.put("g", np.ones(2), store_name=store)
        controller = ts.client(store).controller
        change = await asyncio.wait_for(
            controller.wait_for_change.call_one("g", 10_000_000, timeout=5.0),
            timeout=2.0,
        )
        assert change["state"] == "committed"
        assert change["gen"] < 10_000_000
