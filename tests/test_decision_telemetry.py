"""Decision telemetry (ISSUE 10): traffic ledger + matrix, sync-timeline
SLOs, and the fault-triggered flight recorder — unit semantics plus the
fleet-level acceptance paths (lag gauge rising/settling under an injected
watermark delay, an SLO violation recorded, per-host egress matching bytes
actually moved, and an auto-dumped post-mortem on an injected volume
death)."""

import asyncio
import glob
import json
import os

import numpy as np
import pytest

from torchstore_tpu.observability import ledger as obs_ledger
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability import profile as obs_profile
from torchstore_tpu.observability import recorder as obs_recorder
from torchstore_tpu.observability import timeline as obs_timeline


# --------------------------------------------------------------------------
# unit: ledger cells, rolling windows, matrix folding
# --------------------------------------------------------------------------


class TestTrafficLedger:
    def test_cells_and_key_windows(self):
        led = obs_ledger.TrafficLedger(window_s=3600)
        led.record(
            "shm", obs_ledger.EGRESS, 100, peer_host="h2", volume="0",
            items=[("a", 60), ("b", 40)],
        )
        led.record("shm", obs_ledger.EGRESS, 50, peer_host="h2", volume="0")
        snap = led.snapshot()
        (cell,) = snap["cells"]
        assert cell["bytes"] == 150 and cell["ops"] == 2
        assert cell["peer_host"] == "h2" and cell["direction"] == "egress"
        keys = {k["key"]: k for k in snap["keys"]}
        assert keys["a"]["bytes"] == 60 and keys["b"]["ops"] == 1

    def test_weighted_sample_scales_to_expectation(self):
        led = obs_ledger.TrafficLedger(window_s=3600)
        # A 1-in-8 sampled batch recorded at weight 8 must read like the
        # 8 batches it stands for.
        led.record(
            "one_sided", obs_ledger.INGRESS, 8 * 100, volume="0",
            items=[("k", 100)], ops=8, weight=8,
        )
        (cell,) = led.snapshot()["cells"]
        assert cell["bytes"] == 800 and cell["ops"] == 8
        (key,) = led.snapshot()["keys"]
        assert key["ops"] == 8 and key["bytes"] == 800

    def test_window_rotation_decays_old_keys(self):
        import time as _time

        led = obs_ledger.TrafficLedger(window_s=0.05)
        led.record("shm", obs_ledger.EGRESS, 10, items=[("old", 10)])
        _time.sleep(0.06)
        led.record("shm", obs_ledger.EGRESS, 10, items=[("new", 10)])
        # "old" slid to the previous window (still visible)...
        assert {k["key"] for k in led.top_keys()} == {"old", "new"}
        _time.sleep(0.06)
        led.record("shm", obs_ledger.EGRESS, 10, items=[("newer", 10)])
        # ...and is gone after the second rotation.
        assert "old" not in {k["key"] for k in led.top_keys()}
        # Idle decay: READS rotate too — an idle process's snapshot must
        # not serve hour-old keys as "hot right now".
        _time.sleep(0.11)  # two full windows with zero records
        assert led.top_keys() == []
        assert led.snapshot()["cells"]  # totals are lifetime, not windowed

    def test_disabled_ledger_records_nothing(self):
        led = obs_ledger.TrafficLedger(window_s=3600)
        led.set_enabled(False)
        led.record("shm", obs_ledger.EGRESS, 10, items=[("k", 10)])
        assert led.snapshot()["cells"] == []

    def test_matrix_counts_each_transfer_once(self):
        # Client on hostA: put 100 to a volume on hostB, get 40 back, plus
        # a one-sided read of 60 (peer = own host). The volume's own
        # peer-less cells for the SAME transfers must not double anything.
        client_snap = {
            "host": "hostA",
            "cells": [
                {"peer_host": "hostB", "volume": "0", "transport": "shm",
                 "direction": "egress", "ops": 1, "bytes": 100},
                {"peer_host": "hostB", "volume": "0", "transport": "shm",
                 "direction": "ingress", "ops": 1, "bytes": 40},
                {"peer_host": "hostA", "volume": "1", "transport":
                 "one_sided", "direction": "ingress", "ops": 1, "bytes": 60},
            ],
            "keys": [],
        }
        volume_snap = {
            "host": "hostB",
            "cells": [
                {"peer_host": "", "volume": "0", "transport": "shm",
                 "direction": "ingress", "ops": 1, "bytes": 100},
                {"peer_host": "", "volume": "0", "transport": "shm",
                 "direction": "egress", "ops": 1, "bytes": 40},
            ],
            "keys": [],
        }
        m = obs_ledger.traffic_matrix(
            {"client": client_snap, "volume:0": volume_snap}
        )
        assert m["edges"]["hostA"]["hostB"]["bytes"] == 100
        assert m["edges"]["hostB"]["hostA"]["bytes"] == 40
        assert m["edges"]["hostA"]["hostA"]["bytes"] == 60
        assert m["egress"] == {"hostA": 160, "hostB": 40}
        assert m["ingress"] == {"hostB": 100, "hostA": 100}
        assert m["volumes"]["0"] == {"bytes_in": 100, "bytes_out": 40}
        assert m["volumes"]["1"] == {"bytes_in": 0, "bytes_out": 60}
        # Peer-less volume cells are visible but never double-counted.
        assert m["unattributed"]["hostB"] == {
            "bytes_in": 100, "bytes_out": 40
        }


# --------------------------------------------------------------------------
# unit: quantile digests, SLO checks, timeline reconstruction, recorder
# --------------------------------------------------------------------------


class TestTimelineUnits:
    def test_op_quantiles_publish_gauges(self):
        q = obs_timeline.OpQuantiles()
        for i in range(100):
            q.observe("unit_op", 0.001 * (i + 1))
        quant = q.quantiles("unit_op")
        assert quant["0.5"] <= quant["0.99"]
        assert (
            obs_metrics.get_registry()
            .gauge("ts_op_p99_seconds")
            .value(op="unit_op")
            > 0
        )

    def test_check_slo_counts_and_directions(self, monkeypatch):
        counter = obs_metrics.get_registry().counter(
            "ts_slo_violations_total"
        )
        monkeypatch.setenv("TORCHSTORE_TPU_SLO_GET_P99_MS", "10")
        base = counter.value(slo="get_p99_ms")
        assert obs_timeline.check_slo(obs_timeline.SLO_GET_P99_MS, 50.0)
        assert not obs_timeline.check_slo(obs_timeline.SLO_GET_P99_MS, 5.0)
        assert counter.value(slo="get_p99_ms") == base + 1
        monkeypatch.setenv("TORCHSTORE_TPU_SLO_OVERLAP_MIN", "0.5")
        assert obs_timeline.check_slo(
            obs_timeline.SLO_OVERLAP_MIN, 0.2, worse="below"
        )
        assert not obs_timeline.check_slo(
            obs_timeline.SLO_OVERLAP_MIN, 0.9, worse="below"
        )
        monkeypatch.delenv("TORCHSTORE_TPU_SLO_GET_P99_MS")
        assert not obs_timeline.check_slo(obs_timeline.SLO_GET_P99_MS, 1e9)

    def test_reconstruct_lifecycle(self):
        state = {
            "version": 3,
            "sealed": 3,
            "begin_ts": 100.0,
            "seal_ts": 100.5,
            "landing_ts": {"sd/b": 100.3, "sd/a": 100.1},
            "acks": {"host:1": {"version": 3, "ts": 100.7}},
            "watermarks": {"sd/a": 3, "sd/b": 3},
        }
        tl = obs_timeline.reconstruct(state)
        assert tl["publish_window_s"] == 0.5
        assert tl["first_layer_s"] == pytest.approx(0.1)
        assert [l["key"] for l in tl["landings"]] == ["sd/a", "sd/b"]
        assert tl["subscribers"]["host:1"]["completion_s"] == pytest.approx(
            0.7
        )
        assert obs_timeline.reconstruct(None) is None


class TestFlightRecorder:
    def test_ring_is_bounded_and_snapshot_ordered(self):
        rec = obs_recorder.FlightRecorder(maxlen=4)
        for i in range(10):
            rec.record("op", f"e{i}")
        events = rec.snapshot()
        assert [e["name"] for e in events] == ["e6", "e7", "e8", "e9"]

    def test_dump_writes_atomic_json(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_DIR", str(tmp_path))
        rec = obs_recorder.FlightRecorder(maxlen=64)
        rec.record("fault", "volume.put", action="die")
        path = rec.dump(
            "unit:test", extra_events=[{"ts": 0.0, "kind": "op", "name": "x"}]
        )
        assert path and os.path.exists(path)
        doc = json.loads(open(path).read())
        assert doc["trigger"] == "unit:test"
        # Merged + time-sorted: the extra (older) event sorts first.
        assert doc["events"][0]["name"] == "x"
        assert doc["events"][1]["name"] == "volume.put"
        # Empty ring -> no file, no crash.
        rec.clear()
        assert rec.dump("unit:empty") is None

    def test_disabled_recorder_is_silent(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHSTORE_TPU_FLIGHT_DIR", str(tmp_path))
        rec = obs_recorder.FlightRecorder(maxlen=8)
        rec.set_enabled(False)
        rec.record("fault", "x")
        assert rec.snapshot() == [] and rec.dump("unit:off") is None


# --------------------------------------------------------------------------
# fleet: matrix egress matches bytes moved; hot-key blind-spot regression
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_traffic_matrix_egress_matches_bytes_moved():
    """ISSUE-10 acceptance leg: after a known workload, the matrix's
    per-host egress equals the bytes actually moved (puts: client egress;
    gets: volume egress / one-sided same-host edges) within tolerance."""
    import torchstore_tpu as ts

    obs_ledger.reset_ledger()
    await ts.initialize(
        store_name="tm",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        n_keys, n_elem = 16, 1024  # 16 x 4 KB: exact (unsampled) accounting
        items = {
            f"tm/{i}": np.random.rand(n_elem).astype(np.float32)
            for i in range(n_keys)
        }
        per = n_elem * 4
        await ts.put_batch(items, store_name="tm")
        dests = {k: np.empty_like(v) for k, v in items.items()}
        await ts.get_batch(dict(dests), store_name="tm")  # RPC, records plans
        await ts.get_batch(dict(dests), store_name="tm")  # warm one-sided
        matrix = await ts.traffic_matrix(store_name="tm")
        host = obs_ledger.local_host()
        moved = n_keys * per * 3  # one put + two gets, all on this host
        assert matrix["egress"][host] == pytest.approx(moved, rel=0.02), (
            matrix["egress"],
            moved,
        )
        assert matrix["ingress"][host] == pytest.approx(moved, rel=0.02)
        vol = matrix["volumes"]["0"]
        assert vol["bytes_in"] == pytest.approx(n_keys * per, rel=0.02)
        assert vol["bytes_out"] == pytest.approx(2 * n_keys * per, rel=0.02)
        # The rolling key windows carry the workload's keys.
        client_keys = {k["key"] for k in matrix["keys"]["client"]}
        assert client_keys & set(items)
    finally:
        await ts.shutdown("tm")


@pytest.mark.anyio
async def test_one_sided_reads_feed_labeled_hot_keys():
    """PR-7 blind-spot regression: warm zero-RPC gets must show up in the
    labeled client-side profiler view (and the fleet snapshot's
    ``client:one_sided`` hot list) — no volume can ever count them."""
    import torchstore_tpu as ts

    obs_profile.reset_hot_keys()
    obs_ledger.reset_ledger()
    await ts.initialize(
        store_name="hk",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        arr = np.random.rand(2048).astype(np.float32)
        await ts.put("hk/warm", arr, store_name="hk")
        dest = np.empty_like(arr)
        await ts.get("hk/warm", like=dest, store_name="hk")  # records plan
        reads = obs_metrics.get_registry().counter(
            "ts_one_sided_reads_total"
        )
        before = reads.total()
        for _ in range(3):
            await ts.get("hk/warm", like=dest, store_name="hk")
        assert reads.total() - before >= 3  # genuinely one-sided
        one_sided = obs_profile.hot_keys(source="one_sided")
        assert any(h["key"] == "hk/warm" for h in one_sided), one_sided
        hot = {h["key"]: h for h in one_sided}
        assert hot["hk/warm"]["bytes"] >= 3 * arr.nbytes
        doc = await ts.fleet_snapshot(store_name="hk")
        assert any(
            h["key"] == "hk/warm"
            for h in doc["hot_keys"].get("client:one_sided", ())
        ), doc["hot_keys"].keys()
    finally:
        await ts.shutdown("hk")


# --------------------------------------------------------------------------
# fleet: lag gauge + SLO + generation timeline under a watermark delay
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_stream_lag_slo_and_generation_timeline(monkeypatch):
    """The two-fleet acceptance shape: a publisher streams layers while a
    LAGGING subscriber (slow on_layer) acquires under an injected
    ``channel.watermark`` delay — the lag gauge must rise then settle to
    0, an SLO violation must be recorded, and the controller's timestamped
    stream record must reconstruct into a full generation lifecycle with
    this subscriber's ack."""
    import torchstore_tpu as ts

    monkeypatch.setenv("TORCHSTORE_TPU_SLO_FIRST_LAYER_MS", "0.001")
    await ts.initialize(
        store_name="tl",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:
        await ts.inject_fault(
            "channel.watermark",
            "delay",
            count=2,
            delay_ms=50,
            scope="controller",
            store_name="tl",
        )
        n_layers = 6
        layers = {
            str(i): np.random.rand(512).astype(np.float32)
            for i in range(n_layers)
        }
        order = [f"layers/{i}" for i in range(n_layers)]
        lag_gauge = obs_metrics.get_registry().gauge("ts_stream_lag_keys")
        lag_samples: list[float] = []
        stop_sampling = asyncio.Event()

        async def sampler():
            # The lag gauge moves between wait_for_stream rounds; a
            # concurrent sampler sees it rise while in-order delivery
            # holds ready-but-unserved layers back.
            while not stop_sampling.is_set():
                lag_samples.append(lag_gauge.value())
                await asyncio.sleep(0.005)

        async def publisher():
            # REVERSED publish order: the subscriber's key_order delivery
            # holds every landed layer until layers/0 arrives LAST — the
            # watermarked-but-unserved lag climbs to n_layers - 1.
            stream = ts.state_dict_stream("tl/sd", store_name="tl")
            await stream.begin()
            for i in reversed(range(n_layers)):
                await stream.put({"layers": {str(i): layers[str(i)]}})
                await asyncio.sleep(0.03)
            await stream.seal()

        async def on_layer(fk, value):
            await asyncio.sleep(0.005)

        violations = obs_metrics.get_registry().counter(
            "ts_slo_violations_total"
        )
        base_violations = violations.value(slo="first_layer_ms")
        sampler_task = asyncio.ensure_future(sampler())
        try:
            _, sd = await asyncio.gather(
                publisher(),
                ts.get_state_dict_streamed(
                    "tl/sd",
                    key_order=order,
                    on_layer=on_layer,
                    wait_for_stream_s=30,
                    timeout=120,
                    store_name="tl",
                ),
            )
        finally:
            stop_sampling.set()
            await sampler_task
        assert set(sd["layers"]) == set(layers)
        # Lag ROSE while the publisher outran the slow subscriber...
        assert max(lag_samples) > 0, lag_samples
        # ...and SETTLED once the acquire completed.
        assert lag_gauge.value() == 0
        # The (trivially breachable) first-layer SLO fired and the live
        # production gauges moved.
        assert violations.value(slo="first_layer_ms") > base_violations
        assert (
            obs_metrics.get_registry()
            .gauge("ts_stream_first_layer_seconds")
            .value()
            > 0
        )
        overlap = (
            obs_metrics.get_registry()
            .gauge("ts_stream_overlap_ratio")
            .value()
        )
        assert 0 <= overlap <= 1
        # Generation timeline: begin -> landings -> seal -> our ack.
        tl = await ts.sync_timeline("tl/sd", store_name="tl")
        assert tl is not None and tl["version"] == 1 and tl["sealed"] == 1
        assert tl["publish_window_s"] is not None
        assert tl["publish_window_s"] >= 0
        assert len(tl["landings"]) == n_layers
        assert tl["first_layer_s"] is not None
        sub_id = obs_timeline.subscriber_id()
        assert sub_id in tl["subscribers"], tl["subscribers"]
        assert tl["subscribers"][sub_id]["version"] == 1
    finally:
        await ts.clear_faults(store_name="tl")
        await ts.shutdown("tl")


# --------------------------------------------------------------------------
# fleet: flight recorder post-mortems on injected death + quarantine
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_flight_recorder_dumps_on_injected_volume_death(tmp_path):
    """Injected volume death (die-action faultpoint) must leave the doomed
    process's post-mortem on disk; the supervisor's quarantine must then
    write the controller's MERGED post-mortem; and ts.flight_record()
    must still assemble, reporting the dead volume under errors."""
    import torchstore_tpu as ts
    from torchstore_tpu.runtime import ActorDiedError

    env = {
        "TORCHSTORE_TPU_FLIGHT_DIR": str(tmp_path),
        "TORCHSTORE_TPU_HEALTH_INTERVAL_S": "0.25",
        "TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD": "2",
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        await ts.initialize(store_name="fr", num_storage_volumes=2)
        try:
            await ts.put(
                "fr/k", np.ones(256, np.float32), store_name="fr"
            )
            await ts.inject_fault(
                "volume.put", "die", count=1, scope="volumes",
                store_name="fr",
            )
            with pytest.raises(Exception):
                await ts.put(
                    "fr/k2", np.ones(256, np.float32), store_name="fr"
                )
            # The dying process flushed its ring before os._exit.
            for _ in range(50):
                die_dumps = glob.glob(
                    os.path.join(str(tmp_path), "flight_fault_die_*.json")
                )
                if die_dumps:
                    break
                await asyncio.sleep(0.1)
            assert die_dumps, os.listdir(str(tmp_path))
            doc = json.loads(open(die_dumps[0]).read())
            assert doc["trigger"].startswith("fault_die")
            assert any(e["kind"] == "fault" for e in doc["events"])
            # Supervisor quarantine -> merged controller post-mortem.
            for _ in range(80):
                q_dumps = glob.glob(
                    os.path.join(str(tmp_path), "flight_quarantine_*.json")
                )
                if q_dumps:
                    break
                await asyncio.sleep(0.1)
            assert q_dumps, os.listdir(str(tmp_path))
            qdoc = json.loads(open(q_dumps[0]).read())
            assert any(
                e["kind"] == "health" and e["name"].startswith("quarantine")
                for e in qdoc["events"]
            )
            # On-demand merge still works; the dead volume reports as an
            # error instead of failing the assembly.
            record = await ts.flight_record(store_name="fr")
            assert record["events"]
            procs = {e.get("process") for e in record["events"]}
            assert "client" in procs and "controller" in procs
        finally:
            try:
                await ts.shutdown("fr")
            except (ActorDiedError, Exception):
                pass
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------------------------------------------------------
# satellite: fleet_snapshot under mid-scrape volume death DURING a stream
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_fleet_snapshot_mid_scrape_death_during_active_stream():
    """aggregate errors path x stream records: a volume dying between
    stream layers must land in the snapshot's ``errors`` while the
    controller's LIVE stream record (watermarks + timeline) stays
    readable and the surviving processes' metrics/ledgers still merge."""
    import torchstore_tpu as ts
    from torchstore_tpu.runtime import ActorDiedError

    await ts.initialize(store_name="sd2", num_storage_volumes=2)
    try:
        stream = ts.state_dict_stream("sd2/x", store_name="sd2")
        await stream.begin()
        await stream.put(
            {"layers": {"0": np.ones(256, np.float32)}}
        )
        # Kill a volume mid-stream (prefer one NOT holding the layer so
        # the stream itself could still finish; either way the scrape
        # must tolerate it).
        c = ts.client("sd2")
        located = await c.controller.locate_volumes.call_one(
            ["sd2/x/layers/0"]
        )
        holders = set(located["sd2/x/layers/0"])
        handle = ts.api._stores["sd2"]
        vmap = await c.controller.get_volume_map.call_one()
        victim_vid = next(
            (vid for vid in vmap if vid not in holders),
            next(iter(vmap)),
        )
        target = vmap[victim_vid]["ref"]
        for idx, ref in enumerate(handle.volume_mesh.refs):
            if (ref.host, ref.port, ref.name) == (
                target.host,
                target.port,
                target.name,
            ):
                proc = handle.volume_mesh._processes[idx]
                proc.terminate()
                proc.join(10.0)
                break
        doc = await ts.fleet_snapshot(store_name="sd2")
        assert len(doc["errors"]) == 1, doc["errors"]
        assert victim_vid in doc["errors"]
        # Survivors still merged: metrics, hot keys, AND ledgers.
        procs = {p.get("process") for p in doc["processes"]}
        assert {"client", "controller", "volume"} <= procs
        assert "client" in doc["ledgers"]
        surviving = [k for k in doc["ledgers"] if k.startswith("volume:")]
        assert len(surviving) == 1
        # The ACTIVE stream record survives the scrape: watermark + the
        # generation timeline fields are all present and consistent.
        state = await c.stream_state("sd2/x")
        assert state is not None and state["version"] == 1
        assert state["watermarks"].get("sd2/x/layers/0") == 1
        assert state["begin_ts"] is not None
        assert state["landing_ts"].get("sd2/x/layers/0") is not None
        assert state["seal_ts"] is None  # not sealed yet
        tl = await ts.sync_timeline("sd2/x", store_name="sd2")
        assert tl["first_layer_s"] is not None
        assert tl["seal_ts"] is None
        # The traffic matrix still folds from whatever ledgers arrived.
        matrix = await ts.traffic_matrix(store_name="sd2")
        assert matrix["egress"], matrix
    finally:
        try:
            await ts.shutdown("sd2")
        except (ActorDiedError, Exception):
            pass
