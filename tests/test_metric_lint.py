"""Fast-tier guard for the metric namespace: scripts/check_metric_names.py
must pass on the tree (no kind conflicts, snake_case only) and must actually
catch the failure modes it exists for."""

import importlib.util
import pathlib
import textwrap

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metric_names", REPO_ROOT / "scripts" / "check_metric_names.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_metric_namespace_is_clean():
    checker = _load_checker()
    problems = checker.check(str(REPO_ROOT))
    assert problems == [], "\n".join(problems)
    # Sanity: the scan actually sees the instrumented tree (a glob/layout
    # regression would otherwise make this test pass vacuously).
    sites = checker.collect_sites(str(REPO_ROOT))
    names = {name for _, _, name, _ in sites}
    assert len(sites) >= 30, sites
    assert "ts_client_ops_total" in names
    assert "ts_volume_resident_bytes" in names


def test_checker_catches_conflicts_and_bad_names(tmp_path):
    checker = _load_checker()
    pkg = tmp_path / "torchstore_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        textwrap.dedent(
            """
            from torchstore_tpu.observability import metrics as m
            _C = m.counter("ts_thing_total", "help")
            _BAD = m.gauge("Bad-Name", "not snake case")
            """
        )
    )
    (pkg / "b.py").write_text(
        # Same name, different kind, different file — exactly the two-process
        # fork the runtime guard cannot see.
        'from torchstore_tpu.observability import metrics as m\n'
        '_G = m.gauge("ts_thing_total")\n'
    )
    problems = checker.check(str(tmp_path))
    assert any("conflicting kinds" in p and "ts_thing_total" in p for p in problems)
    assert any("Bad-Name" in p and "snake_case" in p for p in problems)
