"""Native data-path library + device ops tests."""

import numpy as np
import pytest

from torchstore_tpu import native


class TestNative:
    def test_fast_copy_correctness_large(self):
        src = np.random.rand(4 * 1024 * 1024).astype(np.float32)  # 16 MB
        dst = np.empty_like(src)
        native.fast_copy(dst, src)
        np.testing.assert_array_equal(dst, src)

    def test_fast_copy_small_uses_numpy(self):
        src = np.arange(16.0)
        dst = np.zeros(16)
        native.fast_copy(dst, src)
        np.testing.assert_array_equal(dst, src)

    def test_fast_copy_dtype_mismatch_falls_back(self):
        src = np.arange(16, dtype=np.int64)
        dst = np.zeros(16, dtype=np.float64)
        native.fast_copy(dst, src)  # numpy handles the cast path
        np.testing.assert_array_equal(dst, src.astype(np.float64))

    def test_copy_2d_strided(self):
        if not native.available():
            pytest.skip("native library not built")
        base = np.random.rand(4096, 1024).astype(np.float32)
        src = base[:, :512]
        dstbase = np.zeros_like(base)
        dst = dstbase[:, :512]
        # Force through the 2d path regardless of size threshold.
        lib = native.get_lib()
        lib.ts_copy_2d(
            dst.__array_interface__["data"][0], dst.strides[0],
            src.__array_interface__["data"][0], src.strides[0],
            512 * 4, 4096, 0,
        )
        np.testing.assert_array_equal(dst, src)
        assert dstbase[:, 512:].sum() == 0  # untouched outside the block

    def test_fd_io_roundtrip(self):
        if not native.available():
            pytest.skip("native library not built")
        import socket

        lib = native.get_lib()
        a, b = socket.socketpair()
        src = np.random.rand(1024).astype(np.float32)
        dst = np.zeros_like(src)
        sent = lib.ts_write_fd(a.fileno(), src.__array_interface__["data"][0], src.nbytes)
        assert sent == src.nbytes
        got = lib.ts_read_fd(b.fileno(), dst.__array_interface__["data"][0], dst.nbytes)
        assert got == dst.nbytes
        np.testing.assert_array_equal(dst, src)
        a.close()
        b.close()


class TestOps:
    def test_device_cast(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        x = jnp.arange(64.0, dtype=jnp.float32)
        out = __import__("torchstore_tpu.ops", fromlist=["device_cast"]).device_cast(
            x, "bfloat16"
        )
        assert out.dtype == jnp.bfloat16

    def test_pallas_cast_tiled(self):
        pytest.importorskip("jax")
        import jax.numpy as jnp

        from torchstore_tpu.ops import pallas_cast

        x = jnp.arange(8 * 128 * 4, dtype=jnp.float32).reshape(32, 128)
        out = pallas_cast(x, jnp.bfloat16)
        assert out.dtype == jnp.bfloat16 and out.shape == x.shape
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(x), rtol=1e-2
        )

    def test_pallas_cast_unaligned_falls_back(self):
        pytest.importorskip("jax")
        import jax.numpy as jnp

        from torchstore_tpu.ops import pallas_cast

        x = jnp.arange(100.0, dtype=jnp.float32)  # not 1024-divisible
        out = pallas_cast(x, jnp.float16)
        assert out.dtype == jnp.float16 and out.shape == x.shape

    def test_ici_reshard(self):
        jax = pytest.importorskip("jax")
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchstore_tpu import parallel

        mesh1 = parallel.make_mesh({"x": 8})
        mesh2 = parallel.make_mesh({"a": 2, "b": 4})
        g = np.arange(64.0, dtype=np.float32).reshape(8, 8)
        x = jax.device_put(g, NamedSharding(mesh1, P("x", None)))
        y = parallel.reshard(x, NamedSharding(mesh2, P("b", "a")))
        np.testing.assert_array_equal(np.asarray(y), g)
        assert y.sharding.spec == P("b", "a")
