"""Zero-copy SHM read semantics, end to end: snapshot isolation of served
views, lease/release segment recycling, slice descriptor views, and the
adopted-segment rename protocol (VERDICT r1 items 1a/1c; replaces the old
opt-in mutable_shm behavior with safe-by-default zero-copy)."""

import gc
import os

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu.client import Shard
from torchstore_tpu.transport import shared_memory as shm
from torchstore_tpu.transport.shared_memory import ShmClientCache
from torchstore_tpu.transport.types import TensorSlice

pytestmark = pytest.mark.skipif(
    not shm.is_available(), reason="/dev/shm not available"
)


@pytest.fixture
async def store():
    await ts.initialize(
        store_name="zc", strategy=ts.SingletonStrategy(default_transport_type="shm")
    )
    yield "zc"
    await ts.shutdown("zc")


def _client_shm_cache(store_name: str) -> ShmClientCache:
    return ts.client(store_name)._ctx.get_cache(ShmClientCache)


async def test_get_returns_readonly_view(store):
    x = np.arange(64.0, dtype=np.float32)
    await ts.put("k", x, store_name=store)
    out = await ts.get("k", store_name=store)
    np.testing.assert_array_equal(out, x)
    assert not out.flags.writeable  # snapshot views are immutable
    with pytest.raises(ValueError):
        out[0] = 99.0


async def test_snapshot_isolation_across_puts(store):
    """A held view must keep showing the value it was fetched at, even after
    later puts of the same key (the volume retires, never overwrites, leased
    segments)."""
    a = np.full(1024, 1.0, dtype=np.float32)
    b = np.full(1024, 2.0, dtype=np.float32)
    await ts.put("k", a, store_name=store)
    snap_a = await ts.get("k", store_name=store)
    await ts.put("k", b, store_name=store)
    snap_b = await ts.get("k", store_name=store)
    await ts.put("k", a, store_name=store)  # and once more
    np.testing.assert_array_equal(snap_a, a)  # still the old snapshot
    np.testing.assert_array_equal(snap_b, b)


async def test_segment_recycling_after_release(store):
    """Dropping views lets the volume recycle segments: /dev/shm segment
    count stays bounded over many put/get iterations (no per-iteration
    allocation in steady state)."""

    def n_segments() -> int:
        return len([n for n in os.listdir(shm.SHM_DIR) if n.startswith("ts_shm_")])

    x = np.random.rand(1 << 16)
    out = None
    counts = []
    for it in range(8):
        x[0] = float(it)
        await ts.put("k", x, store_name=store)
        out = await ts.get("k", store_name=store)
        assert out[0] == float(it)
        gc.collect()  # make dropped-view weakrefs deterministic
        counts.append(n_segments())
    # Steady state is double-buffer rotation: the count must stop growing.
    assert counts[-1] <= counts[2], f"segment growth: {counts}"


async def test_slice_get_serves_descriptor_view(store):
    """A sub-slice fetch of a stored shard returns correct data without a
    destination (served as an offset/strides descriptor view)."""
    full = np.arange(8 * 8, dtype=np.float32).reshape(8, 8)
    await ts.put("w", full, store_name=store)
    want = TensorSlice(
        offsets=(2, 2),
        local_shape=(4, 4),
        global_shape=(8, 8),
        coordinates=(),
        mesh_shape=(),
    )
    out = await ts.get("w", like=want, store_name=store)
    np.testing.assert_array_equal(out, full[2:6, 2:6])


async def test_slice_get_lands_in_destination(store):
    full = np.arange(6 * 4, dtype=np.float32).reshape(6, 4)
    await ts.put("w", full, store_name=store)
    dest = np.zeros((3, 4), dtype=np.float32)
    want = TensorSlice(
        offsets=(1, 0),
        local_shape=(3, 4),
        global_shape=(6, 4),
        coordinates=(),
        mesh_shape=(),
    )
    out = await ts.get("w", like=Shard(data=dest, tensor_slice=want), store_name=store)
    np.testing.assert_array_equal(dest, full[1:4])
    assert out is dest
    assert dest.flags.writeable  # in-place destinations stay writable


async def test_client_cache_follows_renames(store):
    """After puts, every attachment in the client cache must reference a
    live segment name (the volume's adopt-rename is reported back via
    put_reply — no stale pre-rename entries may linger)."""
    cache = _client_shm_cache(store)
    for it in range(4):
        await ts.put("k", np.random.rand(2048), store_name=store)
        await ts.put("j", np.random.rand(1024), store_name=store)
    for name in cache.segments:
        assert os.path.exists(os.path.join(shm.SHM_DIR, name)), name
    # Bounded: repeated puts of the same keys must not accumulate entries.
    assert len(cache.segments) <= 8


async def test_sharded_put_zero_copy_reassembly(store):
    """Sharded puts + whole-tensor get without destination: parts are served
    as views and assembled; content must match exactly."""
    full = np.arange(16 * 4, dtype=np.float32).reshape(16, 4)
    for i in range(4):
        sl = TensorSlice(
            offsets=(i * 4, 0),
            local_shape=(4, 4),
            global_shape=(16, 4),
            coordinates=(i,),
            mesh_shape=(4,),
        )
        await ts.put("sh", Shard(data=full[i * 4 : (i + 1) * 4], tensor_slice=sl), store_name=store)
    out = await ts.get("sh", store_name=store)
    np.testing.assert_array_equal(out, full)
