"""Broadcast weight distribution (ISSUE 11): relay trees for O(1)
trainer-host egress.

Covers the whole stack: the pure topology solver (torchstore_tpu/relay.py),
the controller's watermark-driven fan-out (each published layer flows
volume-to-volume down the tree via ``pull_from(relay=True)`` as its
watermark lands), nearest-copy acquire routing (streamed reads gate on and
serve from the subscriber's host-local relay copy), elastic membership
(join/leave mid-run), peer-aware traffic-matrix attribution of relay hops,
and the deterministic chaos leg: a relay node killed MID-BROADCAST via the
``relay.forward`` faultpoint re-parents its subtree onto a healthy ancestor
and the leaf still acquires a consistent single-generation version — with
no ``ts.repair()`` call anywhere in this file.
"""

import asyncio
import time
from collections import Counter

import numpy as np
import pytest

import torchstore_tpu as ts
from torchstore_tpu import relay as relay_mod
from torchstore_tpu.strategy import LocalRankStrategy
from torchstore_tpu.weight_channel import WeightPublisher, WeightSubscriber


@pytest.fixture
def fast_health(monkeypatch):
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "0.25")
    monkeypatch.setenv("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "2")


# --------------------------------------------------------------------------
# unit: the topology solver
# --------------------------------------------------------------------------


def test_build_tree_root_out_degree_is_one():
    """Trainer-host egress is O(1): the root forwards to exactly one child
    however many members subscribe; interior nodes honor the fanout."""
    members = [str(i) for i in range(1, 9)]
    parents = relay_mod.build_tree("0", members, fanout=2)
    assert set(parents) == set(members)
    degree = Counter(parents.values())
    assert degree["0"] == 1
    for node, n in degree.items():
        if node != "0":
            assert n <= 2, (node, parents)
    for child in parents:
        assert relay_mod.depth_of(parents, "0", child) is not None


def test_build_tree_chain_and_determinism():
    parents = relay_mod.build_tree("0", ["3", "1", "2"], fanout=1)
    # fanout=1 is a chain in sorted-id order; the solver is deterministic
    # and excludes the root from the member set.
    assert parents == {"1": "0", "2": "1", "3": "2"}
    assert relay_mod.build_tree("0", ["0", "1", "2", "3"], fanout=1) == parents
    assert relay_mod.build_tree("0", [], fanout=2) == {}
    assert relay_mod.depth_of(parents, "0", "3") == 3


def test_reparent_attaches_orphans_to_healthy_ancestor():
    # 0 -> 1; 1 -> 2,3; 2 -> 4,5
    parents = relay_mod.build_tree("0", list("12345"), fanout=2)
    assert parents == {"1": "0", "2": "1", "3": "1", "4": "2", "5": "2"}
    new, moved = relay_mod.reparent(parents, "0", {"1"})
    assert "1" not in new
    assert new["2"] == "0" and new["3"] == "0"
    assert moved == {"2": ("1", "0"), "3": ("1", "0")}
    assert new["4"] == "2" and new["5"] == "2"  # intact subtree untouched
    # A whole dead chain walks all the way to the root.
    chain = relay_mod.build_tree("0", list("123"), fanout=1)
    new2, moved2 = relay_mod.reparent(chain, "0", {"1", "2"})
    assert new2 == {"3": "0"}
    assert moved2["3"] == ("2", "0")


# --------------------------------------------------------------------------
# integration: fan-out, local serve, topology view, traffic attribution
# --------------------------------------------------------------------------


def _layers(n: int, numel: int = 512, fill: float = 1.0) -> dict:
    return {f"w{i}": np.full(numel, fill, np.float32) for i in range(n)}


async def _wait_for_copy(client, key: str, vid: str, timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    while True:
        loc = await client.controller.locate_volumes.call_one(
            [key], missing_ok=True
        )
        infos = loc.get(key)
        if infos and vid in infos:
            return
        assert time.monotonic() < deadline, (
            f"relay never landed {key!r} on volume {vid!r}"
        )
        await asyncio.sleep(0.05)


@pytest.mark.anyio
async def test_relay_tree_distributes_and_serves_locally(monkeypatch):
    """One streamed publish fans out down the tree: every member volume
    lands a full local copy, subscribers acquire a consistent version
    routed through their OWN volume, ts.relay_topology() exposes the
    shape, and the traffic matrix shows O(1) origin egress with relay
    hops attributed as real src->dst host edges (never unattributed)."""
    monkeypatch.setenv("TORCHSTORE_TPU_RELAY_FANOUT", "2")
    await ts.initialize(
        num_storage_volumes=4,
        strategy=LocalRankStrategy(),
        store_name="relay_dist",
        volume_env_fn=lambda rank: {
            "TORCHSTORE_TPU_HOSTNAME": f"rhost{rank}"
        },
    )
    try:
        client = ts.client("relay_dist")
        layers = _layers(6)
        nbytes = sum(v.nbytes for v in layers.values())
        # Register the fleet BEFORE the publish so the very first layer
        # already fans out (a member joining mid-version receives from its
        # join point on; earlier layers stay point-to-point by design).
        for vid in ("1", "2", "3"):
            await client.relay_subscribe("pol", volume_id=vid)
        pub = WeightPublisher("pol", store_name="relay_dist")
        subs = [
            WeightSubscriber(
                "pol", store_name="relay_dist", relay=True,
                relay_volume=str(i),
            )
            for i in (1, 2, 3)
        ]

        async def publish() -> int:
            stream = pub.stream()
            for k, v in layers.items():
                await stream.put({k: v})
            return await stream.seal()

        async def origin_bytes_out() -> int:
            matrix = await ts.traffic_matrix("relay_dist")
            return int(
                matrix["volumes"].get("0", {}).get("bytes_out", 0)
            )

        # Delta accounting: the client PROCESS's ledger is shared across
        # the whole pytest session, and every SingletonStrategy store also
        # has a volume "0" — absolute totals would aggregate other tests'
        # traffic.
        out0 = await origin_bytes_out()
        results = await asyncio.gather(
            publish(), *(s.acquire_streamed(timeout=60) for s in subs)
        )
        version = results[0]
        for sd, v in results[1:]:
            assert v == version
            for k, arr in layers.items():
                got = np.asarray(sd[k])
                assert got.shape == arr.shape
                assert np.unique(got).tolist() == [1.0], k

        # Every member HOST holds exactly one full local copy.
        keys = [f"pol/v{version}/{k}" for k in layers]
        for key in keys:
            for vid in ("1", "2", "3"):
                await _wait_for_copy(client, key, vid)

        topo = await ts.relay_topology("relay_dist")
        assert set(topo["pol"]["members"]) == {"1", "2", "3"}
        run = topo["pol"]["runs"][f"pol/v{version}"]
        assert run["root"] == "0"
        assert run["sealed"] is True
        degree = Counter(run["parents"].values())
        assert degree["0"] == 1  # O(1) origin egress by construction
        # ...and by measurement: the origin volume served ~one copy (the
        # single tree hop + the commit marker), not one per fleet.
        matrix = await ts.traffic_matrix("relay_dist")
        origin_out = await origin_bytes_out() - out0
        assert origin_out >= nbytes, matrix["volumes"]
        assert origin_out < 2 * nbytes, (
            f"origin served {origin_out} bytes for a {nbytes}-byte dict "
            "across 3 fleets — relay hops are not being used"
        )
        # Relay hops are PEER-AWARE src->dst host edges (satellite 1): the
        # origin's single tree edge appears under its real host label.
        first_child = next(
            c for c, p in run["parents"].items() if p == "0"
        )
        edge = (
            matrix["edges"]
            .get("rhost0", {})
            .get(f"rhost{first_child}", {})
        )
        assert edge.get("bytes", 0) >= nbytes, matrix["edges"]
    finally:
        await ts.shutdown("relay_dist")


@pytest.mark.anyio
async def test_relay_elastic_membership(monkeypatch):
    """Generators join/leave mid-run: a member subscribed for v2 (but not
    v1) only receives v2; an unsubscribed member stops receiving."""
    monkeypatch.setenv("TORCHSTORE_TPU_RELAY_FANOUT", "2")
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(),
        store_name="relay_elastic",
    )
    try:
        client = ts.client("relay_elastic")
        assert (await client.relay_subscribe("pol", volume_id="1"))[
            "volume_id"
        ] == "1"
        pub = WeightPublisher("pol", store_name="relay_elastic")
        layers = _layers(3)

        async def publish() -> int:
            stream = pub.stream()
            for k, v in layers.items():
                await stream.put({k: v})
            return await stream.seal()

        v1 = await publish()
        await _wait_for_copy(client, f"pol/v{v1}/w0", "1")
        loc = await client.controller.locate_volumes.call_one(
            [f"pol/v{v1}/w0"]
        )
        assert "2" not in loc[f"pol/v{v1}/w0"]  # not yet a member

        # Join: volume 2 receives the NEXT version.
        await client.relay_subscribe("pol", volume_id="2")
        v2 = await publish()
        for vid in ("1", "2"):
            await _wait_for_copy(client, f"pol/v{v2}/w0", vid)

        # Leave: volume 1's member is gone, v3 flows to volume 2 only.
        await client.relay_unsubscribe("pol", "1")
        v3 = await publish()
        await _wait_for_copy(client, f"pol/v{v3}/w0", "2")
        loc = await client.controller.locate_volumes.call_one(
            [f"pol/v{v3}/w0"]
        )
        assert "1" not in loc[f"pol/v{v3}/w0"], loc
        topo = await ts.relay_topology("relay_elastic")
        assert set(topo["pol"]["members"]) == {"2"}
    finally:
        await ts.shutdown("relay_elastic")


@pytest.mark.anyio
async def test_relay_disabled_by_env(monkeypatch):
    """TORCHSTORE_TPU_RELAY_ENABLED=0 turns subscription into a no-op and
    acquires fall back to plain point-to-point streamed reads."""
    monkeypatch.setenv("TORCHSTORE_TPU_RELAY_ENABLED", "0")
    from torchstore_tpu.config import StoreConfig

    await ts.initialize(
        num_storage_volumes=2,
        strategy=LocalRankStrategy(),
        store_name="relay_off",
        config=StoreConfig(),
    )
    try:
        client = ts.client("relay_off")
        res = await client.relay_subscribe("pol", volume_id="1")
        assert res["volume_id"] is None and res.get("disabled")
        pub = WeightPublisher("pol", store_name="relay_off")
        sub = WeightSubscriber(
            "pol", store_name="relay_off", relay=True, relay_volume="1"
        )
        layers = _layers(2)

        async def publish() -> int:
            stream = pub.stream()
            for k, v in layers.items():
                await stream.put({k: v})
            return await stream.seal()

        version, (sd, got_version) = await asyncio.gather(
            publish(), sub.acquire_streamed(timeout=60)
        )
        assert got_version == version
        assert sub._relay_home is None  # subscription stood down
        loc = await client.controller.locate_volumes.call_one(
            [f"pol/v{version}/w0"]
        )
        assert "1" not in loc[f"pol/v{version}/w0"]  # no fan-out happened
    finally:
        await ts.shutdown("relay_off")


# --------------------------------------------------------------------------
# chaos: kill a relay node mid-broadcast (satellite 3)
# --------------------------------------------------------------------------


@pytest.mark.anyio
async def test_relay_node_death_reparents_and_completes(
    fast_health, monkeypatch
):
    """Deterministic chaos schedule: a chain 0 -> 1 -> 2 relays a streamed
    version; the interior relay node (volume 1) is killed MID-BROADCAST by
    the ``relay.forward`` faultpoint (action=die fires on its next
    forwarding pull). The health supervisor quarantines it, the controller
    re-parents the orphaned subtree (volume 2) onto the healthy ancestor
    (the origin), forwarding resumes from volume 2's last landed watermark
    (layers it already holds are never re-pulled), and the leaf subscriber
    still acquires a complete, consistent single-generation version — zero
    mixed-generation reads, and NO ts.repair() anywhere."""
    monkeypatch.setenv("TORCHSTORE_TPU_RELAY_FANOUT", "1")
    monkeypatch.setenv("TORCHSTORE_TPU_RELAY_REPARENT_TIMEOUT_S", "1.0")
    await ts.initialize(
        num_storage_volumes=3,
        strategy=LocalRankStrategy(),
        store_name="relay_chaos",
    )
    try:
        client = ts.client("relay_chaos")
        await client.relay_subscribe("pol", volume_id="1")
        await client.relay_subscribe("pol", volume_id="2")
        pub = WeightPublisher("pol", store_name="relay_chaos")
        sub = WeightSubscriber(
            "pol", store_name="relay_chaos", relay=True, relay_volume="2"
        )
        layers = _layers(8, fill=7.0)
        names = list(layers)
        leaf_landed_early = asyncio.Event()

        async def publish() -> int:
            stream = pub.stream()
            for k in names[:2]:
                await stream.put({k: layers[k]})
            # Wait for the chain to land the first layers on the LEAF so
            # the kill is provably mid-broadcast (the leaf holds a partial
            # version it must not re-pull after re-parenting).
            await _wait_for_copy(
                client, f"pol/v{stream.version}/{names[0]}", "2"
            )
            leaf_landed_early.set()
            # Kill the interior relay node on its NEXT forwarding hop.
            await ts.inject_fault(
                "relay.forward",
                "die",
                count=1,
                scope="1",
                store_name="relay_chaos",
            )
            for k in names[2:]:
                await stream.put({k: layers[k]})
            return await stream.seal()

        pub_task = asyncio.ensure_future(publish())
        sd, version = await sub.acquire_streamed(timeout=120)
        await pub_task
        assert leaf_landed_early.is_set()

        # Zero mixed-generation reads: one version's weights, complete.
        assert set(sd) == set(layers)
        for k in names:
            vals = np.unique(np.asarray(sd[k]))
            assert vals.tolist() == [7.0], f"{k} mixed generations: {vals}"

        # The orphaned subtree re-parented onto the healthy ancestor and
        # the dead node left the tree; the leaf landed the WHOLE version.
        topo = await ts.relay_topology("relay_chaos")
        run = topo["pol"]["runs"][f"pol/v{version}"]
        assert run["parents"].get("2") == "0", run
        assert "1" not in run["parents"], run
        assert run["landed"]["2"] >= len(names), run
        for k in names:
            loc = await client.controller.locate_volumes.call_one(
                [f"pol/v{version}/{k}"]
            )
            assert "2" in loc[f"pol/v{version}/{k}"]

        # The supervisor (not any repair call) dealt with the dead node...
        health = await ts.volume_health("relay_chaos")
        assert health["1"]["state"] == "quarantined"
        # ...and every re-parenting decision is on the flight recorder as
        # a kind=health event (satellite: operators can replay the tree's
        # history without reading controller state).
        record = await ts.flight_record("relay_chaos")
        reparents = [
            e
            for e in record["events"]
            if e.get("kind") == "health"
            and str(e.get("name", "")).startswith("relay_reparent/")
        ]
        assert reparents, "no relay_reparent decision recorded"
        detail = reparents[-1].get("detail") or {}
        assert detail.get("child") == "2"
        assert detail.get("new_parent") == "0"
    finally:
        await ts.clear_faults(store_name="relay_chaos")
        await ts.shutdown("relay_chaos")
