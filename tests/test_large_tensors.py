"""Large-tensor stress sweep (reference tests/test_large_tensors.py:28-125):
put/get across sizes per transport, with the slow upper sizes gated by
TORCHSTORE_TPU_ENABLE_SLOW_TESTS (reference's slow-test gate pattern)."""

import os

import numpy as np
import pytest

import torchstore_tpu as ts

SIZES_MB = [4, 64]
if os.environ.get("TORCHSTORE_TPU_ENABLE_SLOW_TESTS"):
    SIZES_MB += [512, 2048]


@pytest.fixture(params=["shm", "bulk", "rpc"])
async def store(request):
    await ts.initialize(
        store_name="big",
        strategy=ts.SingletonStrategy(default_transport_type=request.param),
    )
    yield "big"
    await ts.shutdown("big")


@pytest.mark.parametrize("size_mb", SIZES_MB)
async def test_large_roundtrip(store, size_mb):
    n = size_mb * 1024 * 1024 // 4
    x = np.random.rand(1024, n // 1024).astype(np.float32)
    await ts.put("big", x, store_name=store)
    out = await ts.get("big", store_name=store)
    np.testing.assert_array_equal(out, x)
    # In-place get into a preallocated destination too.
    dest = np.zeros_like(x)
    got = await ts.get("big", like=dest, store_name=store)
    assert got is dest
    np.testing.assert_array_equal(dest, x)
    await ts.delete("big", store_name=store)


async def test_large_sharded_reshard(store):
    jax = pytest.importorskip("jax")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    g = np.random.rand(2048, 2048).astype(np.float32)  # 16 MB
    devs = np.array(jax.devices())
    src = jax.device_put(g, NamedSharding(Mesh(devs.reshape(8), ("x",)), P("x")))
    await ts.put("s", src, store_name=store)
    like = jax.device_put(
        np.zeros_like(g),
        NamedSharding(Mesh(devs.reshape(4, 2), ("a", "b")), P("b", "a")),
    )
    out = await ts.get("s", like=like, store_name=store)
    np.testing.assert_array_equal(np.asarray(out), g)


@pytest.mark.skipif(
    not os.environ.get("TORCHSTORE_TPU_ENABLE_SLOW_TESTS"),
    reason="slow tier: runs the full device-bench child on the CPU backend",
)
def test_device_bench_child_runs_on_cpu():
    """The bench's device-section child (register -> per-pull stage ->
    transfer-engine pull -> verify) must stay runnable end to end: the
    TPU tunnel is only intermittently available, and the first live run
    must not be the first execution of this code path. ALLOW_CPU forces
    the child through the full flow on the CPU backend."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        TORCHSTORE_TPU_BENCH_DEVICE_ALLOW_CPU="1",
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--device-section"],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "device-path direct sync" in proc.stdout
