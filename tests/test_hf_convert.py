"""HF interop differential test: a randomly-initialized transformers Llama
and our flax model must produce matching logits after conversion — the
strongest single check of the model family's attention/RoPE/norm math
(reference analog: tests/test_models.py HF e2e)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from torchstore_tpu.models.hf_convert import config_from_hf, convert_hf_llama  # noqa: E402
from torchstore_tpu.models.llama import Llama  # noqa: E402


@pytest.fixture(scope="module")
def hf_model():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        max_position_embeddings=64,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    return model


def test_logits_parity(hf_model):
    cfg = config_from_hf(hf_model.config)
    # fp32 everywhere for a tight comparison.
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(hf_model.state_dict(), cfg)
    params = jax.tree.map(jnp.asarray, params)

    tokens = np.array([[1, 5, 9, 33, 2, 77, 10, 4]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = Llama(cfg).apply(params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=2e-4, rtol=2e-4)


def test_roundtrip_through_store(hf_model):
    import asyncio

    import torchstore_tpu as ts

    cfg = config_from_hf(hf_model.config)
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(hf_model.state_dict(), cfg)

    async def flow():
        await ts.initialize(store_name="hf")
        try:
            await ts.put_state_dict("hf/llama", params, store_name="hf")
            return await ts.get_state_dict("hf/llama", store_name="hf")
        finally:
            await ts.shutdown("hf")

    restored = asyncio.run(flow())
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], dtype=jnp.int32)
    a = Llama(cfg).apply(jax.tree.map(jnp.asarray, params), tokens)
    b = Llama(cfg).apply(jax.tree.map(jnp.asarray, restored), tokens)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_mixtral_logits_parity():
    hf_cfg = transformers.MixtralConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=64,
        rope_theta=10000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(hf_cfg)
    model.eval()
    cfg = config_from_hf(hf_cfg)
    assert cfg.num_experts == 4 and cfg.num_experts_per_tok == 2
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(model.state_dict(), cfg)
    params = jax.tree.map(jnp.asarray, params)
    tokens = np.array([[2, 7, 1, 8, 2, 8, 1, 8]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = Llama(cfg).apply(params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=5e-4, rtol=5e-4)


def test_tied_embeddings_fallback():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=4,
        tie_word_embeddings=True,
    )
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(hf_cfg)
    cfg = config_from_hf(hf_cfg)
    params = convert_hf_llama(model.state_dict(), cfg)
    np.testing.assert_array_equal(
        params["params"]["lm_head"]["kernel"],
        params["params"]["embed"]["embedding"].T,
    )


def test_qwen2_logits_parity():
    """Qwen2-style checkpoints (attention biases on q/k/v) convert with
    logits parity — widens the HF family beyond Llama/Mixtral."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        rope_theta=1e6,
        attn_implementation="eager",
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = transformers.Qwen2ForCausalLM(hf_cfg)
    model.eval()
    cfg = config_from_hf(model.config)
    assert cfg.attention_bias
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(model.state_dict(), cfg)
    params = jax.tree.map(jnp.asarray, params)
    tokens = np.array([[3, 14, 15, 92, 65, 35, 89, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = Llama(cfg).apply(params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=3e-4, rtol=3e-4)


def test_gemma_logits_parity():
    """Gemma-style checkpoints ((1+w) RMSNorm offsets, tanh-gelu MLP,
    sqrt(hidden)-scaled embeddings, tied lm_head) convert with logits
    parity — the fourth HF family."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        head_dim=8,
        max_position_embeddings=64,
        rms_norm_eps=1e-6,
        rope_theta=10000.0,
        attn_implementation="eager",
        hidden_act="gelu_pytorch_tanh",
    )
    torch.manual_seed(2)
    model = transformers.GemmaForCausalLM(hf_cfg)
    model.eval()
    cfg = config_from_hf(model.config)
    assert cfg.rms_offset and cfg.tie_embeddings and cfg.scale_embeddings
    assert cfg.mlp_act == "gelu_tanh"
    import dataclasses

    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    params = convert_hf_llama(model.state_dict(), cfg)
    params = jax.tree.map(jnp.asarray, params)
    assert "lm_head" not in params["params"]  # tied: attends through embed
    tokens = np.array([[3, 14, 15, 92, 65, 35, 89, 7]], dtype=np.int32)
    with torch.no_grad():
        hf_logits = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = Llama(cfg).apply(params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=3e-4, rtol=3e-4)


def test_gemma_train_step_runs():
    """The tiny_gemma config trains under the standard parallel train step
    (tied head + norm offsets differentiate cleanly)."""
    import optax

    from torchstore_tpu import parallel
    from torchstore_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny_gemma()
    model = Llama(cfg)
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    with mesh, parallel.activation_rules(mesh):
        tokens = jnp.zeros((2, 9), jnp.int32)
        boxed = model.init(jax.random.key(0), tokens[:, :-1])
        params = parallel.unbox(parallel.shard_params(boxed, mesh))
        optimizer = optax.adamw(1e-3)
        opt_state = optimizer.init(params)
        step = parallel.make_train_step(model, optimizer)
        params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
    assert float(loss) > 0.0
