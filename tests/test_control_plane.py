"""Unit tests for the control plane's pure core (ISSUE 16).

The solver is a pure function over a frozen :class:`TelemetrySnapshot` —
every policy behavior is pinned here over hand-built snapshots, no fleet,
no clock:

- skew -> co-locate: an overloaded volume's single-replica hot keys
  migrate onto the least-loaded volume on the dominant CONSUMER host;
- hot key -> split: one key dominating its volume's window gains a
  replica instead of moving;
- balanced / settling fleet -> empty plan (the hysteresis band);
- damping: cooldown suppresses same-subject re-decisions and a reversal
  of a remembered migration is dropped even past the cooldown window;
- demote / relay / reshard families and the max_actions budget.

Plus the other two pure pieces: the token-bucket admission math over an
injected clock, and ``build_snapshot``'s fold of raw telemetry dicts.
"""

from __future__ import annotations

import pytest

from torchstore_tpu.control.admission import AdmissionController, TokenBucket
from torchstore_tpu.control.snapshot import (
    KeyStat,
    RelayView,
    TelemetrySnapshot,
    VolumeLoad,
    build_snapshot,
)
from torchstore_tpu.control.solver import (
    DEMOTE,
    MIGRATE,
    RELAY_ORDER,
    RESHARD,
    SPLIT,
    Action,
    ActionRecord,
    ControlPolicy,
    solve,
)
from torchstore_tpu.observability import recorder as obs_recorder

NOW = 1000.0

# Small-number policy so fixtures stay readable: thresholds in KB, not MB.
POLICY = ControlPolicy(
    min_window_bytes=1000,
    hot_key_min_bytes=1000,
    min_edge_bytes=1000,
)


def _vol(vid, host, window, stored=0, tier_resident=0, tier_budget=0):
    return VolumeLoad(
        volume_id=vid,
        host=host,
        window_bytes=window,
        stored_bytes=stored,
        tier_resident_bytes=tier_resident,
        tier_budget_bytes=tier_budget,
    )


def _skewed_snapshot():
    """v0 (host A) runs 10000B against a 4000B fleet mean; its traffic
    flows dominantly to host B, where v1 sits nearly idle."""
    return TelemetrySnapshot(
        generated_ts=NOW,
        volumes={
            "v0": _vol("v0", "hostA", 10000),
            "v1": _vol("v1", "hostB", 1000),
            "v2": _vol("v2", "hostC", 1000),
        },
        edges={"hostA": {"hostB": 5000, "hostC": 100}},
        hot_keys=(
            KeyStat(key="k_hot", ops=50, bytes=6000, volumes=("v0",)),
            KeyStat(key="k_warm", ops=20, bytes=3000, volumes=("v0",)),
        ),
    )


class TestSolverMigration:
    def test_skew_migrates_to_dominant_consumer_host(self):
        actions = solve(_skewed_snapshot(), POLICY)
        assert [a.kind for a in actions] == [MIGRATE]
        (a,) = actions
        # Co-location: v1 (host B, the heaviest outgoing edge), not v2
        # (host C) which is equally idle but off the traffic path.
        assert (a.subject, a.src_volume, a.dst_volume) == (
            "k_hot",
            "v0",
            "v1",
        )
        # Moving k_hot (6000B) clears the settle excess (10000 - 1.5 *
        # 4000 = 4000B), so k_warm stays put.
        assert a.keys == ("k_hot",)

    def test_multi_replica_keys_stay_put(self):
        snap = _skewed_snapshot()
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes=snap.volumes,
            edges=snap.edges,
            hot_keys=(
                KeyStat(key="k_hot", bytes=6000, volumes=("v0", "v2")),
                KeyStat(key="k_warm", bytes=3000, volumes=("v0",)),
            ),
        )
        actions = [a for a in solve(snap, POLICY) if a.kind == MIGRATE]
        # k_hot already has a second serving replica: migration skips it
        # (a split would spread it); k_warm is the mover.
        assert [a.subject for a in actions] == ["k_warm"]

    def test_single_volume_fleet_never_migrates(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={"v0": _vol("v0", "hostA", 50000)},
            hot_keys=(KeyStat(key="k", bytes=40000, volumes=("v0",)),),
        )
        assert [a.kind for a in solve(snap, POLICY)] == []


class TestSolverHotKeySplit:
    def test_dominant_key_gains_replica(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                # mean 5250; 10000 < 2.0x mean, so migration stays quiet
                # and the split family owns this fixture.
                "v0": _vol("v0", "hostA", 10000),
                "v1": _vol("v1", "hostB", 500),
            },
            hot_keys=(KeyStat(key="k_hot", bytes=6000, volumes=("v0",)),),
        )
        actions = solve(snap, POLICY)
        assert [a.kind for a in actions] == [SPLIT]
        (a,) = actions
        assert (a.subject, a.src_volume, a.dst_volume) == (
            "k_hot",
            "v0",
            "v1",
        )

    def test_replica_cap_stops_splitting(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol("v0", "hostA", 10000),
                "v1": _vol("v1", "hostB", 500),
                "v2": _vol("v2", "hostC", 500),
                "v3": _vol("v3", "hostD", 500),
            },
            hot_keys=(
                KeyStat(
                    key="k_hot", bytes=9000, volumes=("v0", "v1", "v2")
                ),
            ),
        )
        assert solve(snap, POLICY) == []  # at max_replicas=3 already


class TestSolverHysteresis:
    def test_balanced_fleet_solves_to_empty_plan(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol("v0", "hostA", 5000),
                "v1": _vol("v1", "hostB", 5000),
            },
            hot_keys=(KeyStat(key="k", bytes=400, volumes=("v0",)),),
        )
        assert solve(snap, POLICY) == []

    def test_settling_band_is_left_alone(self):
        # 8500B vs a 5000B mean = 1.7x: past settle (1.5) but under the
        # enter threshold (2.0) — the fleet is settling, no new plan.
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol("v0", "hostA", 8500),
                "v1": _vol("v1", "hostB", 1500),
            },
            hot_keys=(KeyStat(key="k", bytes=900, volumes=("v0",)),),
        )
        assert solve(snap, POLICY) == []

    def test_cooldown_suppresses_recent_subject(self):
        history = [
            ActionRecord(
                ts=NOW - 5.0,
                kind=MIGRATE,
                subject="k_hot",
                src_volume="v0",
                dst_volume="v1",
            )
        ]
        actions = solve(_skewed_snapshot(), POLICY, history)
        # k_hot is inside cooldown_s=30: the solver falls through to the
        # next-hottest single-replica key.
        assert [a.subject for a in actions if a.kind == MIGRATE] == [
            "k_warm"
        ]

    def test_cooldown_expires(self):
        history = [
            ActionRecord(
                ts=NOW - 500.0,
                kind=MIGRATE,
                subject="k_hot",
                src_volume="v0",
                dst_volume="v1",
            )
        ]
        actions = solve(_skewed_snapshot(), POLICY, history)
        assert [a.subject for a in actions if a.kind == MIGRATE] == [
            "k_hot"
        ]

    def test_reversal_dropped_even_past_cooldown(self):
        # The remembered move went v1 -> v0 long ago; proposing v0 -> v1
        # for the same key would oscillate — dropped regardless of age.
        history = [
            ActionRecord(
                ts=NOW - 10_000.0,
                kind=MIGRATE,
                subject="k_hot",
                src_volume="v1",
                dst_volume="v0",
            )
        ]
        actions = solve(_skewed_snapshot(), POLICY, history)
        assert [a.subject for a in actions if a.kind == MIGRATE] == [
            "k_warm"
        ]


class TestSolverOtherFamilies:
    def test_tier_pressure_demotes_cold_keys(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol(
                    "v0", "hostA", 500, tier_resident=900, tier_budget=1000
                ),
                "v1": _vol("v1", "hostB", 500),
            },
            cold_keys={"v0": ("idle_a", "idle_b")},
        )
        actions = solve(snap, POLICY)
        assert [a.kind for a in actions] == [DEMOTE]
        assert actions[0].subject == "v0"
        assert actions[0].keys == ("idle_a", "idle_b")

    def test_relay_reorders_by_measured_proximity(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol("v0", "hostA", 100),
                "v1": _vol("v1", "hostB", 100),
                "v2": _vol("v2", "hostC", 100),
            },
            edges={"hostA": {"hostC": 5000}},
            relays=(
                RelayView(
                    channel="ch0", root="v0", members=("v0", "v1", "v2")
                ),
            ),
        )
        actions = solve(snap, POLICY)
        assert [a.kind for a in actions] == [RELAY_ORDER]
        # v2 (host C) carries the measured origin edge: it attaches
        # nearest the root, displacing the sorted-id default (v1, v2).
        assert actions[0].order == ("v2", "v1")

    def test_quiet_relay_keeps_default_order(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={
                "v0": _vol("v0", "hostA", 100),
                "v1": _vol("v1", "hostB", 100),
                "v2": _vol("v2", "hostC", 100),
            },
            edges={"hostA": {"hostC": 10}},  # under min_edge_bytes
            relays=(
                RelayView(
                    channel="ch0", root="v0", members=("v0", "v1", "v2")
                ),
            ),
        )
        assert solve(snap, POLICY) == []

    def test_meta_pressure_doubles_shards(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={"v0": _vol("v0", "hostA", 0)},
            meta_inflight={"coord": 40},
            n_shards=1,
        )
        actions = solve(snap, POLICY)
        assert [a.kind for a in actions] == [RESHARD]
        assert actions[0].shards == 2

    def test_reshard_capped_at_max_shards(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes={"v0": _vol("v0", "hostA", 0)},
            meta_inflight={"s0": 100, "s1": 100},
            n_shards=8,
        )
        assert solve(snap, POLICY) == []

    def test_max_actions_budget_keeps_highest_priority(self):
        snap = TelemetrySnapshot(
            generated_ts=NOW,
            volumes=_skewed_snapshot().volumes,
            edges=_skewed_snapshot().edges,
            hot_keys=_skewed_snapshot().hot_keys,
            meta_inflight={"coord": 100},
            n_shards=1,
        )
        policy = ControlPolicy(
            min_window_bytes=1000,
            hot_key_min_bytes=1000,
            min_edge_bytes=1000,
            max_actions=1,
        )
        actions = solve(snap, policy)
        # Both the migrate and the reshard qualify; the budget keeps the
        # higher-priority family.
        assert [a.kind for a in actions] == [MIGRATE]

    def test_action_describe_is_json_shaped(self):
        (a,) = solve(_skewed_snapshot(), POLICY)
        doc = a.describe()
        assert doc["kind"] == MIGRATE and doc["keys"] == ["k_hot"]
        assert isinstance(doc["reason"], str) and doc["reason"]


class TestTokenBucket:
    def test_burst_then_deficit_then_refill(self):
        bucket = TokenBucket(rate_hz=10.0, burst=5.0)
        assert bucket.reserve(0.0, 5.0) == 0.0  # burst covers it
        assert bucket.reserve(0.0, 1.0) == pytest.approx(0.1)  # 1 token short
        # One second later the refill (10 tokens, capped at burst) has
        # cleared the deficit.
        assert bucket.reserve(1.0, 1.0) == 0.0

    def test_deficits_queue_fairly(self):
        bucket = TokenBucket(rate_hz=1.0, burst=1.0)
        assert bucket.reserve(0.0, 1.0) == 0.0
        assert bucket.reserve(0.0, 1.0) == pytest.approx(1.0)
        # The next reserver waits behind the first deficit, not beside it.
        assert bucket.reserve(0.0, 1.0) == pytest.approx(2.0)

    def test_set_rate_rescales_waits(self):
        bucket = TokenBucket(rate_hz=10.0, burst=1.0)
        bucket.reserve(0.0, 1.0)
        bucket.set_rate(1.0)
        assert bucket.reserve(0.0, 1.0) == pytest.approx(1.0)


class TestAdmissionController:
    def test_unthrottled_fast_path(self):
        ctl = AdmissionController(rate_hz=100.0, tenant="t1")
        assert ctl.admit(1, now=0.0) == 0.0
        assert ctl.factor == 1.0 and not ctl.describe()["throttling"]

    def test_overload_scales_rate_down_and_back(self):
        obs_recorder.reset_recorder()
        ctl = AdmissionController(
            rate_hz=100.0, tenant="t1", overload_inflight=16
        )
        factor = ctl.refresh(
            {"volumes": {"v0": {"landing_inflight": 64}}}
        )
        assert factor == pytest.approx(16 / 64)
        assert ctl.bucket.rate_hz == pytest.approx(100.0 * 16 / 64)
        # Releasing the pressure restores the base rate.
        assert ctl.refresh({}) == 1.0
        assert ctl.bucket.rate_hz == pytest.approx(100.0)
        # Only the two TRANSITIONS hit the flight ring, as decision
        # events — not one event per admitted op.
        names = [
            e["name"]
            for e in obs_recorder.snapshot()
            if e["kind"] == "decision"
        ]
        assert names == ["admission_throttle", "admission_release"]

    def test_floor_factor(self):
        ctl = AdmissionController(
            rate_hz=10.0, tenant="t1", overload_inflight=4, min_factor=0.25
        )
        assert ctl.refresh(
            {"metadata_rpc_inflight": {"s0": 10_000}}
        ) == pytest.approx(0.25)

    def test_local_signal_feeds_refresh(self):
        ctl = AdmissionController(
            rate_hz=10.0, tenant="t1", overload_inflight=8
        )
        ctl.bind_local_signal(lambda: {"coord": 32})
        assert ctl.refresh() == pytest.approx(8 / 32)


class TestBuildSnapshot:
    def test_folds_ledger_windows_and_hot_keys(self):
        snap = build_snapshot(
            volume_stats={
                "v0": {
                    "entries": 3,
                    "stored_bytes": 4096,
                    "ledger": {"window": {"ops": 7, "bytes": 9000}},
                    "hot_keys": [{"key": "k", "ops": 5, "bytes": 6000}],
                    "tier": {"resident_bytes": 10, "budget_bytes": 100},
                },
            },
            traffic={
                "edges": {"hostA": {"hostB": {"bytes": 1234}}},
                # One-sided serves only the CLIENT ledgers saw: they fold
                # into the same per-key stat.
                "keys": {"client": [{"key": "k", "ops": 2, "bytes": 500}]},
            },
            placement={"v0": "hostA", "v1": "hostB"},
            key_placement={"k": ["v0"]},
            cold_keys={"v0": ["idle"]},
            n_shards=2,
            relays={"ch0": ("v0", ["v0", "v1"])},
            generated_ts=NOW,
        )
        v0 = snap.volumes["v0"]
        assert (v0.window_bytes, v0.window_ops) == (9000, 7)
        assert (v0.host, v0.tier_budget_bytes) == ("hostA", 100)
        # Placement-only volumes still appear (they are migration
        # targets even when idle).
        assert snap.volumes["v1"].window_bytes == 0
        (k,) = snap.hot_keys
        assert (k.key, k.ops, k.bytes, k.volumes) == ("k", 7, 6500, ("v0",))
        assert snap.edges == {"hostA": {"hostB": 1234}}
        assert snap.cold_keys == {"v0": ("idle",)}
        assert snap.n_shards == 2
        assert snap.relays[0].members == ("v0", "v1")

    def test_overload_view_max_merges(self):
        snap = build_snapshot(
            volume_stats={
                "v0": {"ledger": {"window": {"ops": 1, "bytes": 100}}}
            },
            overload={
                "volumes": {
                    "v0": {"window_bytes": 9999, "landing_inflight": 3},
                    "v9": {"window_bytes": 50},
                },
                "metadata_rpc_inflight": {"coord": 7},
            },
        )
        # The fleet-side fold refines the local view upward, never down.
        assert snap.volumes["v0"].window_bytes == 9999
        assert snap.volumes["v0"].landing_inflight == 3
        assert snap.volumes["v9"].window_bytes == 50
        assert snap.meta_inflight == {"coord": 7}

    def test_empty_inputs_build_empty_snapshot(self):
        snap = build_snapshot()
        assert snap.volumes == {} and snap.hot_keys == ()
        assert solve(snap) == []  # and the solver no-ops over it
